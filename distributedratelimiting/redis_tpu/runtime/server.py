"""The store server — the framework's "Redis process".

The reference's topology is a pure client-server star: clients never talk
to each other; all coordination is mediated by the shared store over TCP
(SURVEY.md §5.8). :class:`BucketStoreServer` is that shared store for
deployments whose clients are not co-located with the TPU host: it fronts
any :class:`~.store.BucketStore` (typically :class:`~.store.DeviceBucketStore`)
with an asyncio TCP listener speaking the :mod:`~.wire` protocol.

The crucial inversion of the reference's economics: every concurrent
request from *every* connection funnels into the store's micro-batcher, so
N clients × M in-flight requests coalesce into single kernel launches —
the server gets *more* efficient under load, where one Redis paid one Lua
execution per request.

Each request is served as its own task, so slow store operations from one
connection never head-of-line-block another, and responses return in
completion order (the seq id lets clients match them — same contract as a
multiplexed Redis connection).
"""

from __future__ import annotations

import asyncio
import hmac
import time

import numpy as np

from distributedratelimiting.redis_tpu.runtime import (
    admission,
    liveconfig,
    placement,
    wire,
)
from distributedratelimiting.redis_tpu.runtime.audit import (
    AuditConfig,
    ConservationAuditor,
)
from distributedratelimiting.redis_tpu.runtime.store import BucketStore
from distributedratelimiting.redis_tpu.utils import faults, log, tracing
from distributedratelimiting.redis_tpu.utils.flight_recorder import (
    FlightRecorder,
)
from distributedratelimiting.redis_tpu.utils.heavy_hitters import HeavyHitters
from distributedratelimiting.redis_tpu.utils.metrics import (
    LatencyHistogram,
    MetricsRegistry,
)

__all__ = ["BucketStoreServer"]


#: Scalar keyed admission ops fed to the heavy-hitter sketch. Module
#: constant on purpose: an inline `(wire.OP_..., ...)` tuple rebuilds
#: from four global lookups on every request (~0.35µs — measured as its
#: own line item in the plane's overhead audit).
_HOT_KEYED_OPS = frozenset(
    (wire.OP_ACQUIRE, wire.OP_WINDOW, wire.OP_FWINDOW, wire.OP_SEMA))

#: Keyed ops the placement gate checks once a map is adopted. Admission
#: ops on a parked (mid-handoff) key serve from the handoff's fair-share
#: envelope; everything else gated answers the routable moved error.
_PLACEMENT_GATED_OPS = frozenset(
    (wire.OP_ACQUIRE, wire.OP_WINDOW, wire.OP_FWINDOW, wire.OP_SEMA,
     wire.OP_PEEK, wire.OP_SYNC))
_ENVELOPE_KIND = {wire.OP_ACQUIRE: "bucket", wire.OP_WINDOW: "window",
                  wire.OP_FWINDOW: "fwindow"}
#: Keyed ops the live-config gate checks once a rule commits: a frame
#: carrying a retired ``(a, b)`` answers the routable "config moved"
#: error so the client re-sends with the new operands. THE table lives
#: in liveconfig (shared with the native batch lane and the client —
#: one table, zero drift).
_CONFIG_GATED_OPS = liveconfig.OP_KINDS
_BULK_ENVELOPE_KIND = {wire.BULK_KIND_BUCKET: "bucket",
                       wire.BULK_KIND_WINDOW: "window",
                       wire.BULK_KIND_FWINDOW: "fwindow"}


def _recover_seq(body: bytes) -> int:
    """Best-effort seq extraction from a frame body ([version][u32 seq]…)
    so even a malformed frame gets a *routable* error reply — a reply with
    the wrong seq would strand the client's future for its whole timeout."""
    return int.from_bytes(body[1:5], "little") if len(body) >= 5 else 0


class BucketStoreServer:
    """Serve a :class:`BucketStore` over TCP.

    Usage::

        server = BucketStoreServer(DeviceBucketStore(), host="0.0.0.0", port=6380)
        await server.start()
        ...
        await server.aclose()
    """

    def __init__(self, store: BucketStore, *, host: str = "127.0.0.1",
                 port: int = 0, snapshot_path: str | None = None,
                 auth_token: str | None = None,
                 native_frontend: bool = False,
                 native_max_batch: int = 4096,
                 native_deadline_us: int = 300,
                 native_tier0=False,
                 native_bulk: bool = True,
                 native_shards: int = 1,
                 native_pin_shards: bool = False,
                 native_uring: "str | bool | int | None" = None,
                 metrics_port: int | None = None,
                 observability: bool = True,
                 heavy_hitters_k: int = 64,
                 flight_dir: str | None = None,
                 flight_capacity: int = 512,
                 tracing_config: "bool | dict | None" = None,
                 audit: "bool | AuditConfig | None" = None,
                 snapshot_incremental: bool = False,
                 overflow_pool: "dict | None" = None) -> None:
        self.store = store
        self.host = host
        self.port = port
        # Native front-end (native/frontend.cc): the C++ epoll loop owns
        # the sockets and hands per-request micro-batches to Python once
        # per flush — the serving path's answer to the measured ~13K
        # req/s/core asyncio per-request ceiling (benchmarks/RESULTS.md
        # "Per-request socket ceiling isolated").
        self.native_frontend = native_frontend
        # The C batcher's own knobs (≙ the store micro-batcher's
        # max_batch/max_delay_s, OPERATIONS.md §3): flush size cap and
        # the timerfd deadline for the oldest pending request. Fail-fast
        # like MicroBatcher does — fe_start would silently coerce
        # nonpositive values to its defaults, running a config the
        # operator didn't ask for.
        if native_max_batch <= 0:
            raise ValueError("native_max_batch must be positive")
        if native_deadline_us <= 0:
            raise ValueError("native_deadline_us must be positive")
        self.native_max_batch = native_max_batch
        self.native_deadline_us = native_deadline_us
        # Tier-0 admission cache (native front-end only): False/None off,
        # True for defaults, or a native_frontend.Tier0Config instance.
        # Hot ACQUIRE keys with confident headroom then decide inside the
        # C epoll loop — no batcher, no Python, no device round trip —
        # reconciled by an async bulk debit (docs/OPERATIONS.md §3).
        self.native_tier0 = native_tier0
        # Native bulk lane (round 8, native front-end only): well-formed
        # OP_ACQUIRE_MANY frames parse, tier-0-decide per row, and
        # encode RESP_BULK in C — only cold-row residue reaches Python.
        # Default on; --no-fe-bulk restores the round-7 passthrough.
        self.native_bulk = native_bulk
        # Multi-shard native serving (round 11): N epoll shards accept
        # on SO_REUSEPORT listeners bound to one port — node-level
        # scaling for the C front-end (docs/OPERATIONS.md §12). Shard
        # count 1 keeps the single-listener posture bit for bit.
        if native_shards < 1:
            raise ValueError("native_shards must be >= 1")
        self.native_shards = native_shards
        self.native_pin_shards = native_pin_shards
        # io_uring data plane (round 16, native front-end only): swap
        # the shard IO loop's transport under the same reply bytes
        # (docs/DESIGN.md §21). None defers to DRL_TPU_URING (off when
        # unset); "on"/"sqpoll" opt in; per-shard fallback to epoll is
        # graceful and loud when the kernel/seccomp refuses.
        self.native_uring = native_uring
        self._native = None
        # Server-configured checkpoint destination for OP_SAVE (≙ Redis
        # BGSAVE writing its configured dump file — clients never supply
        # paths, so the wire cannot be used to write arbitrary files).
        self.snapshot_path = snapshot_path
        # Incremental checkpoints (docs/OPERATIONS.md §10): OP_SAVE then
        # writes a v4 delta against the previous save instead of a full
        # v3 file — the chain manager owns base retention, integrity
        # chaining, and compaction (runtime/checkpoint.py).
        self._snapshot_chain = None
        if snapshot_incremental and snapshot_path is not None:
            from distributedratelimiting.redis_tpu.runtime.checkpoint import (
                SnapshotChain,
            )

            self._snapshot_chain = SnapshotChain(snapshot_path)
            dirty = getattr(store, "enable_dirty_tracking", None)
            if callable(dirty):
                dirty()  # arm the store's dirty accounting (OP_STATS)
        # Shared-secret auth (≙ the AUTH the reference inherits from the
        # Redis Configuration string, …Options.cs:30-40): when set, a
        # connection's first frame must be a HELLO carrying this token.
        self.auth_token = auth_token
        self._server: asyncio.AbstractServer | None = None
        self._conn_tasks: set[asyncio.Task] = set()
        self._save_task: asyncio.Task | None = None
        self.connections_served = 0
        self.requests_served = 0
        # Requests dropped unexecuted because their client-stamped
        # deadline (wire DEADLINE_FLAG tail) expired while the frame sat
        # in this server's own queueing — answering them would serve the
        # dead while live requests wait behind them.
        self.requests_shed = 0
        # Goodput-under-overload plane (docs/DESIGN.md §24). The two
        # gates are DISARMED by default — the controller's storm rung
        # (or an operator) arms them; a healthy fleet's serving path is
        # byte-identical to the ungated one.
        #: Requests denied by the doomed-work gate: their propagated
        #: deadline cannot be met given current p99 serving latency —
        #: granting them would burn tokens on work the client will
        #: never collect.
        self.requests_doomed = 0
        #: Frames that arrived stamped attempt >= 1 (wire ATTEMPT_FLAG
        #: tail / bulk deadline tail) — the storm's raw size signal.
        self.retry_attempts_seen = 0
        #: Retry-stamped frames denied while retry-shed was armed.
        self.retries_shed = 0
        #: OP_RESERVE requests answered with a route-to-pool redirect.
        self.reserves_routed = 0
        #: Armed: deny attempt >= 1 frames before the store is touched.
        self.retry_shed_enabled = False
        #: Armed: deny deadline-stamped work that current p99 says
        #: cannot finish inside its budget.
        self.doomed_gate_enabled = False
        #: Overflow pool config for budget-aware routing: a dict
        #: ``{"pool", "ta", "tb", "priority"}`` naming the batch/
        #: overflow pool OP_RESERVE redirects doomed-at-admit
        #: interactive requests to (None disables routing).
        self.overflow_pool = dict(overflow_pool) if overflow_pool else None
        # Server-side serving latency: request decoded (arrival) →
        # result ready (before the reply hits the socket). This is the
        # latency the FRAMEWORK is accountable for — client-observed
        # latency adds the network RTT, which on a tunneled test link
        # swamps it (benchmarks/RESULTS.md p99 decomposition). Exposed
        # via OP_STATS as serving_p50_ms/serving_p99_ms.
        self.serving_latency = LatencyHistogram()
        # Reply stage (result ready → reply handed to the transport):
        # with the store's queue/flush histograms this completes the
        # per-stage decomposition — serving ≈ queue + flush + reply.
        self.reply_latency = LatencyHistogram()
        # The observability plane: heavy-hitter key telemetry, flight
        # recorder, and the OpenMetrics registry behind OP_METRICS and
        # the /metrics HTTP endpoint. Pull-only by design; disable
        # wholesale with observability=False (the ablation the
        # serving_metrics_overhead bench section compares against).
        self.observability = observability
        self.heavy_hitters = (HeavyHitters(heavy_hitters_k)
                              if observability and heavy_hitters_k > 0
                              else None)
        # Per-tenant tokens/sec (runtime/admission.py): fed by the
        # hierarchical lanes' GRANTED costs, exported via OP_STATS
        # "token_velocity" + drl_token_velocity{tenant=…} — the signal
        # an autoscaler (or the resharder) consumes.
        self.token_velocity = (admission.TokenVelocity()
                               if observability else None)
        self.flight_recorder = (FlightRecorder(flight_capacity,
                                               dump_dir=flight_dir)
                                if observability else None)
        self.metrics_port = metrics_port
        self._metrics_server: asyncio.AbstractServer | None = None
        self._registry: MetricsRegistry | None = None
        # Distributed tracing rides the PROCESS-global tracer (every
        # layer — client, batcher, store, native pump — references it at
        # call time): True enables with defaults, a dict passes knobs
        # through (sample_rate, latency_threshold_s, …), None leaves
        # whatever the process already configured.
        if tracing_config is not None:
            if isinstance(tracing_config, dict):
                tracing.configure(**tracing_config)
            else:
                tracing.configure(enabled=bool(tracing_config))
        self.tracer = tracing.get_tracer()
        # Elastic-membership half: the epoch-versioned placement map +
        # handoff state (docs/OPERATIONS.md §9). Dormant — zero serving
        # cost — until a coordinator announces a map (OP_PLACEMENT_*).
        self.placement = placement.NodePlacementState()
        # Live-config half (docs/OPERATIONS.md §10): committed forwarding
        # rules behind OP_CONFIG. Dormant until the first rule commits.
        self.liveconfig = liveconfig.ConfigState()
        # Estimate-reserve-settle ledger (runtime/reservations.py):
        # the STORE-attached ledger, shared with the migration import
        # lane (placement.import_entries routes "reservations" entry
        # sections into the same instance), wired with this server's
        # observability plane. Always on — reservations are admission
        # correctness, not telemetry; the OP_STATS section and metric
        # families render only once traffic arrives.
        if callable(getattr(store, "reservation_ledger", None)):
            self.reservations = store.reservation_ledger()
            # (Re)wire explicitly rather than via creation kwargs: a
            # store re-fronted by a new server (rolling restarts in
            # tests) must see THIS server's plane, not the old one's.
            self.reservations.flight_recorder = self.flight_recorder
            self.reservations.velocity = self.token_velocity
            self.reservations.liveconfig = self.liveconfig
        else:  # pragma: no cover — every BucketStore carries the hook
            self.reservations = None
        #: Region-side federation agent, when this process hosts one
        #: (an embedder or the controller wiring assigns it): its
        #: partition/degraded counters ride OP_STATS and the
        #: drl_federation_region_* families below.
        self.federation_agent = None
        # Drain-and-handoff shutdown (shutdown()): while a drain is in
        # flight, admission ops serve from this bounded fair-share
        # envelope instead of the (already exported) store.
        self._drain_envelope: "placement._FairShareEnvelope | None" = None
        #: Successor handle while a drain window is open: OP_SETTLE is
        #: RELAYED there (the ledger entries shipped with the export),
        #: so in-flight streams settle instead of erroring out.
        self._drain_successor = None
        self._drain_deadline = 0.0
        self._shutdown_done = False
        #: Autonomous control plane, when this process hosts one (the
        #: ``--controller`` CLI or an embedder assigns it): its audit
        #: surface rides OP_STATS, /flight (shared flight recorder),
        #: and the drl_controller_* families below.
        self.controller = None
        # Conservation audit plane (runtime/audit.py): the witness pair
        # below is the reply/witness identity's raw material — tokens
        # this server TOLD clients it granted vs tokens the store
        # actually debited, incremented adjacently at the scalar
        # decision site. Plain counters (the requests_served posture),
        # MONOTONIC, never reset.
        self.audit_replied_tokens = 0.0
        self.audit_witnessed_tokens = 0.0
        # audit=None follows the observability master switch; an
        # AuditConfig passes knobs through; False is the ablation the
        # audit_overhead bench section compares against.
        if audit is None:
            audit = observability
        self.auditor = (ConservationAuditor(
            self, audit if isinstance(audit, AuditConfig) else None)
            if audit else None)
        self._audit_task: "asyncio.Task | None" = None

    def set_retry_shed(self, enabled: bool) -> None:
        """Arm/disarm the server-side retry-shed gate (the controller's
        storm rung actuates this on every retry-shed target it holds —
        the same name :meth:`AdmissionPolicy.set_retry_shed` answers on
        the gateway side)."""
        self.retry_shed_enabled = bool(enabled)

    def set_doomed_gate(self, enabled: bool) -> None:
        """Arm/disarm the doomed-work gate: deadline-stamped requests
        whose budget cannot be met given current p99 serving latency
        are denied at admit instead of granted tokens they will burn
        uselessly (docs/DESIGN.md §24)."""
        self.doomed_gate_enabled = bool(enabled)

    async def start(self) -> tuple[str, int]:
        """Bind and listen; returns the bound ``(host, port)`` (port 0 in
        the constructor picks a free one — the tests' and examples'
        localhost-cluster trick, ≙ ``UseLocalhostClustering`` with per-
        instance port offsets, ``TestApp/Program.cs:43-52``)."""
        await self.store.connect()
        metrics = getattr(self.store, "metrics", None)
        if (self.flight_recorder is not None and metrics is not None
                and hasattr(metrics, "flight_recorder")):
            # The store's flush observer feeds the ring (one frame per
            # flush) and fires the degraded-entry auto-dump on a flush
            # error — see DeviceBucketStore._flush_observer.
            metrics.flight_recorder = self.flight_recorder
        if self.auditor is not None and self._audit_task is None:
            # The ε-ledger's pacer — spawned before either listener
            # path binds so both serve an already-ticking audit plane.
            self._audit_task = asyncio.create_task(self.auditor.run())
        if self.native_frontend:
            from distributedratelimiting.redis_tpu.runtime.native_frontend import (
                NativeFrontend,
            )

            try:
                self._native = NativeFrontend(
                    self, host=self.host, port=self.port,
                    max_batch=self.native_max_batch,
                    deadline_us=self.native_deadline_us,
                    tier0=self.native_tier0,
                    bulk=self.native_bulk,
                    shards=self.native_shards,
                    pin_shards=self.native_pin_shards,
                    uring=self.native_uring)
            except RuntimeError as exc:
                # Library unavailable (no compiler / DRL_TPU_NO_NATIVE):
                # serve anyway on the asyncio path — availability over
                # peak throughput, loudly (the operator asked for native
                # and is getting ~10× less per-request headroom).
                import logging

                logging.getLogger(__name__).warning(
                    "native front-end unavailable (%s); falling back to "
                    "the asyncio socket path%s", exc,
                    " — tier-0 admission cache DISABLED with it"
                    if self.native_tier0 else "")
                self.native_frontend = False
            else:
                self.port = self._native.port
                await self._start_metrics_http()
                return self.host, self.port
        elif self.native_tier0:
            import logging

            logging.getLogger(__name__).warning(
                "native_tier0 is set but native_frontend is off — the "
                "tier-0 admission cache only exists inside the native "
                "front-end and is NOT active")
        self._server = await asyncio.start_server(
            self._serve_connection, self.host, self.port
        )
        sock = self._server.sockets[0]
        self.host, self.port = sock.getsockname()[:2]
        await self._start_metrics_http()
        return self.host, self.port

    # -- /metrics HTTP exposition -------------------------------------------
    async def _start_metrics_http(self) -> None:
        """Bind the stdlib-asyncio ``/metrics`` listener when
        ``metrics_port`` is set (0 = ephemeral; the bound port lands back
        in ``self.metrics_port``). Independent of the wire listener, so
        it serves identically whether the sockets are owned by asyncio or
        by the native C front-end."""
        if self.metrics_port is None:
            return
        self._metrics_server = await asyncio.start_server(
            self._serve_metrics_http, self.host, self.metrics_port)
        self.metrics_port = (
            self._metrics_server.sockets[0].getsockname()[1])

    #: Content type served when the scraper did NOT Accept openmetrics:
    #: the Prometheus text 0.0.4 format (same sample lines, exemplar
    #: annotations suppressed — they are an OpenMetrics-only construct).
    PLAIN_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    async def _serve_metrics_http(self, reader: asyncio.StreamReader,
                                  writer: asyncio.StreamWriter) -> None:
        """Minimal one-shot HTTP/1.1 responder: GET /metrics → the
        exposition (content negotiated on ``Accept:`` — scrapers asking
        for ``application/openmetrics-text`` get the full OpenMetrics
        answer with exemplars; everyone else gets Prometheus text
        0.0.4); GET /flight → explicit flight-recorder dump (returns the
        path); GET /traces → Chrome-trace-event JSON of the kept traces
        (``?drain=1`` empties the buffer), loadable in Perfetto.
        Anything fancier belongs in a real scraper-side proxy — this
        exists so ``curl``/Prometheus can reach the plane with zero
        dependencies."""
        import json

        try:
            request_line = await asyncio.wait_for(reader.readline(), 10.0)
            parts = request_line.split()
            path = parts[1].decode("latin-1") if len(parts) >= 2 else "/"
            accept = ""
            while True:  # drain headers (Accept drives negotiation)
                line = await asyncio.wait_for(reader.readline(), 10.0)
                if line in (b"\r\n", b"\n", b""):
                    break
                if line[:7].lower() == b"accept:":
                    accept = line[7:].decode("latin-1", "replace").strip()
            route, _, query = path.partition("?")
            if route in ("/metrics", "/"):
                openmetrics = "application/openmetrics-text" in accept
                body = self.registry.render(
                    exemplars=openmetrics).encode("utf-8")
                status = "200 OK"
                ctype = (MetricsRegistry.CONTENT_TYPE if openmetrics
                         else self.PLAIN_CONTENT_TYPE)
            elif route == "/traces":
                from urllib.parse import parse_qs

                # Proper param parse: the drain is destructive, so a
                # substring match (?nodrain=1, ?drain=10) must not
                # trigger it.
                drain = parse_qs(query).get("drain", ["0"])[-1] == "1"
                body = self.tracer.export_chrome_json(
                    drain=drain).encode("utf-8")
                status, ctype = "200 OK", "application/json"
            elif route == "/flight" and self.flight_recorder is not None:
                # Rate-limited on purpose: the metrics listener carries
                # no auth (unlike the wire's OP_STATS trigger behind
                # HELLO), so an unthrottled dump here would let any peer
                # that can reach the port fill the disk. A suppressed
                # request answers {"dumped": null, "suppressed": true}.
                dump_path = self.flight_recorder.auto_dump("http_trigger")
                body = json.dumps({"dumped": dump_path,
                                   "suppressed": dump_path is None}
                                  ).encode()
                status, ctype = "200 OK", "application/json"
            elif route == "/audit":
                from urllib.parse import parse_qs

                # ?bundles=N ships the newest N incident bundles along
                # with the conservation snapshot (runtime/audit.py).
                try:
                    n = int(parse_qs(query).get("bundles", ["0"])[-1])
                except ValueError:
                    n = 0
                body = self._audit_json({"bundles": n}).encode("utf-8")
                status, ctype = "200 OK", "application/json"
            else:
                body, status, ctype = b"not found\n", "404 Not Found", \
                    "text/plain"
            writer.write(
                (f"HTTP/1.1 {status}\r\nContent-Type: {ctype}\r\n"
                 f"Content-Length: {len(body)}\r\n"
                 f"Connection: close\r\n\r\n").encode("latin-1") + body)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionResetError,
                BrokenPipeError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    @property
    def registry(self) -> MetricsRegistry:
        """The server's OpenMetrics registry (built lazily — families
        read live counters through callables, so construction order
        doesn't matter)."""
        if self._registry is None:
            self._registry = self._build_registry()
        return self._registry

    def _goodput_numeric_stats(self) -> "dict[str, float]":
        """drl_goodput_* family: deadline outcomes from the reservation
        ledger plus the server-side doomed/route gate work. Always
        renders (zeros before any deadline-stamped traffic) so the
        controller's goodput sensor has a stable scrape target."""
        led = self.reservations
        return {
            "settled_in_deadline": (led.settled_in_deadline
                                    if led is not None else 0),
            "settled_late": led.settled_late if led is not None else 0,
            "deadline_expired_grants": (led.deadline_expired_grants
                                        if led is not None else 0),
            "first_attempt_grants": (led.first_attempt_grants
                                     if led is not None else 0),
            "requests_doomed": self.requests_doomed,
            "reserves_routed": self.reserves_routed,
            "doomed_gate_enabled": 1.0 if self.doomed_gate_enabled
            else 0.0,
        }

    def _retry_numeric_stats(self) -> "dict[str, float]":
        """drl_retry_* family: attempt-tail observations and the
        retry-shed gate (scalar + reserve lanes)."""
        led = self.reservations
        return {
            "attempts_seen": self.retry_attempts_seen,
            "shed": self.retries_shed,
            "grants": led.retry_grants if led is not None else 0,
            "reserves": led.retry_reserves if led is not None else 0,
            "shed_enabled": 1.0 if self.retry_shed_enabled else 0.0,
        }

    def _build_registry(self) -> MetricsRegistry:
        reg = MetricsRegistry()
        reg.counter("connections_served", "Accepted TCP connections",
                    lambda: (self._native.counts()[1]
                             if self._native is not None
                             else self.connections_served))
        reg.counter("requests_served", "Requests answered on any lane",
                    lambda: (self._native.counts()[0]
                             if self._native is not None
                             else self.requests_served))
        reg.counter("batches_flushed",
                    "Native front-end micro-batches handed to Python",
                    lambda: (self._native.counts()[2]
                             if self._native is not None else 0))
        reg.counter("requests_shed",
                    "Requests dropped unexecuted: client deadline "
                    "expired in server queueing",
                    lambda: self.requests_shed)
        reg.gauge("native_fe_shards", "native front-end epoll shard "
                  "count (0 = asyncio path)",
                  lambda: (float(self._native.n_shards)
                           if self._native is not None else 0.0))
        reg.gauge("native_frontend", "1 when the C front-end owns the "
                  "sockets", lambda: 1.0 if self._native is not None
                  else 0.0)
        reg.gauge("native_fe_uring_shards", "native front-end shards "
                  "serving on the io_uring transport (0 = epoll or "
                  "asyncio path)",
                  lambda: (float(getattr(self._native, "uring_shards",
                                         0))
                           if self._native is not None else 0.0))
        reg.histogram("serving_latency_seconds",
                      "Request arrival to result ready",
                      lambda: (self._native.latency_histogram()
                               if self._native is not None
                               else self.serving_latency))
        # Per-stage decomposition: serving ≈ queue + flush + reply on the
        # asyncio path; the native path adds its own C-measured
        # queue/exec split. One family, one label.
        metrics = getattr(self.store, "metrics", None)
        reg.histogram("stage_latency_seconds",
                      "Per-stage share of the serving span",
                      lambda: getattr(metrics, "queue_latency", None),
                      labels={"stage": "queue"})
        reg.histogram("stage_latency_seconds",
                      "Per-stage share of the serving span",
                      lambda: getattr(metrics, "flush_latency", None),
                      labels={"stage": "flush"})
        reg.histogram("stage_latency_seconds",
                      "Per-stage share of the serving span",
                      lambda: self.reply_latency,
                      labels={"stage": "reply"})
        for stage in ("native_queue", "native_exec"):
            reg.histogram(
                "stage_latency_seconds",
                "Per-stage share of the serving span",
                lambda s=stage: ((self._native.stage_histograms() or {})
                                 .get(s) if self._native is not None
                                 else None),
                labels={"stage": stage})
        reg.register_numeric_dict(
            "store", "store metrics",
            lambda: metrics.snapshot() if metrics is not None else None,
            counters={"launches", "rows_processed", "rows_valid", "sweeps",
                      "slots_evicted", "pallas_sweep_failures",
                      "rows_coalesced", "pregrows", "fp_unresolved"})
        reg.register_numeric_dict(
            "tier0", "tier-0 admission cache",
            lambda: (self._native.tier0_stats()
                     if self._native is not None else None),
            counters={"hits", "local_denies", "misses", "installs",
                      "evictions", "syncs", "sync_failures",
                      "keys_synced"})
        reg.register_numeric_dict(
            "native_bulk", "native bulk admission lane",
            lambda: (self._native.bulk_stats()
                     if self._native is not None else None),
            counters={"frames", "frames_local", "rows", "rows_local",
                      "rows_residue", "permits_local",
                      "hot_ring_dropped"})
        if self.heavy_hitters is not None:
            hh = self.heavy_hitters
            reg.gauge("hot_keys_offered",
                      "Total admission weight offered to the top-K sketch",
                      lambda: hh.offered)
            reg.labeled_gauges(
                "hot_key_count",
                "Top-K admission weight per key (space-saving sketch; "
                "count may overshoot by at most hot_key_error)",
                lambda: [({"key": k}, c) for k, c, _ in hh.top()])
            reg.labeled_gauges(
                "hot_key_error",
                "Space-saving overcount bound per tracked key",
                lambda: [({"key": k}, e) for k, _, e in hh.top()])
        if self.token_velocity is not None:
            tv = self.token_velocity
            reg.counter("admitted_tokens",
                        "Tokens admitted through the hierarchical "
                        "(tenant-budgeted) lanes",
                        lambda: tv.observed_tokens)
            reg.labeled_gauges(
                "token_velocity",
                "Per-tenant admitted tokens/sec (exponentially decayed "
                "rate, tau=token_velocity tau_s) — the autoscaling / "
                "resharding signal",
                lambda: [({"tenant": t}, r)
                         for t, r in tv.rates().items()])
        if self.reservations is not None:
            led = self.reservations
            reg.labeled_gauges(
                "reservations_outstanding",
                "Outstanding reserved tokens per tenant (reserve "
                "issued, settle pending) — the unsettled-load signal "
                "the controller's shed ladder folds into its pressure "
                "sensor",
                lambda: [({"tenant": t}, v)
                         for t, v in led.outstanding_by_tenant()
                         .items()])
            reg.labeled_gauges(
                "reservation_debt",
                "Per-tenant unsettled under-estimate debt (tokens the "
                "budget must cover before the next reserve admits)",
                lambda: [({"tenant": t}, v)
                         for t, v in led.debts().items()])
            reg.register_numeric_dict(
                "reservation", "estimate-reserve-settle ledger",
                lambda: (led.numeric_stats() if led.active else None),
                counters={"reserves", "reserve_denied",
                          "reserve_duplicates", "ledger_full_denials",
                          "debt_denials", "settles",
                          "settle_duplicates", "settle_unknown",
                          "ttl_expired", "refunds", "refunded_tokens",
                          "debts_created", "debt_tokens_created",
                          "debt_tokens_collected", "rehomed",
                          "reserved_tokens_total",
                          "settled_tokens_total",
                          "extra_debited_tokens",
                          "exported_tokens_out", "restored_tokens_in",
                          "dropped_tokens", "forfeited_tokens"})
            # Settle-error magnitude histograms. Values record at
            # tokens × 1e-6 (the class buckets from 1e-6 up — see
            # reservations.py), so bucket bounds read as micro-tokens.
            reg.histogram("reservation_refund_tokens",
                          "Over-estimate refund magnitudes "
                          "(bucket unit: tokens x 1e-6)",
                          lambda: led.refund_hist)
            reg.histogram("reservation_debt_tokens",
                          "Under-estimate overage magnitudes "
                          "(bucket unit: tokens x 1e-6)",
                          lambda: led.debt_hist)
        # Goodput-under-overload plane (docs/DESIGN.md §24). Two
        # families: drl_goodput_* folds the reservation ledger's
        # deadline outcomes with the server's doomed/route gates into
        # the controller's goodput sensor; drl_retry_* carries the
        # retry-storm posture (attempt-tail observations plus the
        # retry-shed gate's work). Both render even with the gates
        # disarmed so operators can watch a storm build before arming.
        reg.register_numeric_dict(
            "goodput", "goodput sensor (deadline-outcome ledger + "
            "doomed-work and pool-routing gates)",
            lambda: self._goodput_numeric_stats(),
            counters={"settled_in_deadline", "settled_late",
                      "deadline_expired_grants", "first_attempt_grants",
                      "requests_doomed", "reserves_routed"})
        reg.register_numeric_dict(
            "retry", "retry-storm defense (attempt-tail admissions "
            "and the retry-shed gate)",
            lambda: self._retry_numeric_stats(),
            counters={"attempts_seen", "shed", "grants", "reserves"})
        # Global quota federation (runtime/federation.py). Read
        # dynamically: the home ledger materializes on the first
        # OP_FED_* frame and the region agent is attached by an
        # embedder — both may postdate the first scrape.
        reg.register_numeric_dict(
            "federation", "WAN federation ledger (home side)",
            lambda: (self.federation.numeric_stats()
                     if self.federation is not None
                     and self.federation.active else None),
            counters={"leases_granted", "lease_duplicates",
                      "lease_denied", "renews", "renew_unknown",
                      "resizes", "reclaims", "reclaim_duplicates",
                      "reclaim_unknown", "leases_expired", "heals",
                      "charged_tokens", "conservative_tokens",
                      "refunded_tokens", "debts_created",
                      "debt_tokens_created", "debt_tokens_collected",
                      "restores"})
        reg.labeled_gauges(
            "federation_slice_share",
            "Leased share of each global tenant budget per region "
            "(slice utilization — Σ over regions <= 1 per tenant)",
            lambda: ([({"tenant": t, "region": r}, s)
                      for t, r, s in self.federation.shares()]
                     if self.federation is not None else []))
        reg.register_numeric_dict(
            "federation_region",
            "WAN federation agent (region side): partition/degraded "
            "counters",
            lambda: (self.federation_agent.numeric_stats()
                     if self.federation_agent is not None else None),
            counters={"leases_acquired", "lease_failures", "renews",
                      "renew_failures", "partition_errors",
                      "degraded_entries", "heals", "slice_updates",
                      "stale_slice_replies", "reclaims",
                      "fed_fallbacks"})
        if self.flight_recorder is not None:
            reg.register_numeric_dict(
                "flight", "flight recorder",
                self.flight_recorder.snapshot,
                counters={"frames_recorded", "dumps_written",
                          "dumps_suppressed"})
        reg.register_numeric_dict(
            "placement", "placement/migration state",
            lambda: (self.placement.stats()
                     if self.placement.active else None),
            counters={"moved_errors", "envelope_decisions",
                      "handoff_deferrals", "pulls", "pushes_applied",
                      "pushes_duplicate", "rows_imported", "aborts",
                      "expired_aborts", "announces", "stale_announces"})
        reg.register_numeric_dict(
            "config", "live-config mutation state",
            lambda: (self.liveconfig.stats()
                     if (self.liveconfig.active
                         or self.liveconfig.version) else None),
            counters={"moved_errors", "commits", "aborts",
                      "stale_announces", "rebased_rows"})
        reg.register_numeric_dict(
            "snapshot_chain", "incremental checkpoint chain",
            lambda: (self._snapshot_chain.stats()
                     if self._snapshot_chain is not None else None),
            counters={"full_saves", "delta_saves"})
        reg.register_numeric_dict(
            "trace", "distributed tracer",
            lambda: (self.tracer.snapshot()
                     if self.tracer.enabled else None),
            counters={"spans_recorded", "traces_kept", "traces_dropped",
                      "traces_evicted"})
        # Autonomous control plane (read dynamically: the CLI attaches
        # the controller after start(), which may be after the first
        # scrape built this registry — a None controller just renders
        # nothing).
        reg.register_numeric_dict(
            "controller", "autonomous control plane",
            lambda: (self.controller.numeric_stats()
                     if self.controller is not None else None),
            counters={"ticks", "tick_failures", "actions_recorded",
                      "actuation_errors"})
        reg.labeled_counters(
            "controller_actions",
            "Controller decisions by action and outcome",
            lambda: (self.controller.action_series()
                     if self.controller is not None else []))
        # Conservation audit plane (runtime/audit.py): the drl_audit_*
        # prefix carries drl_audit_overadmitted_tokens — the SLI
        # numerator SLO_SERIES (utils/slo.py) pins to this site.
        reg.register_numeric_dict(
            "audit", "conservation audit plane (epsilon ledger)",
            lambda: (self.auditor.numeric_stats()
                     if self.auditor is not None else None),
            counters={"ticks", "tick_failures", "breaches",
                      "overadmitted_tokens", "bundles_assembled"})
        reg.register_numeric_dict(
            "slo", "multi-window burn-rate watchdog (utils/slo.py)",
            lambda: (self.auditor.watchdog.numeric_stats()
                     if self.auditor is not None else None),
            counters={"ticks", "alerts", "trips", "clears"})
        reg.labeled_gauges(
            "epsilon_budget_used_ratio",
            "Fraction of each documented epsilon allowance consumed "
            "(source=tier0|shard|envelope|federation; 1.0 = realized "
            "drift ate the whole budget — see DESIGN.md §22)",
            lambda: (self.auditor.epsilon_series()
                     if self.auditor is not None else []))
        reg.counter("stats_resets",
                    "Destructive serving-window resets, any trigger "
                    "(the shared-window tripwire, utils/metrics.py)",
                    lambda: self.serving_latency.resets)
        return reg

    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        self.connections_served += 1
        write_lock = asyncio.Lock()
        request_tasks: set[asyncio.Task] = set()
        # Bulk frames chain per connection: a chunked acquire_many arrives
        # as several ACQUIRE_MANY frames whose duplicate keys must decide
        # in chunk order (the documented request-order serialization,
        # store.py acquire_many) — independent tasks could race chunk 2
        # past chunk 1. Non-bulk ops stay fully concurrent.
        bulk_tail: asyncio.Task | None = None
        authed = self.auth_token is None
        conn_task = asyncio.current_task()
        if conn_task is not None:
            self._conn_tasks.add(conn_task)
            conn_task.add_done_callback(self._conn_tasks.discard)
        try:
            while True:
                body = await wire.read_frame(reader)
                if body is None:
                    break
                # Frame-read time is the arrival stamp: deadline shedding
                # and serving latency both measure from the moment the
                # bytes were in hand, so task-scheduling lag under load
                # counts against the budget it actually consumed.
                t_read = time.perf_counter()
                # Version + auth are connection-level gates, checked in
                # order here (not in per-request tasks, which complete out
                # of order): a bad frame gets one best-effort error reply,
                # then the connection drops.
                if body and body[0] != wire.PROTOCOL_VERSION:
                    await self._reply(writer, write_lock, wire.encode_response(
                        _recover_seq(body), wire.RESP_ERROR,
                        f"protocol version mismatch: peer speaks "
                        f"v{body[0]}, server speaks "
                        f"v{wire.PROTOCOL_VERSION}"))
                    break
                op = body[5] if len(body) >= 6 else 0
                if op == wire.OP_HELLO:
                    try:
                        seq, _, token, _, _, _ = wire.decode_request(body)
                    except Exception:  # malformed HELLO: routable error, drop
                        await self._reply(writer, write_lock,
                                          wire.encode_response(
                                              _recover_seq(body),
                                              wire.RESP_ERROR,
                                              "malformed HELLO frame"))
                        break
                    if self.auth_token is not None and not hmac.compare_digest(
                            token.encode(), self.auth_token.encode()):
                        await self._reply(writer, write_lock,
                                          wire.encode_response(
                                              seq, wire.RESP_ERROR,
                                              "authentication failed"))
                        break
                    authed = True
                    await self._reply(writer, write_lock,
                                      wire.encode_response(
                                          seq, wire.RESP_EMPTY))
                    continue
                if not authed:
                    await self._reply(writer, write_lock, wire.encode_response(
                        _recover_seq(body), wire.RESP_ERROR,
                        "authentication required: send HELLO first"))
                    break
                if len(body) >= 6 and body[5] == wire.OP_ACQUIRE_MANY:
                    # Only continuation chunks chain (duplicate keys
                    # spanning a chunk boundary keep request order);
                    # independent bulk frames — including every
                    # client-coalesced flush — pipeline freely.
                    after = (bulk_tail if wire.bulk_request_chained(body)
                             else None)
                    task = asyncio.ensure_future(self._serve_request(
                        body, writer, write_lock, after=after,
                        arrival_s=t_read))
                    bulk_tail = task
                else:
                    task = asyncio.ensure_future(
                        self._serve_request(body, writer, write_lock,
                                            arrival_s=t_read)
                    )
                request_tasks.add(task)
                task.add_done_callback(request_tasks.discard)
        except wire.RemoteStoreError as exc:
            log.error_evaluating_kernel(exc)  # protocol-broken peer: drop
        finally:
            for t in request_tasks:
                t.cancel()
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    #: Only await drain once the transport's buffer passes this —
    #: per-reply drains cost an extra await/req on the hot path while the
    #: buffer is nearly always empty; past the mark, drain applies real
    #: backpressure against a slow-reading client.
    _DRAIN_HIGH_WATER = 256 * 1024

    async def _reply(self, writer: asyncio.StreamWriter,
                     write_lock: asyncio.Lock, resp: bytes) -> None:
        # The lock keeps concurrent request tasks' frames from
        # interleaving; a vanished client just drops the reply (its
        # futures die with the socket).
        async with write_lock:
            try:
                wire.write_frame(writer, resp)
                if (writer.transport.get_write_buffer_size()
                        > self._DRAIN_HIGH_WATER):
                    await writer.drain()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _serve_request(self, body: bytes, writer: asyncio.StreamWriter,
                             write_lock: asyncio.Lock,
                             after: "asyncio.Task | None" = None,
                             arrival_s: "float | None" = None) -> None:
        t_arrival = time.perf_counter() if arrival_s is None else arrival_s
        if after is not None:
            # Per-connection bulk ordering (see _serve_connection). The
            # predecessor's own failure was already replied/logged there.
            await asyncio.gather(after, return_exceptions=True)
        if faults._INJECTOR is not None:  # chaos seam; no-op in prod
            try:
                await faults._INJECTOR.on_event("server.dispatch")
            except faults.BlackholeFault:
                return  # no reply: the client's timeout owns this one
            except Exception as exc:
                await self._reply(writer, write_lock, wire.encode_response(
                    _recover_seq(body), wire.RESP_ERROR, repr(exc)))
                return
        resp = await self.handle_frame_body(body, arrival_s=t_arrival)
        self.requests_served += 1
        t_ready = time.perf_counter()
        self.serving_latency.record(t_ready - t_arrival)
        await self._reply(writer, write_lock, resp)  # client went away ⇒
        # its futures die with the socket
        # Reply stage: result ready → frame handed to the transport
        # (includes any backpressure drain) — the fan-out share of the
        # decomposition.
        self.reply_latency.record(time.perf_counter() - t_ready)

    async def handle_frame_body(self, body: bytes,
                                arrival_s: "float | None" = None) -> bytes:
        """Serve one frame body and return the encoded reply — the shared
        dispatch behind both the asyncio socket path and the native
        front-end's passthrough lane (runtime/native_frontend.py). Store
        and decode failures come back as routable RESP_ERROR frames, never
        as raises (except cancellation), so one bad request can never take
        a connection down with it.

        Trace-stamped frames (scalar op flag / bulk flags bit 4, see
        wire.py) are stripped here and served inside a ``server.<op>``
        span parented on the client's wire context; the span's status is
        sniffed from the encoded reply (denied decision / error), which
        is what lets the tail sampler keep every denied request's trace.

        Deadline-stamped frames (op-byte bit 6) are stripped next: when
        ``arrival_s`` is given (the asyncio socket path stamps frame-read
        time) and this server's own queueing already consumed the
        client's budget, the request is SHED — a routable "deadline
        exceeded" error, the store untouched — instead of doing work
        whose caller has already timed out.
        """
        tctx = None
        deadline_s = None
        attempt = 0
        flagged_op = None
        if (len(body) >= 6 and body[5] & (wire.TRACE_FLAG
                                          | wire.DEADLINE_FLAG
                                          | wire.ATTEMPT_FLAG)):
            # Remember the raw flagged byte: if the residual frame fails
            # strict decode after the strips below, it was never a
            # flagged <op> — answer the routable "unknown op" an old
            # server gives the byte as sent, not a misparse of whatever
            # real op the masked bits happen to spell.
            flagged_op = body[5]
        if len(body) >= 6:
            if body[5] & wire.TRACE_FLAG:
                try:
                    body, tctx = wire.strip_trace(body)
                except wire.RemoteStoreError as exc:
                    return wire.encode_response(
                        _recover_seq(body), wire.RESP_ERROR, repr(exc))
            if body[5] & wire.DEADLINE_FLAG:
                try:
                    body, deadline_s = wire.strip_deadline(body)
                except wire.RemoteStoreError as exc:
                    return wire.encode_response(
                        _recover_seq(body), wire.RESP_ERROR, repr(exc))
            if body[5] & wire.ATTEMPT_FLAG:
                try:
                    body, attempt = wire.strip_attempt(body)
                except wire.RemoteStoreError as exc:
                    return wire.encode_response(
                        _recover_seq(body), wire.RESP_ERROR, repr(exc))
            if body[5] == wire.OP_ACQUIRE_MANY:
                tctx = wire.bulk_trace_tail(body)
                # The bulk lane's deadline + attempt ride one payload
                # tail (flags bit 5) — honored through the SAME gates
                # below, frame-level like the config/placement gates
                # (no row is applied on a shed; the reply is the same
                # routable error the scalar lane answers).
                btail = wire.bulk_deadline_tail(body)
                if btail is not None:
                    deadline_s, attempt = btail
        if attempt:
            self.retry_attempts_seen += 1
        if deadline_s is not None and arrival_s is not None:
            waited = time.perf_counter() - arrival_s
            if waited > deadline_s:
                self.requests_shed += 1
                return wire.encode_response(
                    _recover_seq(body), wire.RESP_ERROR,
                    f"deadline exceeded: request waited "
                    f"{waited * 1e3:.1f}ms against a "
                    f"{deadline_s * 1e3:.1f}ms budget (shed unexecuted)")
            if self.doomed_gate_enabled:
                # Doomed-work gate (armed with the storm defense): the
                # remaining budget cannot cover this server's current
                # p99 serving latency — deny at admit, store untouched,
                # instead of granting tokens the client will never
                # collect (docs/DESIGN.md §24).
                p99 = (self.serving_latency.p99
                       if self.serving_latency.total else 0.0)
                if waited + p99 > deadline_s:
                    self.requests_doomed += 1
                    self.requests_shed += 1
                    return wire.encode_response(
                        _recover_seq(body), wire.RESP_ERROR,
                        f"doomed: {deadline_s * 1e3:.1f}ms budget "
                        f"cannot cover p99 {p99 * 1e3:.1f}ms at admit "
                        "(shed unexecuted)")
        if attempt and self.retry_shed_enabled:
            # Retry-shed gate: retries shed FIRST, before any priority
            # class — a granted retry burns budget a first attempt
            # could have turned into goodput (docs/DESIGN.md §24).
            self.retries_shed += 1
            self.requests_shed += 1
            return wire.encode_response(
                _recover_seq(body), wire.RESP_ERROR,
                f"retry shed: attempt {attempt} denied while the "
                "retry-storm defense is armed")
        if tctx is None or not self.tracer.enabled:
            return await self._handle_frame_inner(body,
                                                  flagged_op=flagged_op)
        op = body[5] if len(body) >= 6 else 0
        with self.tracer.start_span(
                f"server.{wire.op_name(op)}", parent=tctx) as span:
            resp = await self._handle_frame_inner(body,
                                                  flagged_op=flagged_op)
            kind = resp[9] if len(resp) >= 10 else 0
            if kind == wire.RESP_ERROR:
                span.set_status("error")
            elif (kind == wire.RESP_DECISION and len(resp) >= 11
                    and resp[10] == 0):
                span.set_status("denied")
            elif kind == wire.RESP_BULK and len(resp) >= 15:
                # Bulk reply: [u8 flags][u32 n][granted bits…] at offset
                # 10. Any denied row marks the span — the coalesced
                # lane's denials must reach the tail sampler too (the
                # traced minority pays this popcount, nobody else).
                n = int.from_bytes(resp[11:15], "little")
                nbits = (n + 7) // 8
                granted = sum(bin(b).count("1")
                              for b in resp[15:15 + nbits])
                if granted < n:
                    span.set_status("denied")
                    span.set_attr("denied_rows", n - granted)
            if span.context is not None:
                # Exemplar on the serving histogram: the span's own
                # duration IS (within µs) the serving stage for this
                # request — the jump from a histogram bucket to the
                # exported trace that filled it.
                self.serving_latency.exemplar(
                    time.perf_counter() - span.start_s,
                    span.context.trace_id)
        return resp

    async def _handle_frame_inner(self, body: bytes, *,
                                  flagged_op: "int | None" = None) -> bytes:
        seq = _recover_seq(body)
        try:
            if len(body) >= 6 and body[5] == wire.OP_ACQUIRE_MANY:
                # Bulk frames carry arrays, not the scalar request shape —
                # decode + serve them on their own path. One frame = one
                # store bulk call = (on a device store) a handful of
                # scanned kernel launches for thousands of decisions.
                # as_view: keys stay a zero-copy KeyBlob over the frame
                # bytes — device-backed stores resolve them natively
                # without materializing per-key Python strings; serial
                # stores iterate the view like the list they used to get.
                seq, keys, counts, a, b, with_rem, kind = (
                    wire.decode_bulk_request(body, as_view=True))
                if kind == wire.BULK_KIND_HBUCKET:
                    # Hierarchical bulk: one tenant's rows, decided
                    # two-level — its own lane (tenant extension, both
                    # config gates, priority-aware envelopes).
                    return await self._serve_bulk_hier(
                        seq, body, keys, counts, a, b, with_rem)
                if self.liveconfig.active:
                    # Frame-level config gate: one (kind, a, b) decides a
                    # whole bulk frame, so one probe covers every row —
                    # a retired config answers the routable moved error
                    # (no row was applied) and the client re-sends the
                    # frame with the new operands.
                    ckind = liveconfig.BULK_KINDS.get(kind)
                    fwd = (self.liveconfig.forward(ckind, a, b)
                           if ckind is not None else None)
                    if fwd is not None:
                        return wire.encode_response(
                            seq, wire.RESP_ERROR,
                            self.liveconfig.moved(ckind, a, b, fwd))
                env = self._drain_envelope
                if env is not None:
                    return self._serve_bulk_draining(
                        seq, keys, counts, a, b, with_rem, kind, env)
                gate = (self.placement.bulk_gate(keys)
                        if self.placement.active else None)
                if gate is not None and gate[2].any():
                    # Misrouted rows answer a FRAME-level moved error —
                    # the same routable signal the scalar gate emits.
                    # No row was applied (all-or-error), so the client
                    # refreshes its map and resends the whole frame; a
                    # bulk-only client would otherwise hold a stale map
                    # forever (silent denial gave it no refresh trigger).
                    i = int(np.nonzero(gate[2])[0][0])
                    key = keys[int(i)]
                    return wire.encode_response(
                        seq, wire.RESP_ERROR,
                        self.placement.moved_message(
                            key, int(self.placement.pmap.node_of(key))))
                self._offer_bulk_hot(keys, counts)
                if gate is not None:
                    res = await self._serve_bulk_gated(
                        keys, counts, a, b, with_rem, kind, gate)
                elif kind == wire.BULK_KIND_BUCKET:
                    res = await self.store.acquire_many(
                        keys, counts, a, b, with_remaining=with_rem)
                else:
                    res = await self.store.window_acquire_many(
                        keys, counts, a, b,
                        fixed=(kind == wire.BULK_KIND_FWINDOW),
                        with_remaining=with_rem)
                return wire.encode_bulk_response(seq, res.granted,
                                                 res.remaining)
            if len(body) >= 6 and body[5] == wire.OP_ACQUIRE_H:
                return await self._serve_hierarchical(body)
            try:
                seq, op, key, count, a, b = wire.decode_request(body)
            except wire.RemoteStoreError:
                raise  # already routable ("unknown op N", truncated, ...)
            except Exception as exc:
                if flagged_op is not None:
                    # The tails were stripped off a flagged op byte whose
                    # masked bits spell a real op, but the residual
                    # payload is not that op's shape — the frame was
                    # never a flagged <op>. Reject the byte as sent.
                    raise wire.RemoteStoreError(
                        f"unknown op {flagged_op}") from exc
                raise
            if self.liveconfig.active and op in _CONFIG_GATED_OPS:
                fwd = self.liveconfig.forward(_CONFIG_GATED_OPS[op], a, b)
                if fwd is not None:
                    # Retired config: routable moved error, store
                    # untouched — the client re-sends once with the new
                    # operands and caches the translation (the placement
                    # MOVED posture; DESIGN.md §13).
                    return wire.encode_response(
                        seq, wire.RESP_ERROR,
                        self.liveconfig.moved(_CONFIG_GATED_OPS[op],
                                              a, b, fwd))
            env = self._drain_envelope
            if env is not None and op in _PLACEMENT_GATED_OPS:
                ekind = _ENVELOPE_KIND.get(op)
                if ekind is not None and count >= 0:
                    # Draining: the store's balances already shipped to
                    # the successor — admission serves the bounded
                    # fair-share envelope the export withheld, exactly
                    # the mid-handoff parked-key treatment.
                    granted, remaining = env.acquire(key, count, a, b,
                                                     ekind)
                    return wire.encode_response(
                        seq, wire.RESP_DECISION, granted, remaining)
                return wire.encode_response(
                    seq, wire.RESP_ERROR,
                    f"{placement.HANDOFF_DEFERRAL_PREFIX}: server is "
                    "draining to its successor; retry shortly")
            if self.placement.active and op in _PLACEMENT_GATED_OPS:
                verdict = self.placement.gate(key)
                if verdict is not None:
                    what, info = verdict
                    ekind = _ENVELOPE_KIND.get(op)
                    if what == "envelope":
                        if ekind is not None and count >= 0:
                            granted, remaining = \
                                self.placement.envelope_acquire(
                                    info, key, count, a, b, ekind)
                            return wire.encode_response(
                                seq, wire.RESP_DECISION, granted,
                                remaining)
                        # Parked PEEK/SYNC/SEMA have no envelope value
                        # and no authoritative owner yet (pre-commit) —
                        # a MOVED here would name THIS node and send the
                        # client in a circle. Answer a transient typed
                        # error instead; the window bounds the wait.
                        self.placement.handoff_deferrals += 1
                        return wire.encode_response(
                            seq, wire.RESP_ERROR,
                            f"{placement.HANDOFF_DEFERRAL_PREFIX} for "
                            f"this key (target epoch "
                            f"{info.target_epoch}); retry shortly")
                    # Plainly-misrouted keys answer the routable moved
                    # error: the client refetches the map and re-routes
                    # rather than reading a non-authority.
                    return wire.encode_response(
                        seq, wire.RESP_ERROR,
                        self.placement.moved_message(key, info))
            hh = self.heavy_hitters
            if hh is not None and count > 0 and op in _HOT_KEYED_OPS:
                # Hot-key telemetry: scalar admission lane (the bulk
                # KeyBlob lane stays zero-copy and is deliberately not
                # counted — utils/heavy_hitters.py overhead discipline).
                # count > 0 gates out SEMA releases (signed delta < 0)
                # and zero-permit probes — neither is admission demand,
                # and counting releases would double-weight semaphore
                # keys. Unit-weight requests (the overwhelming shape)
                # stage through the buffered feed: one list append here,
                # the sketch merge amortized across the buffer.
                if count > 1:
                    hh.offer(key, count)
                else:
                    hh.offer_buffered(key)
            if op == wire.OP_ACQUIRE:
                res = await self.store.acquire(key, count, a, b)
                granted = res.granted
                if granted:
                    # Witnessed: the store ACTUALLY debited this grant.
                    self.audit_witnessed_tokens += count
                if faults._INJECTOR is not None and not granted:
                    # audit.leak (utils/faults.py): flip a deny into a
                    # grant WITHOUT the store debit — a deliberate
                    # token leak between the two witness counters, so
                    # the seeded soak can prove the conservation
                    # auditor catches exactly this class of bug.
                    if faults._INJECTOR.decide("audit.leak") is not None:
                        granted = True
                if granted:
                    # Replied: what the CLIENT was told. Any positive
                    # replied−witnessed delta is a leak no ε excuses
                    # (runtime/audit.py reply/witness identity).
                    self.audit_replied_tokens += count
                resp = wire.encode_response(
                    seq, wire.RESP_DECISION, granted, res.remaining)
            elif op == wire.OP_PEEK:
                # peek_blocking can wait on the store lock / a device op —
                # run it off-loop so one PEEK never stalls other
                # connections' traffic.
                value = await asyncio.to_thread(
                    self.store.peek_blocking, key, a, b)
                resp = wire.encode_response(seq, wire.RESP_VALUE, value)
            elif op == wire.OP_SYNC:
                res = await self.store.sync_counter(key, a, b)
                resp = wire.encode_response(
                    seq, wire.RESP_PAIR, res.global_score, res.period_ewma_ticks)
            elif op == wire.OP_WINDOW:
                res = await self.store.window_acquire(key, count, a, b)
                resp = wire.encode_response(
                    seq, wire.RESP_DECISION, res.granted, res.remaining)
            elif op == wire.OP_FWINDOW:
                res = await self.store.fixed_window_acquire(key, count, a, b)
                resp = wire.encode_response(
                    seq, wire.RESP_DECISION, res.granted, res.remaining)
            elif op == wire.OP_SEMA:
                if count >= 0:
                    res = await self.store.concurrency_acquire(
                        key, count, int(a))
                else:
                    await self.store.concurrency_release(key, -count)
                    res = None
                resp = wire.encode_response(
                    seq, wire.RESP_DECISION,
                    True if res is None else res.granted,
                    0.0 if res is None else res.remaining)
            elif op == wire.OP_PING:
                resp = wire.encode_response(seq, wire.RESP_EMPTY)
            elif op == wire.OP_SAVE:
                if self.snapshot_path is None:
                    resp = wire.encode_response(
                        seq, wire.RESP_ERROR,
                        "server has no --snapshot-path configured")
                else:
                    from distributedratelimiting.redis_tpu.runtime import (
                        checkpoint,
                    )

                    # Coalesce concurrent SAVEs: requests arriving while a
                    # save is in flight piggyback on it (BGSAVE semantics)
                    # instead of queueing N redundant full-state pulls.
                    if self._save_task is None or self._save_task.done():
                        # Placement-versioned checkpoint: a rejoining
                        # node restoring this file can be held to the
                        # cluster's current epoch (placement.py).
                        epoch = (self.placement.epoch
                                 if self.placement.active else None)
                        if self._snapshot_chain is not None:
                            # Incremental: a v4 delta against the last
                            # save (the chain compacts to a full base
                            # on its own thresholds).
                            self._save_task = asyncio.ensure_future(
                                asyncio.to_thread(
                                    self._snapshot_chain.save,
                                    self.store, epoch))
                        else:
                            self._save_task = asyncio.ensure_future(
                                asyncio.to_thread(
                                    checkpoint.save_snapshot, self.store,
                                    self.snapshot_path,
                                    placement_epoch=epoch))
                    await asyncio.shield(self._save_task)
                    resp = wire.encode_response(seq, wire.RESP_EMPTY)
            elif op == wire.OP_STATS:
                if (count & wire.STATS_FLAG_FLIGHT_DUMP
                        and self.flight_recorder is not None):
                    # Explicit operator trigger (OP_SAVE-style): dump
                    # BEFORE snapshotting so the stats payload carries
                    # the fresh path.
                    self.flight_recorder.dump("stats_trigger")
                resp = wire.encode_response(
                    seq, wire.RESP_TEXT, self._stats_json())
                if count & wire.STATS_FLAG_RESET:
                    # Start a fresh measurement window (serving + every
                    # stage histogram, both halves of the stack). The
                    # window is SHARED — see the destructive-reset
                    # contract in utils/metrics.py; the serving
                    # histogram's own `resets` count (surfaced as
                    # stats_resets) is the tripwire other consumers
                    # watch, and it counts direct embedder resets too.
                    if self._native is not None:
                        self._native.reset_latency()
                    self.serving_latency.reset()
                    self.reply_latency.reset()
                    metrics = getattr(self.store, "metrics", None)
                    if metrics is not None:
                        if hasattr(metrics, "flush_latency"):
                            metrics.flush_latency.reset()
                        if hasattr(metrics, "queue_latency"):
                            metrics.queue_latency.reset()
            elif op == wire.OP_METRICS:
                resp = wire.encode_response(
                    seq, wire.RESP_TEXT, self.registry.render())
            elif op == wire.OP_PLACEMENT:
                import json

                resp = wire.encode_response(
                    seq, wire.RESP_TEXT,
                    json.dumps(self.placement.snapshot_payload()))
            elif op == wire.OP_PLACEMENT_ANNOUNCE:
                import json

                epoch = self.placement.announce(json.loads(key))
                if self._native is not None and self.native_tier0:
                    # The C tier-0 cache decides hot keys without the
                    # gate; its epsilon bound still holds, but a
                    # membership change deserves the operator's eye
                    # (docs/OPERATIONS.md §9 failure modes).
                    import logging

                    logging.getLogger(__name__).warning(
                        "placement epoch %d adopted with the tier-0 "
                        "cache enabled: tier-0 keeps deciding hot keys "
                        "until their budgets drain", epoch)
                resp = wire.encode_response(seq, wire.RESP_VALUE,
                                            float(epoch))
            elif op == wire.OP_MIGRATE_PULL:
                import json

                await faults.seam("server.migrate")
                out = await self.placement.pull(json.loads(key),
                                                self.store)
                resp = wire.encode_response(seq, wire.RESP_TEXT,
                                            json.dumps(out))
            elif op == wire.OP_MIGRATE_PUSH:
                import json

                await faults.seam("server.migrate")
                applied = await self.placement.push(json.loads(key),
                                                    self.store)
                resp = wire.encode_response(seq, wire.RESP_VALUE,
                                            float(applied))
            elif op == wire.OP_CONFIG:
                import json

                payload = json.loads(key)
                if not payload:
                    resp = wire.encode_response(
                        seq, wire.RESP_TEXT, json.dumps(
                            self.liveconfig.snapshot_payload()))
                else:
                    await faults.seam("server.config")
                    version = await self.liveconfig.announce(
                        payload, self.store)
                    resp = wire.encode_response(seq, wire.RESP_VALUE,
                                                float(version))
            elif op == wire.OP_RESERVE:
                import json

                resp = await self._serve_reserve(seq, json.loads(key))
            elif op == wire.OP_SETTLE:
                import json

                resp = await self._serve_settle(seq, json.loads(key))
            elif op in (wire.OP_FED_LEASE, wire.OP_FED_RENEW,
                        wire.OP_FED_RECLAIM):
                import json

                await faults.seam("server.federation")
                resp = await self._serve_federation(seq, op,
                                                    json.loads(key))
            elif op == wire.OP_TRACES:
                # Chrome-trace JSON capped under MAX_FRAME (newest traces
                # win); flag bit 0 drains the buffer after export.
                resp = wire.encode_response(
                    seq, wire.RESP_TEXT, self.tracer.export_chrome_json(
                        max_bytes=wire.MAX_FRAME - 256,
                        drain=bool(count & 1)))
            elif op == wire.OP_AUDIT:
                import json

                resp = wire.encode_response(
                    seq, wire.RESP_TEXT,
                    self._audit_json(json.loads(key) if key else {}))
            else:  # pragma: no cover — decode_request raises first
                resp = wire.encode_response(
                    seq, wire.RESP_ERROR, f"unknown op {op}")
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # relay (with the recovered seq), never
            log.error_evaluating_kernel(exc)  # kill the connection
            resp = wire.encode_response(seq, wire.RESP_ERROR, repr(exc))
        return resp

    def _offer_bulk_hot(self, keys, counts) -> None:
        """Cost-weighted heavy-hitter feed for the asyncio bulk lane —
        closes the PR-2 zero-copy exemption: ``offer_blob`` aggregates
        straight off the frame's byte blob (bounded sample + top-K
        merge, no 100K-string materialization), so velocity/split
        telemetry sees all three serving lanes, weighted in TOKENS."""
        hh = self.heavy_hitters
        if hh is None:
            return
        if isinstance(keys, wire.KeyBlob):
            hh.offer_blob(keys.blob, keys.offsets, counts)
        else:
            hh.offer_many(keys, np.asarray(counts, np.float64))

    def _hier_config_gate(self, seq: int, a: float, b: float,
                          ta: float, tb: float) -> "bytes | None":
        """Both levels of a hierarchical frame gate on the live-config
        rules — a retired CHILD config and a retired PARENT (tenant)
        config each answer the routable moved error (the client learns
        the rule for whichever level moved and re-sends translated;
        both rules live under the one "bucket" kind)."""
        if not self.liveconfig.active:
            return None
        for pa, pb in ((a, b), (ta, tb)):
            fwd = self.liveconfig.forward("bucket", pa, pb)
            if fwd is not None:
                return wire.encode_response(
                    seq, wire.RESP_ERROR,
                    self.liveconfig.moved("bucket", pa, pb, fwd))
        return None

    @staticmethod
    def _hier_envelope(env_acquire, tenant: str, key: str, count: int,
                       a: float, b: float, ta: float, tb: float,
                       priority: int) -> tuple[bool, float]:
        """Two-level envelope serving for hierarchical requests during
        a drain window / parked handoff: child envelope then tenant
        envelope, grant iff both (a child-envelope debit on a tenant
        deny stays debited — envelope over-conservatism, the safe
        direction). The priority shed order applies at BOTH levels via
        the shared gate (admission.shed_allows)."""
        g1, r1 = env_acquire(key, count, a, b, "bucket", priority)
        if not g1:
            return False, r1
        g2, r2 = env_acquire(tenant, count, ta, tb, "bucket", priority)
        return g2, min(r1, r2)

    async def _serve_hierarchical(self, body: bytes) -> bytes:
        """One OP_ACQUIRE_H frame: tenant → key two-level weighted-cost
        admission (runtime/admission.py; DESIGN.md §15). Mirrors the
        scalar ACQUIRE lane gate-for-gate — live-config (both levels),
        drain envelope, placement — with the placement gate keyed on
        the TENANT: hierarchical calls route by tenant (the parent
        bucket must live whole on one node), so tenant ownership is
        the routing truth the MOVED error must name."""
        seq, key, count, a, b, tenant, ta, tb, priority = (
            wire.decode_hierarchical_request(body))
        gate_resp = self._hier_config_gate(seq, a, b, ta, tb)
        if gate_resp is not None:
            return gate_resp
        env = self._drain_envelope
        if env is not None:
            if count >= 0:
                granted, remaining = self._hier_envelope(
                    env.acquire, tenant, key, count, a, b, ta, tb,
                    priority)
                return wire.encode_response(
                    seq, wire.RESP_DECISION, granted, remaining)
            return wire.encode_response(
                seq, wire.RESP_ERROR,
                f"{placement.HANDOFF_DEFERRAL_PREFIX}: server is "
                "draining to its successor; retry shortly")
        if self.placement.active:
            verdict = self.placement.gate(tenant)
            if verdict is not None:
                what, info = verdict
                if what == "envelope" and count >= 0:
                    granted, remaining = self._hier_envelope(
                        lambda k, c, pa, pb, kind, prio:
                        self.placement.envelope_acquire(
                            info, k, c, pa, pb, kind, prio),
                        tenant, key, count, a, b, ta, tb, priority)
                    return wire.encode_response(
                        seq, wire.RESP_DECISION, granted, remaining)
                if what == "envelope":
                    self.placement.handoff_deferrals += 1
                    return wire.encode_response(
                        seq, wire.RESP_ERROR,
                        f"{placement.HANDOFF_DEFERRAL_PREFIX} for "
                        f"this tenant (target epoch "
                        f"{info.target_epoch}); retry shortly")
                return wire.encode_response(
                    seq, wire.RESP_ERROR,
                    self.placement.moved_message(tenant, info))
        hh = self.heavy_hitters
        if hh is not None and count > 0:
            # Cost-weighted: an N-token admission weighs N in the
            # sketch, so hot-COST keys surface as split candidates.
            if count > 1:
                hh.offer(key, count)
            else:
                hh.offer_buffered(key)
        res = await self.store.acquire_hierarchical(
            tenant, key, count, ta, tb, a, b, priority=priority)
        if res.granted and count > 0 and self.token_velocity is not None:
            self.token_velocity.observe(tenant, float(count))
        return wire.encode_response(seq, wire.RESP_DECISION,
                                    res.granted, res.remaining)

    # -- estimate-reserve-settle dispatch (runtime/reservations.py) ----------
    async def _serve_reserve(self, seq: int, req: dict) -> bytes:
        """One OP_RESERVE frame: admission at the estimate + a TTL'd
        ledger hold. Mirrors the OP_ACQUIRE_H lane gate-for-gate —
        live-config on both levels, drain envelope, placement keyed on
        the TENANT (reservations route with the hierarchical traffic
        they budget). Envelope-served reserves (drain window / parked
        handoff) take NO ledger entry: the state is mid-flight to
        another owner, the grant is envelope-bounded, and the eventual
        settle answers the counted "unknown" no-op — the hold is never
        refunded, the conservative direction (DESIGN.md §18)."""
        import json

        rid = str(req.get("rid") or "")
        tenant = str(req.get("tenant") or "")
        key = str(req.get("key") or "")
        if not rid or not tenant or not key:
            return wire.encode_response(
                seq, wire.RESP_ERROR,
                "reserve requires rid, tenant, and key")
        estimate = req.get("estimate")
        a, b = float(req.get("a", 0.0)), float(req.get("b", 0.0))
        ta, tb = float(req.get("ta", 0.0)), float(req.get("tb", 0.0))
        priority = int(req.get("priority", 0))
        ttl_s = req.get("ttl_s")
        attempt = int(req.get("attempt", 0) or 0)
        try:
            deadline_s = (float(req["deadline_s"])
                          if req.get("deadline_s") is not None else None)
        except (TypeError, ValueError):
            deadline_s = None
        gate_resp = self._hier_config_gate(seq, a, b, ta, tb)
        if gate_resp is not None:
            return gate_resp
        from distributedratelimiting.redis_tpu.runtime import (
            reservations,
        )
        from distributedratelimiting.redis_tpu.runtime.reservations import (
            fallback_charge,
        )

        led = self.reservations
        est = float(estimate) if estimate else None
        if est is None and led is not None:
            est = led.prior.estimate(tenant, priority)
            if est is None:
                est = led.default_estimate
        # fallback_charge floors an estimate-less charge at the same
        # DEFAULT_ESTIMATE the ledger uses — the envelope paths below
        # must not admit a typical stream for a 1-token charge.
        charge = fallback_charge(est)

        def envelope_reply(env_acquire) -> bytes:
            granted, remaining = self._hier_envelope(
                env_acquire, tenant, key, charge, a, b, ta, tb,
                priority)
            return wire.encode_response(seq, wire.RESP_TEXT, json.dumps(
                {"granted": bool(granted),
                 "reserved": float(charge) if granted else 0.0,
                 "remaining": float(remaining), "debt": 0.0,
                 "envelope": True}))

        env = self._drain_envelope
        if env is not None:
            return envelope_reply(env.acquire)
        if self.placement.active:
            verdict = self.placement.gate(tenant)
            if verdict is not None:
                what, info = verdict
                if what == "envelope":
                    return envelope_reply(
                        lambda k, c, pa, pb, kind, prio:
                        self.placement.envelope_acquire(
                            info, k, c, pa, pb, kind, prio))
                return wire.encode_response(
                    seq, wire.RESP_ERROR,
                    self.placement.moved_message(tenant, info))
        if led is None:  # pragma: no cover — every store has a ledger
            return wire.encode_response(
                seq, wire.RESP_ERROR,
                "this server has no reservation ledger")
        if attempt:
            self.retry_attempts_seen += 1
            if self.retry_shed_enabled:
                # The reservation lane's retry-shed answer is a plain
                # deny (granted False) — a deny is terminal to the
                # client, exactly the posture a storm needs; a routable
                # error would invite another retry.
                self.retries_shed += 1
                self.requests_shed += 1
                return wire.encode_response(
                    seq, wire.RESP_TEXT, json.dumps(
                        {"granted": False, "reserved": 0.0,
                         "remaining": 0.0, "debt": 0.0,
                         "duplicate": False, "shed": "retry"}))
        if self.doomed_gate_enabled and deadline_s is not None:
            p99 = (self.serving_latency.p99
                   if self.serving_latency.total else 0.0)
            if p99 > deadline_s:
                self.requests_doomed += 1
                self.requests_shed += 1
                return wire.encode_response(
                    seq, wire.RESP_TEXT, json.dumps(
                        {"granted": False, "reserved": 0.0,
                         "remaining": 0.0, "debt": 0.0,
                         "duplicate": False, "shed": "doomed"}))
        pool = self.overflow_pool
        if (pool is not None and deadline_s is not None
                and priority == admission.PRIORITY_INTERACTIVE):
            # Budget-aware pool routing (docs/DESIGN.md §24): when the
            # estimate will not fit the interactive pool's remaining
            # tenant budget inside the client's deadline, answer the
            # routable route-to-pool redirect (the config-moved
            # posture: chase-once client, never a silent grant the
            # budget cannot honor in time).
            peek = getattr(self.store, "peek_blocking", None)
            balance = None
            if callable(peek):
                try:
                    balance = peek(tenant, ta, tb)
                except Exception:  # drl-check: ok(swallowed-exception)
                    # — a backing without a sync peek lane (e.g. a
                    # remote/device store behind this node) degrades to
                    # routing-off, the pre-§24 behavior; the reserve
                    # itself still runs and is the visible outcome.
                    balance = None
            if (balance is not None
                    and charge > balance + tb * max(0.0, deadline_s)):
                self.reserves_routed += 1
                return wire.encode_response(
                    seq, wire.RESP_ERROR,
                    reservations.route_message(
                        str(pool.get("pool", "overflow")),
                        float(pool.get("ta", ta)),
                        float(pool.get("tb", tb)),
                        int(pool.get("priority",
                                     admission.PRIORITY_BATCH))))
        hh = self.heavy_hitters
        if hh is not None and charge > 1:
            hh.offer(key, charge)
        res = await led.reserve(rid, tenant, key, estimate, ta, tb,
                                a, b, priority=priority, ttl_s=ttl_s,
                                attempt=attempt, deadline_s=deadline_s)
        return wire.encode_response(seq, wire.RESP_TEXT, json.dumps(
            {"granted": res.granted, "reserved": res.reserved,
             "remaining": res.remaining, "debt": res.debt,
             "duplicate": res.duplicate}))

    async def _serve_settle(self, seq: int, req: dict) -> bytes:
        """One OP_SETTLE frame: reconcile a reservation's actual cost.
        During a drain window the settle RELAYS to the successor (the
        ledger entries shipped with the export; settle is idempotent,
        so even a duplicated relay is safe); a parked/moved tenant
        answers the deferral/MOVED errors so the retry lands on the
        ledger's new owner."""
        import json

        rid = str(req.get("rid") or "")
        tenant = str(req.get("tenant") or "")
        if not rid or not tenant:
            return wire.encode_response(
                seq, wire.RESP_ERROR,
                "settle requires rid and tenant")
        try:
            actual = float(req.get("actual", 0.0))
        except (TypeError, ValueError):
            return wire.encode_response(seq, wire.RESP_ERROR,
                                        "settle actual must be a number")
        successor = self._drain_successor
        if self._drain_envelope is not None and successor is not None:
            try:
                res = await successor.settle(rid, tenant, actual)
            except Exception as exc:
                log.error_evaluating_kernel(exc)
                return wire.encode_response(
                    seq, wire.RESP_ERROR,
                    f"{placement.HANDOFF_DEFERRAL_PREFIX}: settle "
                    "relay to the drain successor failed; retry")
            return wire.encode_response(
                seq, wire.RESP_TEXT, json.dumps(res._asdict()))
        if self.placement.active:
            verdict = self.placement.gate(tenant)
            if verdict is not None:
                what, info = verdict
                if what == "envelope":
                    # Parked mid-handoff: the ledger rows already left
                    # with the export — the retry (settle is post-send-
                    # retry-safe) lands after commit on the new owner.
                    self.placement.handoff_deferrals += 1
                    return wire.encode_response(
                        seq, wire.RESP_ERROR,
                        f"{placement.HANDOFF_DEFERRAL_PREFIX} for "
                        f"this tenant (target epoch "
                        f"{info.target_epoch}); retry shortly")
                return wire.encode_response(
                    seq, wire.RESP_ERROR,
                    self.placement.moved_message(tenant, info))
        if self.reservations is None:  # pragma: no cover
            return wire.encode_response(
                seq, wire.RESP_ERROR,
                "this server has no reservation ledger")
        res = await self.reservations.settle(rid, tenant, actual)
        return wire.encode_response(seq, wire.RESP_TEXT,
                                    json.dumps(res._asdict()))

    # -- global quota federation dispatch (runtime/federation.py) ------------
    @property
    def federation(self):
        """The store-attached home ledger, or ``None`` until the first
        federation frame creates it (non-home servers never pay for
        one) — read dynamically so the registry/stats callables see it
        the moment it exists."""
        return getattr(self.store, "_federation", None)

    def _fed_ledger(self):
        """Get-or-create the home ledger, wired into THIS server's
        observability plane (the reservations re-wire posture: a store
        re-fronted by a new server must see the new plane)."""
        led = self.store.federation_ledger()
        led.flight_recorder = self.flight_recorder
        led.velocity = self.token_velocity
        return led

    async def _serve_federation(self, seq: int, op: int,
                                req: dict) -> bytes:
        """One federation control frame at the home: lease / renew /
        reclaim against the store-attached :class:`~.federation.
        FederationLedger`. All three are post-send-retry-safe
        (lease/reclaim replay recorded results, renew is absorbing) —
        validation failures answer the routable error, the ledger
        untouched."""
        import json

        led = self._fed_ledger()
        if op == wire.OP_FED_LEASE:
            out = await led.lease(req)
        elif op == wire.OP_FED_RENEW:
            out = await led.renew(req)
        else:
            out = await led.reclaim(req)
        return wire.encode_response(seq, wire.RESP_TEXT,
                                    json.dumps(out))

    async def _serve_bulk_hier(self, seq: int, body: bytes, keys,
                               counts, a: float, b: float,
                               with_rem: bool) -> bytes:
        """One BULK_KIND_HBUCKET frame: one tenant's rows decided
        two-level in one store call (the fused kernel on device
        stores). Frame-level gates mirror the flat bulk lane's; the
        placement gate keys on the tenant (the frame's routing
        identity)."""
        tenant, ta, tb, priority = wire.bulk_hier_tail(body)
        gate_resp = self._hier_config_gate(seq, a, b, ta, tb)
        if gate_resp is not None:
            return gate_resp
        n = len(keys)
        counts_np = np.asarray(counts, np.int64)
        env = self._drain_envelope
        env_acquire = None
        if env is not None:
            env_acquire = env.acquire
        elif self.placement.active:
            verdict = self.placement.gate(tenant)
            if verdict is not None:
                what, info = verdict
                if what != "envelope":
                    return wire.encode_response(
                        seq, wire.RESP_ERROR,
                        self.placement.moved_message(tenant, info))
                env_acquire = (
                    lambda k, c, pa, pb, kind, prio:
                    self.placement.envelope_acquire(info, k, c, pa, pb,
                                                    kind, prio))
        if env_acquire is not None:
            granted = np.zeros(n, bool)
            remaining = np.zeros(n, np.float32) if with_rem else None
            for i in range(n):
                g, rem = self._hier_envelope(
                    env_acquire, tenant, keys[i], int(counts_np[i]),
                    a, b, ta, tb, priority)
                granted[i] = g
                if remaining is not None:
                    remaining[i] = rem
            return wire.encode_bulk_response(seq, granted, remaining)
        self._offer_bulk_hot(keys, counts_np)
        res = await self.store.acquire_hierarchical_many(
            [tenant] * n, keys, counts_np, ta, tb, a, b,
            with_remaining=with_rem, priority=priority)
        if self.token_velocity is not None:
            admitted = int(counts_np[np.asarray(res.granted,
                                                bool)].sum())
            if admitted > 0:
                self.token_velocity.observe(tenant, float(admitted))
        return wire.encode_bulk_response(seq, res.granted,
                                         res.remaining)

    async def _serve_bulk_gated(self, keys, counts, a: float, b: float,
                                with_rem: bool, kind: int, gate):
        """One bulk frame under an active placement map with at least
        one parked row (frames containing MISROUTED rows never reach
        here — the caller answers those with a frame-level moved error
        so stale bulk clients refresh their map): owned rows take the
        normal store path, parked rows serve from their handoff
        envelope. Row order is preserved."""
        from distributedratelimiting.redis_tpu.runtime.store import (
            BulkAcquireResult,
        )

        serve_mask, envelope_rows, _moved = gate
        n = len(keys)
        counts_np = np.asarray(counts, np.int64)
        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32) if with_rem else None
        idx = np.nonzero(serve_mask)[0]
        if len(idx):
            sub_keys = [keys[int(i)] for i in idx]
            if kind == wire.BULK_KIND_BUCKET:
                res = await self.store.acquire_many(
                    sub_keys, counts_np[idx], a, b,
                    with_remaining=with_rem)
            else:
                res = await self.store.window_acquire_many(
                    sub_keys, counts_np[idx], a, b,
                    fixed=(kind == wire.BULK_KIND_FWINDOW),
                    with_remaining=with_rem)
            granted[idx] = res.granted
            if remaining is not None and res.remaining is not None:
                remaining[idx] = res.remaining
        ekind = _BULK_ENVELOPE_KIND[kind]
        for i, handoff in envelope_rows:
            g, rem = self.placement.envelope_acquire(
                handoff, keys[i], int(counts_np[i]), a, b, ekind)
            granted[i] = g
            if remaining is not None:
                remaining[i] = rem
        return BulkAcquireResult(granted, remaining)

    def _serve_bulk_draining(self, seq: int, keys, counts, a: float,
                             b: float, with_rem: bool, kind: int,
                             env) -> bytes:
        """One bulk frame while the drain is in flight: every row serves
        from the shutdown envelope (the store's balances already shipped
        to the successor). Row order is preserved; SEMA never reaches
        here (bulk frames carry admission kinds only)."""
        ekind = _BULK_ENVELOPE_KIND[kind]
        n = len(keys)
        counts_np = np.asarray(counts, np.int64)
        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32) if with_rem else None
        for i in range(n):
            g, rem = env.acquire(keys[i], int(counts_np[i]), a, b, ekind)
            granted[i] = g
            if remaining is not None:
                remaining[i] = rem
        return wire.encode_bulk_response(seq, granted, remaining)

    # -- drain-and-handoff shutdown (docs/OPERATIONS.md §10) ----------------
    async def shutdown(self, successor=None, *, window_s: float = 2.0,
                       envelope_fraction: float =
                       placement.DEFAULT_ENVELOPE_FRACTION) -> dict:
        """Planned shutdown that ships state instead of wiping it.

        With a ``successor`` store (any :class:`~.store.BucketStore` —
        typically a :class:`~.remote.RemoteBucketStore` at the new
        process), this reuses the migration handoff lane end to end:
        the whole keyspace is exported with the fair-share envelope
        debit applied, the local store is charged for the shipped
        amount (:func:`placement.debit_source` — the dual-ownership
        bound holds even if this process lingers), in-flight and
        late-arriving admission traffic serves from the withheld
        envelope for at most ``window_s``, and the exact remainder
        lands on the successor through the MIGRATE_PUSH import lane
        (batch-deduped — a retried push cannot double-apply).

        With no successor, the final state goes to the configured
        snapshot path instead (through the incremental chain when one
        is armed) — the restarted process restores it and no state is
        dropped. Returns a summary dict; idempotent once COMPLETE: a
        failed drain re-opens for retry after falling back to a final
        checkpoint (when one is configured) — the state must land
        somewhere."""
        if self._shutdown_done:
            return {"already": True}
        self._shutdown_done = True
        # An OP_SAVE still writing must finish first: SnapshotChain has
        # no internal lock, and a concurrent final save would interleave
        # delta links (divergent prev_crc → SnapshotChainError → the
        # restart falls back to init-on-miss, losing exactly the state
        # this shutdown exists to keep).
        if self._save_task is not None and not self._save_task.done():
            try:
                await asyncio.shield(self._save_task)
            # The save's own OP_SAVE caller already saw this failure.
            # drl-check: ok(swallowed-exception)
            except Exception:
                pass
        try:
            return await self._shutdown_body(successor, window_s,
                                             envelope_fraction)
        except asyncio.CancelledError:
            self._shutdown_done = False
            self._drain_envelope = None
            self._drain_successor = None
            raise
        except Exception as exc:
            # Resume authoritative serving from the (possibly already
            # debited) store — the migration-abort posture: the residual
            # IS the envelope, so un-gating under-admits at worst. Left
            # armed, the envelope would cap this server forever.
            # (Exported reservations stay gone from the local ledger —
            # which chunks landed at the successor is unknowable, and a
            # blind restore could double-count a delivered hold; their
            # settles answer the counted "unknown" no-op, the
            # conservative direction.)
            self._drain_envelope = None
            self._drain_successor = None
            if successor is not None and self.snapshot_path is not None:
                # The drain failed mid-flight (successor unreachable,
                # push error) AFTER the source debit may have landed:
                # the shipped-but-unreceived balance must not evaporate.
                # Final checkpoint is the fallback home; the restarted
                # process restores it.
                try:
                    path = await self._final_checkpoint()
                except Exception as save_exc:
                    log.error_evaluating_kernel(save_exc)
                else:
                    log.error_evaluating_kernel(exc)
                    await self.aclose()
                    return {"shipped_rows": 0, "checkpoint": path,
                            "drain_error": repr(exc)}
            self._shutdown_done = False  # retryable — nothing landed
            raise

    async def _final_checkpoint(self) -> str:
        from distributedratelimiting.redis_tpu.runtime import checkpoint

        epoch = (self.placement.epoch if self.placement.active else None)
        if self._snapshot_chain is not None:
            return await asyncio.to_thread(self._snapshot_chain.save,
                                           self.store, epoch)
        await asyncio.to_thread(checkpoint.save_snapshot, self.store,
                                self.snapshot_path,
                                placement_epoch=epoch)
        return self.snapshot_path

    async def _shutdown_body(self, successor, window_s: float,
                             envelope_fraction: float) -> dict:
        out: dict = {"shipped_rows": 0, "checkpoint": None}
        if successor is not None:
            env = placement._FairShareEnvelope(envelope_fraction)
            entries = await asyncio.to_thread(
                placement._export_from_store, self.store, lambda _k: True)
            export = placement.debit_export(entries, envelope_fraction)
            target_epoch = (self.placement.epoch + 1
                            if self.placement.active else 1)
            # Outstanding reservations (and tenant debts) ship with the
            # state: their settles will be relayed to the successor for
            # the window and must find the ledger entries there. The
            # tag dedups a re-delivered debt chunk at the successor.
            led = self.reservations
            if led is not None:
                res_rows, debt_rows = led.export_rows(
                    lambda _t: True, tag=f"drain:{target_epoch}")
                if res_rows or debt_rows:
                    export = dict(export)
                    export["reservations"] = res_rows
                    export["debts"] = debt_rows
            # Gate on BEFORE the source debit lands: from here until
            # aclose, admission serves only the envelope the export
            # withheld — late requests cannot spend balances the
            # successor already received.
            self._drain_envelope = env
            self._drain_successor = successor
            self._drain_deadline = time.monotonic() + window_s
            await placement.debit_source(self.store, entries,
                                         envelope_fraction,
                                         keep_envelope=True)
            push = getattr(successor, "migrate_push", None)
            rows = 0
            for bid, chunk in enumerate(placement.chunk_entries(export)):
                if callable(push):
                    rows += await push({"target_epoch": target_epoch,
                                        # Namespaced like the cluster's
                                        # per-source batch ids: drain
                                        # pushes must never collide with
                                        # a concurrent migration's.
                                        "batch": (0xD << 24) | bid,
                                        "entries": chunk})
                else:
                    rows += await placement.import_entries(successor,
                                                           chunk)
            if self.liveconfig.active:
                # The gates ride along: a successor serving the shipped
                # (already-rebased) state without the forwarding rules
                # would silently re-open every retired config
                # init-on-miss — the exact over-admission this shutdown
                # exists to prevent. Adopt is idempotent + version-
                # monotonic, so a coordinator-side replay is harmless.
                ann = getattr(successor, "config_announce", None)
                if callable(ann):
                    try:
                        await ann({"adopt":
                                   self.liveconfig.snapshot_payload()})
                        out["config_version"] = self.liveconfig.version
                    except Exception as exc:
                        log.error_evaluating_kernel(exc)
                        out["config_forward_error"] = repr(exc)
            out["shipped_rows"] = rows
            # Linger for the rest of the handoff window serving the
            # envelope: in-flight and stale-mapped clients get bounded
            # answers instead of connection resets, and the window is
            # the documented epsilon term — the same accounting as a
            # migration's parked keys (DESIGN.md §13).
            linger = self._drain_deadline - time.monotonic()
            if linger > 0:
                await asyncio.sleep(linger)
            out["envelope_decisions"] = env.decisions
        elif self.snapshot_path is not None:
            out["checkpoint"] = await self._final_checkpoint()
        await self.aclose()
        return out

    def _audit_json(self, req: "dict | None" = None) -> str:
        """OP_AUDIT / ``GET /audit`` body: the conservation snapshot,
        plus the newest ``req["bundles"]`` black-box incident bundles
        when asked (bundles carry whole flight/trace windows — heavy,
        so they ship only on request)."""
        import json

        out: dict = {"enabled": self.auditor is not None}
        if self.auditor is not None:
            out.update(self.auditor.snapshot())
            n = int((req or {}).get("bundles", 0) or 0)
            if n > 0:
                out["bundles"] = list(self.auditor.bundles)[-n:]
        return json.dumps(out, default=repr)

    def _stats_json(self) -> str:
        import json

        if self._native is not None:
            # The C front-end owns the sockets and the hot-path histogram
            # (arrival→completion measured in C, same 82-bucket
            # convention); passthrough ops served here also count into
            # its requests_served via fe_send.
            hist = self._native.latency_histogram()
            requests, connections, batches = self._native.counts()
            payload = {
                "connections_served": connections,
                "requests_served": requests,
                "serving_p50_ms": hist.p50 * 1e3,
                "serving_p99_ms": hist.p99 * 1e3,
                "serving_samples": hist.total,
                "native_frontend": True,
                "batches_flushed": batches,
            }
            tier0 = self._native.tier0_stats()
            if tier0 is not None:
                payload["tier0"] = tier0
            bulk = self._native.bulk_stats()
            if bulk is not None:
                payload["native_bulk"] = bulk
            shards = self._native.shard_stats()
            if shards is not None:
                # Per-shard breakdown beside the merged gauges above
                # (which stay the whole-node sums — the invariant
                # sum(shards[*].x) == merged x is test-pinned).
                payload["fe_shards"] = len(shards)
                payload["shards"] = shards
            transport = self._native.transport_stats()
            if transport is not None and transport["mode"] != "epoll":
                # Only when uring was requested: the epoll lane's
                # OP_STATS shape is pinned (and the parity contract
                # says the transport must be invisible there).
                payload["fe_transport"] = transport
        else:
            payload = {
                "connections_served": self.connections_served,
                "requests_served": self.requests_served,
                "serving_p50_ms": self.serving_latency.p50 * 1e3,
                "serving_p99_ms": self.serving_latency.p99 * 1e3,
                "serving_samples": self.serving_latency.total,
            }
        payload["requests_shed"] = self.requests_shed
        # The destructive-reset tripwire (utils/metrics.py): the
        # serving histogram counts its resets, whoever triggered them
        # (the OP_STATS flag path resets it unconditionally, direct
        # embedder resets count too).
        payload["stats_resets"] = self.serving_latency.resets
        metrics = getattr(self.store, "metrics", None)
        if metrics is not None:
            payload["store"] = metrics.snapshot()
        # Per-stage decomposition: "serving p99 = queue + flush + reply"
        # as a scrape, not a bench-time inference.
        stages: dict = {}

        def stage(name: str, hist: "LatencyHistogram | None") -> None:
            if hist is not None and hist.total:
                stages[name] = {"p50_ms": hist.p50 * 1e3,
                                "p99_ms": hist.p99 * 1e3,
                                "samples": hist.total}

        stage("queue", getattr(metrics, "queue_latency", None))
        stage("flush", getattr(metrics, "flush_latency", None))
        stage("reply", self.reply_latency)
        if self._native is not None:
            for name, hist in (self._native.stage_histograms()
                               or {}).items():
                stage(name, hist)
        if stages:
            payload["stages"] = stages
        if self.placement.active:
            payload["placement"] = self.placement.stats()
        if self.liveconfig.active or self.liveconfig.version:
            payload["config"] = self.liveconfig.stats()
        if self._snapshot_chain is not None:
            payload["snapshot_chain"] = self._snapshot_chain.stats()
            dirty = getattr(self.store, "dirty_stats", None)
            if callable(dirty):
                payload["snapshot_chain"]["dirty"] = dirty()
        if self.heavy_hitters is not None:
            payload["hot_keys"] = self.heavy_hitters.snapshot()
        if (self.token_velocity is not None
                and self.token_velocity.observed_tokens > 0):
            payload["token_velocity"] = self.token_velocity.snapshot()
        if self.reservations is not None and self.reservations.active:
            # stats() piggybacks one TTL-expiry pass — a scraped-but-
            # idle server still auto-settles dead clients' holds.
            payload["reservations"] = self.reservations.stats()
        # Goodput-under-overload plane (docs/DESIGN.md §24). Emitted
        # once any deadline/attempt-stamped traffic or gate has left a
        # mark (or a gate is armed) so the pinned idle OP_STATS shape
        # is untouched; the controller scrape treats a missing section
        # as all-zeros.
        goodput = self._goodput_numeric_stats()
        retry = self._retry_numeric_stats()
        if any(goodput.values()) or self.doomed_gate_enabled:
            payload["goodput"] = goodput
        if any(retry.values()) or self.retry_shed_enabled:
            payload["retry"] = retry
        if self.federation is not None and self.federation.active:
            # stats() piggybacks one monotonic-expiry pass — a
            # scraped-but-idle home still expires unrenewed leases.
            payload["federation"] = self.federation.stats()
        if self.federation_agent is not None:
            payload["federation_region"] = self.federation_agent.stats()
        if self.flight_recorder is not None:
            payload["flight_recorder"] = self.flight_recorder.snapshot()
        if self.tracer.enabled:
            payload["tracing"] = self.tracer.snapshot()
        if self.controller is not None:
            payload["controller"] = self.controller.stats()
        if self.auditor is not None:
            payload["audit"] = self.auditor.snapshot()
        return json.dumps(payload)

    async def aclose(self) -> None:
        if self._audit_task is not None:
            self._audit_task.cancel()
            try:
                await self._audit_task
            except asyncio.CancelledError:
                pass
            self._audit_task = None
        if self._metrics_server is not None:
            self._metrics_server.close()
            await self._metrics_server.wait_closed()
            self._metrics_server = None
        if self._native is not None:
            await self._native.aclose()
            self._native = None
            return
        if self._server is not None:
            self._server.close()
        # Cancel live connection handlers BEFORE wait_closed(): since
        # Python 3.12 wait_closed() waits for handler tasks too, so a
        # server with connected clients would deadlock shutdown.
        for t in list(self._conn_tasks):
            t.cancel()
        if self._conn_tasks:
            await asyncio.gather(*list(self._conn_tasks),
                                 return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    async def __aenter__(self) -> "BucketStoreServer":
        await self.start()
        return self

    async def __aexit__(self, *exc: object) -> None:
        await self.aclose()


def main(argv: list[str] | None = None) -> None:
    """Run a store server from the console — the deployment unit that plays
    the Redis process's role on the TPU host:

        python -m distributedratelimiting.redis_tpu.runtime.server --port 6380
    """
    import argparse

    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()

    parser = argparse.ArgumentParser(description="TPU bucket-store server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=6380)
    parser.add_argument("--backend", choices=("device", "mesh", "inprocess"),
                        default="device",
                        help="device = single-chip TPU store; mesh = "
                        "key-sharded over every visible chip (the "
                        "pod-slice deployment); inprocess = pure-Python "
                        "store (CPU baseline / tests)")
    parser.add_argument("--slots", type=int, default=2**17,
                        help="table slots (device backend) or per-shard "
                        "slots (mesh backend)")
    parser.add_argument("--directory", choices=("host", "fp"),
                        default="host",
                        help="key-directory home for the device and mesh backends: "
                        "host = native C++ host table (default); fp = "
                        "device-resident fingerprint directory (in-kernel "
                        "probe/insert — see docs/OPERATIONS.md §2)")
    parser.add_argument("--sync-cadence", choices=("batch", "launch"),
                        default="batch",
                        help="global-tier psum cadence for the mesh "
                        "backend's sharded bucket tiers: batch = one "
                        "collective per scanned batch; launch = one per "
                        "launch (~+22%% bulk throughput, counter "
                        "staleness bounded by one launch's span — "
                        "docs/OPERATIONS.md §3)")
    parser.add_argument("--snapshot-path", default=None,
                        help="checkpoint file for OP_SAVE (≙ Redis BGSAVE "
                        "dump path); if it exists at startup, the store "
                        "restores from it (any .delta.* chain beside it "
                        "is applied too)")
    parser.add_argument("--snapshot-incremental", action="store_true",
                        help="OP_SAVE writes v4 delta checkpoints "
                        "against the previous save instead of full "
                        "files (base + bounded chain + compaction — "
                        "docs/OPERATIONS.md §10); requires "
                        "--snapshot-path")
    parser.add_argument("--drain-to", default=None, metavar="HOST:PORT",
                        help="on SIGTERM, ship the whole keyspace's "
                        "state to the successor server at this address "
                        "through the migration handoff lane before "
                        "exiting (drain-and-handoff shutdown); without "
                        "it SIGTERM writes a final checkpoint to "
                        "--snapshot-path when one is configured")
    parser.add_argument("--sweep-period", type=float, default=0.0,
                        help="active TTL-expiry period in seconds "
                        "(0 = on-demand sweeps only; device backend only)")
    parser.add_argument("--expect-placement-epoch", type=int, default=None,
                        help="refuse a startup snapshot whose recorded "
                        "placement epoch differs (typed mismatch → "
                        "init-on-miss): a node rejoining a resharded "
                        "cluster must not serve key memberships from a "
                        "retired epoch (docs/OPERATIONS.md §9)")
    parser.add_argument("--auth-token", default=None,
                        help="shared secret; when set, clients must HELLO "
                        "with it before any other op (≙ Redis AUTH)")
    parser.add_argument("--native-frontend", action="store_true",
                        help="serve sockets from the C++ epoll front-end "
                        "(native/frontend.cc): per-request frames batch "
                        "in C and reach Python once per flush — lifts "
                        "the per-request serving ceiling ~an order of "
                        "magnitude per core (docs/OPERATIONS.md)")
    parser.add_argument("--fe-max-batch", type=int, default=4096,
                        help="native front-end: max per-request frames "
                        "per micro-batch flush")
    parser.add_argument("--fe-deadline-us", type=int, default=300,
                        help="native front-end: flush deadline for the "
                        "oldest pending request, microseconds")
    parser.add_argument("--fe-tier0", action="store_true",
                        help="native front-end: enable the tier-0 "
                        "admission cache — hot ACQUIRE keys with "
                        "confident headroom decide locally in the C "
                        "epoll loop and reconcile via an async bulk "
                        "debit; over-admission bounded by the documented "
                        "epsilon (docs/OPERATIONS.md §3)")
    parser.add_argument("--fe-tier0-sync-ms", type=float, default=20.0,
                        help="tier-0 sync pump cadence, milliseconds")
    parser.add_argument("--fe-tier0-min-budget", type=float, default=64.0,
                        help="tier-0: smallest local budget worth "
                        "hosting; smaller buckets stay exact")
    parser.add_argument("--fe-tier0-fraction", type=float, default=0.5,
                        help="tier-0: fraction of the last-synced "
                        "balance granted as local headroom")
    parser.add_argument("--fe-shards", type=int, default=1,
                        help="native front-end: number of epoll shards "
                        "accepting on SO_REUSEPORT listeners bound to "
                        "the one port (kernel-level accept balancing). "
                        "1 = the single-listener posture; dozens-of-"
                        "cores nodes want one shard per serving core "
                        "(docs/OPERATIONS.md §12)")
    parser.add_argument("--fe-pin-shards", action="store_true",
                        help="native front-end: pin shard i's IO thread "
                        "to CPU i mod nproc (combine with numactl/"
                        "taskset for NUMA placement)")
    parser.add_argument("--fe-uring", default=None,
                        choices=["off", "on", "sqpoll"],
                        help="native front-end transport: 'on' serves "
                        "each shard's IO from an io_uring ring "
                        "(multishot accept/recv, linked send, provided "
                        "buffers); 'sqpoll' adds a kernel submission "
                        "poller so a hot shard submits without any "
                        "syscall. Default defers to DRL_TPU_URING (off "
                        "when unset); shards fall back to epoll loudly "
                        "when the kernel or seccomp refuses "
                        "(docs/OPERATIONS.md §17)")
    parser.add_argument("--no-uring", action="store_true",
                        help="force the epoll transport regardless of "
                        "--fe-uring/DRL_TPU_URING (the same kill switch "
                        "as DRL_TPU_NO_URING=1)")
    parser.add_argument("--no-fe-bulk", action="store_true",
                        help="disable the native bulk lane: "
                        "OP_ACQUIRE_MANY frames fall back to the Python "
                        "passthrough path instead of parsing, tier-0-"
                        "deciding, and encoding RESP_BULK in C "
                        "(docs/OPERATIONS.md §3)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="serve the OpenMetrics exposition over HTTP "
                        "on this port (GET /metrics; 0 picks a free "
                        "port). The same text is always available on the "
                        "wire as OP_METRICS.")
    parser.add_argument("--flight-dir", default=None,
                        help="flight-recorder dump directory (default "
                        "$DRL_TPU_FLIGHT_DIR or the system tempdir)")
    parser.add_argument("--no-observability", action="store_true",
                        help="disable the observability plane (heavy-"
                        "hitter telemetry + flight recorder); stage "
                        "latency stamps and OP_STATS remain")
    parser.add_argument("--trace", action="store_true",
                        help="enable distributed tracing: sampled "
                        "requests record span trees across every hop "
                        "(wire, dispatch, batcher, kernel launch, "
                        "tier-0), exported as Perfetto-loadable JSON on "
                        "GET /traces and the OP_TRACES wire op "
                        "(docs/OPERATIONS.md §6)")
    parser.add_argument("--trace-sample", type=float, default=0.01,
                        help="head-sampling rate: fraction of new "
                        "traces recorded at all (non-sampled requests "
                        "take the allocation-free null path)")
    parser.add_argument("--trace-latency-ms", type=float, default=50.0,
                        help="tail-sampling latency threshold: recorded "
                        "traces with any span at/above this are always "
                        "kept (denied/queued/error/degraded always keep "
                        "regardless)")
    parser.add_argument("--trace-buffer", type=int, default=256,
                        help="bounded in-memory kept-trace buffer "
                        "(oldest evicted first)")
    parser.add_argument("--controller", default=None,
                        metavar="HOST:PORT[,HOST:PORT...]",
                        help="arm the autonomous control plane: run the "
                        "reconciliation loop (runtime/controller.py) "
                        "inside this server process over the given "
                        "fleet (comma-separated store addresses — "
                        "include this node's own to reconcile it). The "
                        "controller scrapes the fleet's OP_STATS plane "
                        "every tick, derives rates from counter deltas "
                        "(never reset=True), and autonomously splits "
                        "hot-cost keys, rebalances, drains/rejoins "
                        "breaker-dead nodes, and steps the shed ladder; "
                        "every action is a flight-recorder frame + "
                        "drl_controller_* series (docs/OPERATIONS.md "
                        "§13)")
    parser.add_argument("--controller-tick-ms", type=float, default=500.0,
                        help="controller reconciliation cadence")
    parser.add_argument("--controller-dry-run", action="store_true",
                        help="controller decides and logs intended "
                        "actions without executing — the recommended "
                        "first rollout posture (docs/OPERATIONS.md §13)")
    parser.add_argument("--controller-token-rate", type=float,
                        default=None,
                        help="sustainable fleet admitted-tokens/sec for "
                        "the controller's shed ladder (unset disarms "
                        "the shed actuator; membership/split actuators "
                        "stay armed). NOTE: shed actuation needs "
                        "admission gateways (AdmissionPolicy "
                        "shed_targets), which live client-side — a "
                        "server-embedded controller records shed "
                        "decisions as outcome=noop and exports the "
                        "decided level for gateways to poll "
                        "(docs/OPERATIONS.md §13)")
    args = parser.parse_args(argv)
    if args.fe_tier0 and not args.native_frontend:
        parser.error("--fe-tier0 requires --native-frontend (the tier-0 "
                     "admission cache lives inside the C front-end)")
    if args.fe_shards != 1 and not args.native_frontend:
        parser.error("--fe-shards requires --native-frontend (the epoll "
                     "shards ARE the C front-end)")
    if args.fe_uring in ("on", "sqpoll") and not args.native_frontend:
        parser.error("--fe-uring requires --native-frontend (the uring "
                     "transport lives under the C front-end's shards)")
    if args.no_uring:
        args.fe_uring = "off"
    if args.snapshot_incremental and not args.snapshot_path:
        parser.error("--snapshot-incremental requires --snapshot-path "
                     "(there is no chain without a base file)")
    if (args.controller_dry_run or args.controller_token_rate
            is not None) and not args.controller:
        parser.error("--controller-dry-run/--controller-token-rate "
                     "require --controller (there is no loop to "
                     "configure)")
    if args.controller_token_rate is not None \
            and args.controller_token_rate <= 0:
        parser.error("--controller-token-rate must be positive")

    async def serve() -> None:
        if args.backend == "device":
            if args.directory == "fp":
                from distributedratelimiting.redis_tpu.runtime.fp_store import (
                    FingerprintBucketStore,
                )

                store: BucketStore = FingerprintBucketStore(
                    n_slots=args.slots)
            else:
                from distributedratelimiting.redis_tpu.runtime.store import (
                    DeviceBucketStore,
                )

                store = DeviceBucketStore(n_slots=args.slots)
        elif args.backend == "mesh":
            from distributedratelimiting.redis_tpu.parallel.mesh_store import (
                MeshBucketStore,
            )

            store = MeshBucketStore(per_shard_slots=args.slots,
                                    directory=args.directory,
                                    sync_cadence=args.sync_cadence)
        else:
            from distributedratelimiting.redis_tpu.runtime.store import (
                InProcessBucketStore,
            )

            store = InProcessBucketStore()
        if args.snapshot_path:
            import os

            from distributedratelimiting.redis_tpu.runtime import checkpoint

            if os.path.exists(args.snapshot_path):
                try:
                    # Chain-aware: applies any .delta.* files beside the
                    # base (exactly load_snapshot when there are none).
                    deltas = checkpoint.load_snapshot_chain(
                        store, args.snapshot_path,
                        expected_placement_epoch=(
                            args.expect_placement_epoch))
                except checkpoint.SnapshotCorruptError as exc:
                    # Documented init-on-miss fallback: a torn snapshot
                    # (or broken delta chain — SnapshotChainError folds
                    # in here) must not keep the store down — serve
                    # fresh (state self-heals) and say so loudly.
                    print(f"WARNING: ignoring corrupt snapshot: {exc}\n"
                          "starting with empty state (init-on-miss)",
                          flush=True)
                else:
                    print(f"restored snapshot from {args.snapshot_path}"
                          + (f" (+{deltas} deltas)" if deltas else ""),
                          flush=True)
        if args.sweep_period > 0 and hasattr(store, "start_sweeper"):
            store.start_sweeper(args.sweep_period)
        native_tier0 = False
        if args.fe_tier0:
            from distributedratelimiting.redis_tpu.runtime.native_frontend import (
                Tier0Config,
            )

            native_tier0 = Tier0Config(
                sync_interval_s=args.fe_tier0_sync_ms / 1e3,
                min_budget=args.fe_tier0_min_budget,
                budget_fraction=args.fe_tier0_fraction)
        server = BucketStoreServer(store, host=args.host, port=args.port,
                                   snapshot_path=args.snapshot_path,
                                   auth_token=args.auth_token,
                                   native_frontend=args.native_frontend,
                                   native_max_batch=args.fe_max_batch,
                                   native_deadline_us=args.fe_deadline_us,
                                   native_tier0=native_tier0,
                                   native_bulk=not args.no_fe_bulk,
                                   native_shards=args.fe_shards,
                                   native_pin_shards=args.fe_pin_shards,
                                   native_uring=args.fe_uring,
                                   metrics_port=args.metrics_port,
                                   observability=not args.no_observability,
                                   flight_dir=args.flight_dir,
                                   tracing_config={
                                       "enabled": True,
                                       "sample_rate": args.trace_sample,
                                       "latency_threshold_s":
                                           args.trace_latency_ms / 1e3,
                                       "max_traces": args.trace_buffer,
                                   } if args.trace else None,
                                   snapshot_incremental=(
                                       args.snapshot_incremental))
        host, port = await server.start()
        print(f"bucket-store server listening on {host}:{port}", flush=True)
        if server.metrics_port is not None:
            print(f"metrics exposition on "
                  f"http://{host}:{server.metrics_port}/metrics",
                  flush=True)
        controller_task = None
        controller_cluster = None
        if args.controller:
            from distributedratelimiting.redis_tpu.runtime.cluster import (
                ClusterBucketStore,
            )
            from distributedratelimiting.redis_tpu.runtime.controller import (
                Controller,
                ControllerConfig,
            )

            urls = [u.strip() for u in args.controller.split(",")
                    if u.strip()]
            controller_cluster = ClusterBucketStore(
                urls=urls, breaker=True, auth_token=args.auth_token,
                flight_recorder=server.flight_recorder)
            server.controller = Controller(
                controller_cluster,
                config=ControllerConfig(
                    tick_s=args.controller_tick_ms / 1e3,
                    dry_run=args.controller_dry_run,
                    token_rate_capacity=args.controller_token_rate),
                flight_recorder=server.flight_recorder)
            controller_task = asyncio.ensure_future(
                server.controller.run())
            print(f"controller reconciling {len(urls)} node(s) every "
                  f"{args.controller_tick_ms:g} ms"
                  + (" [dry-run]" if args.controller_dry_run else ""),
                  flush=True)
        # SIGTERM = planned shutdown: drain to the successor (or write
        # the final checkpoint) instead of dying with wiped state.
        import signal

        term = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, term.set)
            except (NotImplementedError, RuntimeError):
                pass  # non-main thread / platform without signals
        try:
            await term.wait()
            successor = None
            if args.drain_to:
                from distributedratelimiting.redis_tpu.runtime.remote import (
                    RemoteBucketStore,
                )

                successor = RemoteBucketStore(url=args.drain_to,
                                              auth_token=args.auth_token)
            print("SIGTERM: drain-and-handoff shutdown"
                  + (f" → {args.drain_to}" if args.drain_to else ""),
                  flush=True)
            summary = await server.shutdown(successor)
            print(f"shutdown complete: {summary}", flush=True)
            if successor is not None:
                await successor.aclose()
        finally:
            if controller_task is not None:
                server.controller.stop()
                controller_task.cancel()
                await asyncio.gather(controller_task,
                                     return_exceptions=True)
            if controller_cluster is not None:
                await controller_cluster.aclose()
            await server.aclose()
            await store.aclose()

    asyncio.run(serve())


if __name__ == "__main__":
    main()
