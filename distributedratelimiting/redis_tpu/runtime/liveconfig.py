"""Live limit mutation — versioned bucket/window config rewrites with
no restart and no dropped balances (ROADMAP item 5; docs/OPERATIONS.md
§10, DESIGN.md §13).

The reference's only way to change a limiter's ``(capacity, fill_rate)``
is a redeploy: state is wiped and self-heals init-on-miss — every limit
change is an over-admission event at production traffic. Here a config
change is a first-class, epoch-versioned control operation:

- A **rule** maps one retired config to its replacement:
  ``(kind, old_a, old_b) → (new_a, new_b)`` where ``kind`` is
  ``bucket`` / ``window`` / ``fwindow`` and ``(a, b)`` are the wire's
  config operands (capacity+rate, or limit+window_s). Rules commit at a
  **config version** that only moves forward — the placement plane's
  epoch-monotonic announce discipline (``OP_PLACEMENT_ANNOUNCE``), so
  ``OP_CONFIG`` is application-idempotent and post-send-retry-safe.
- Commit is **two-phase per node**: ``prepare`` stages the rule
  (validated, no behavior change — any failure aborts the whole
  mutation cleanly back to the old version), ``commit`` flips the
  serving gate and *rebases* the state. The coordinator
  (:meth:`~.cluster.ClusterBucketStore.mutate_config`) drives all nodes
  under its membership lock, commit order first-node → rest — the
  placement plane's dst→rest discipline.
- The **rebase** ships balances through the existing saturating
  ``debit_many`` kernel: every key of the old table re-homes into the
  (fresh, init-on-miss-full) new table debited by what it had already
  *spent* — ``max(0, old_cap − tokens)`` — so device stores need no
  slot surgery and a consumed budget stays consumed across the
  mutation. Windows replay their current-window count. Saturating by
  construction, the rebase can only under-admit, never over-admit.
- **Stale clients chase one routable error**: a request carrying a
  retired config answers ``config moved: {json}`` (the MOVED-redirect
  posture — the store is untouched, so the re-send is not a replay);
  the client learns the forwarding rule, re-sends once with the new
  operands, and caches the translation for every later call.

The over-admission bound: the gate flips BEFORE the old table is
exported, so post-flip traffic lands on the new table only; requests
already in flight past the gate when it flips are bounded by the
serving pipeline's in-flight depth — the same epsilon family as the
handoff window (DESIGN.md §13 derives the envelope).
"""

from __future__ import annotations

import asyncio
import json
import math
from typing import Callable, Mapping

from distributedratelimiting.redis_tpu.runtime import wire

__all__ = ["ConfigState", "ConfigRule", "StaleConfigError",
           "ConfigError", "CONFIG_MOVED_PREFIX", "KINDS",
           "OP_KINDS", "BULK_KINDS"]

#: Stable prefix of the routable "retired config" error — clients detect
#: it with a substring match (the placement MOVED posture) and re-send
#: with the rule's new operands instead of failing the caller. The JSON
#: payload after the prefix is the rule itself.
CONFIG_MOVED_PREFIX = "config moved"

#: Config families a rule may rewrite. Semaphore limits are deliberately
#: excluded: a semaphore's limit is per-call state, not table identity —
#: callers change it by passing a new limit.
KINDS = ("bucket", "window", "fwindow")

#: The config kind each gated wire op's ``(a, b)`` belongs to — THE one
#: table every lane routes through (server dispatch, native batch lane,
#: client translation); a copy per lane is exactly the drift a future
#: op would slip past. PEEK gates too: a balance probe against a
#: retired table would report a number nobody serves from anymore.
OP_KINDS = {wire.OP_ACQUIRE: "bucket", wire.OP_WINDOW: "window",
            wire.OP_FWINDOW: "fwindow", wire.OP_PEEK: "bucket"}

#: Bulk-frame kind bits → config kind (the frame-level gate: one
#: ``(kind, a, b)`` decides a whole ACQUIRE_MANY frame).
BULK_KINDS = {wire.BULK_KIND_BUCKET: "bucket",
              wire.BULK_KIND_WINDOW: "window",
              wire.BULK_KIND_FWINDOW: "fwindow"}


class ConfigError(RuntimeError):
    """Config control-plane failure (validation, rebase) — the mutation
    aborted cleanly at the old version."""


class StaleConfigError(ConfigError):
    """The announced version is not the node's ``version + 1`` (prepare)
    or conflicts with an already-staged rule at the same version.
    Versions are monotonic; re-announcing the current state is
    idempotent, going backwards is a protocol error."""


class ConfigRule:
    """One committed (or staged) config rewrite."""

    __slots__ = ("kind", "old", "new")

    def __init__(self, kind: str, old: "tuple[float, float]",
                 new: "tuple[float, float]") -> None:
        if kind not in KINDS:
            raise ConfigError(f"unknown config kind {kind!r}")
        self.kind = kind
        self.old = (float(old[0]), float(old[1]))
        self.new = (float(new[0]), float(new[1]))
        if self.old == self.new:
            raise ConfigError("config rule rewrites a config to itself")
        for a, b in (self.old, self.new):
            if not (math.isfinite(a) and math.isfinite(b)) or a <= 0:
                raise ConfigError(
                    f"config operands must be finite with a > 0: ({a}, {b})")

    def to_dict(self) -> dict:
        return {"kind": self.kind, "old": list(self.old),
                "new": list(self.new)}

    @classmethod
    def from_dict(cls, data: Mapping) -> "ConfigRule":
        return cls(data["kind"], tuple(data["old"]), tuple(data["new"]))

    def __eq__(self, other) -> bool:
        return (isinstance(other, ConfigRule) and self.kind == other.kind
                and self.old == other.old and self.new == other.new)

    def __repr__(self) -> str:
        return f"ConfigRule({self.kind}, {self.old} -> {self.new})"


def moved_message(kind: str, old: "tuple[float, float]",
                  new: "tuple[float, float]", version: int) -> str:
    """The routable retired-config error text: stable prefix + the rule
    as JSON, so the client parses operands instead of scraping prose."""
    return CONFIG_MOVED_PREFIX + ": " + json.dumps(
        {"kind": kind, "old": list(old), "new": list(new),
         "version": int(version)})


def parse_moved(message: str) -> "tuple[str, tuple, tuple, int] | None":
    """Inverse of :func:`moved_message`; ``None`` when the message is
    not a config-moved error (or its payload is unreadable — a client
    must fail the call rather than guess operands)."""
    i = message.find(CONFIG_MOVED_PREFIX)
    if i < 0:
        return None
    try:
        data = json.loads(message[i + len(CONFIG_MOVED_PREFIX) + 1:])
        old = (float(data["old"][0]), float(data["old"][1]))
        new = (float(data["new"][0]), float(data["new"][1]))
        return str(data["kind"]), old, new, int(data["version"])
    except (ValueError, KeyError, IndexError, TypeError):
        return None


class ConfigState:
    """A serving node's live-config half: the committed forwarding rules
    plus one staged (prepared, uncommitted) mutation. Dormant — zero
    serving cost — until the first rule commits (``active`` is a plain
    attribute read on the hot path)."""

    #: Committed rules kept in the forwarding map. Bounded like every
    #: other ledger: a fleet cycling thousands of configs through
    #: retirement keeps the newest rules (older retired configs then
    #: answer plain denials from their own — long-idle — tables).
    _MAX_RULES = 1 << 10

    def __init__(self) -> None:
        self.version = 0
        #: ``(kind, old_a, old_b) → (new_a, new_b, version)`` — THE
        #: serving gate's lookup. Chains compress on commit: committing
        #: B→C rewrites an existing A→B rule to A→C, so a twice-moved
        #: client chases one error, not one per hop.
        self.rules: dict[tuple, tuple[float, float, int]] = {}
        self._staged: "dict[int, ConfigRule]" = {}
        # Serializes prepare/commit/abort bodies: a commit's rebase
        # spans awaits (snapshot off-thread, debit through the store)
        # and a post-send retry must hit the idempotent no-op, not run
        # a second rebase.
        self._lock = asyncio.Lock()
        # Visible counters (OP_STATS "config" section + OpenMetrics).
        self.moved_errors = 0
        self.commits = 0
        self.aborts = 0
        self.adopts = 0
        self.stale_announces = 0
        self.rebased_rows = 0

    @property
    def active(self) -> bool:
        return bool(self.rules)

    # -- serving gate --------------------------------------------------------
    def forward(self, kind: str, a: float, b: float
                ) -> "tuple[float, float, int] | None":
        """The admission-path check: ``None`` (config current — the
        overwhelming steady state, one dict probe) or the committed
        ``(new_a, new_b, version)`` the caller must be redirected to."""
        return self.rules.get((kind, float(a), float(b)))

    def moved(self, kind: str, a: float, b: float,
              fwd: "tuple[float, float, int]") -> str:
        self.moved_errors += 1
        return moved_message(kind, (a, b), (fwd[0], fwd[1]), fwd[2])

    # -- control plane -------------------------------------------------------
    def snapshot_payload(self) -> dict:
        """The OP_CONFIG fetch reply: committed version + rules (staged
        mutations are invisible until commit, by design)."""
        return {"version": self.version,
                "rules": [{"kind": k[0], "old": [k[1], k[2]],
                           "new": [na, nb], "version": v}
                          for k, (na, nb, v) in sorted(self.rules.items())]}

    async def announce(self, payload: Mapping, store) -> int:
        """One OP_CONFIG control frame: ``{"prepare": rule, "version":
        v}`` stages, ``{"commit": v}`` flips the gate and rebases
        through ``store``, ``{"abort": v}`` drops the staged rule, and
        ``{"adopt": snapshot}`` installs another node's whole committed
        rule set WITHOUT rebasing — the restart-survival lane: a
        drained predecessor (or the coordinator's LB switch) hands the
        successor the gates, whose state already arrived rebased
        through the handoff. Every form is idempotent at its version; a
        stale version raises the typed, routable error. Returns the
        committed version."""
        async with self._lock:
            if "prepare" in payload:
                return self._prepare(int(payload["version"]),
                                     ConfigRule.from_dict(
                                         payload["prepare"]))
            if "commit" in payload:
                return await self._commit(int(payload["commit"]), store)
            if "abort" in payload:
                self._staged.pop(int(payload["abort"]), None)
                self.aborts += 1
                return self.version
            if "adopt" in payload:
                return self._adopt(payload["adopt"])
            if not payload:
                return self.version
            raise ConfigError(
                f"unknown OP_CONFIG form {sorted(payload)!r}")

    def _adopt(self, data: Mapping) -> int:
        version = int(data.get("version", 0))
        if version <= self.version:
            return self.version  # idempotent: stale/duplicate no-op
        rules: dict[tuple, tuple[float, float, int]] = {}
        for row in data.get("rules", ()):
            rule = ConfigRule.from_dict(row)  # validated, typed errors
            rules[(rule.kind, rule.old[0], rule.old[1])] = (
                rule.new[0], rule.new[1], int(row.get("version",
                                                      version)))
        self.rules = rules
        self.version = version
        self.adopts += 1
        return self.version

    def _prepare(self, version: int, rule: ConfigRule) -> int:
        if version <= self.version:
            self.stale_announces += 1
            raise StaleConfigError(
                f"stale config version {version} "
                f"(this node committed {self.version})")
        staged = self._staged.get(version)
        if staged is not None and staged != rule:
            # Two coordinators raced the same target version with
            # different rules: the second loses loudly (the placement
            # plane's conflicting-twin posture).
            self.stale_announces += 1
            raise StaleConfigError(
                f"conflicting config rule already staged at version "
                f"{version}; rebase and retry")
        self._staged[version] = rule
        return self.version

    async def _commit(self, version: int, store) -> int:
        if version <= self.version:
            return self.version  # idempotent: a retried commit no-ops
        rule = self._staged.pop(version, None)
        if rule is None:
            raise ConfigError(
                f"commit for unstaged config version {version}; "
                "prepare it first (or the abort already dropped it)")
        # Gate FIRST: from this instant every new request carrying the
        # old config answers the routable moved error and retries onto
        # the new table — the old table quiesces (up to the in-flight
        # pipeline depth, the documented epsilon) before it is exported.
        old_key = (rule.kind, rule.old[0], rule.old[1])
        self.rules[old_key] = (rule.new[0], rule.new[1], version)
        # Chain compression: A→old becomes A→new, one chase per client —
        # and a REVERT (new == A) deletes A's rule outright: A is
        # current again, and an A→A self-rule would brick the config
        # (forward() would bounce every A frame to itself, which the
        # client rightly refuses to chase).
        for k, (na, nb, _v) in list(self.rules.items()):
            if k != old_key and (k[0], na, nb) == old_key:
                if (k[1], k[2]) == rule.new:
                    del self.rules[k]
                else:
                    self.rules[k] = (rule.new[0], rule.new[1], version)
        while len(self.rules) > self._MAX_RULES:
            self.rules.pop(next(iter(self.rules)))
        self.version = version
        try:
            self.rebased_rows += await _rebase_state(store, rule)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            # The gate already flipped and the version advanced — the
            # new config SERVES (init-on-miss full, the reference's
            # whole posture for every deploy). A failed balance carry is
            # degraded, visible, and bounded by that posture; unwinding
            # the version here would split-brain the fleet's gates.
            from distributedratelimiting.redis_tpu.utils import log

            log.error_evaluating_kernel(exc)
        self.commits += 1
        return self.version

    def stats(self) -> dict:
        return {"version": self.version, "rules": len(self.rules),
                "staged": len(self._staged),
                "moved_errors": self.moved_errors,
                "commits": self.commits, "aborts": self.aborts,
                "adopts": self.adopts,
                "stale_announces": self.stale_announces,
                "rebased_rows": self.rebased_rows}


async def _rebase_state(store, rule: ConfigRule) -> int:
    """Carry the retired config's consumed budget into the new config's
    table through the store's public state lanes — the epoch-rebase
    step. Buckets: a fresh key under the new config is born full, so
    debiting ``max(0, old_cap − tokens)`` (clamped into the new
    capacity by the saturating kernel) lands ``new_cap − spent`` —
    consumed budget survives, headroom re-scales to the new cap.
    Windows: the current window's count replays (denials impossible to
    over-admit — the replay can only consume). Stores whose snapshot
    cannot enumerate keys (fingerprint directories) raise
    :class:`ConfigError` — the coordinator aborts rather than silently
    granting every key a fresh full budget."""
    from distributedratelimiting.redis_tpu.runtime import placement

    try:
        snap = await asyncio.to_thread(store.snapshot)
        entries = placement.extract_entries(snap, lambda _k: True)
    except asyncio.CancelledError:
        raise
    except Exception as exc:
        raise ConfigError(
            f"store cannot enumerate keys for a config rebase "
            f"({exc!r}); the mutation must abort — committing blind "
            "would reset every bucket to a full budget") from exc
    n = 0
    if rule.kind == "bucket":
        keys, amounts = [], []
        for key, cap, rate, tokens, _age in entries.get("buckets", ()):
            if (float(cap), float(rate)) != rule.old:
                continue
            spent = max(0.0, float(cap) - float(tokens))
            if spent > 0.0:
                keys.append(key)
                amounts.append(spent)
            n += 1
        if keys:
            await placement._debit_buckets(
                store, {rule.new: (keys, amounts)})
    else:
        interp_want = rule.kind == "window"
        new_limit, new_window = rule.new
        from distributedratelimiting.redis_tpu.ops import bucket_math

        old_wt = int(rule.old[1] * bucket_math.TICKS_PER_SECOND)
        for key, limit, wt, interp, _prev, curr, behind in \
                entries.get("windows", ()):
            if (float(limit), int(wt)) != (rule.old[0], old_wt) \
                    or bool(interp) != interp_want or behind != 0:
                continue
            # floor, not ceil: a fractional carry rounded UP past a
            # fractional limit would be DENIED by the replay — and a
            # denied replay records nothing, resetting the key to a
            # fresh full budget (over-admission from the very mechanism
            # meant to prevent it). Flooring under-carries by <1, the
            # conservative direction.
            count = int(math.floor(min(float(curr), new_limit)))
            if count > 0:
                if interp_want:
                    await store.window_acquire(key, count, new_limit,
                                               new_window)
                else:
                    await store.fixed_window_acquire(key, count,
                                                     new_limit,
                                                     new_window)
            n += 1
    return n


