"""Global quota federation — the WAN lease ledger (ROADMAP item 5;
docs/OPERATIONS.md §16, DESIGN.md §20).

The paper's ApproximateTokenBucket posture — decide locally at full
speed, reconcile with the authority asynchronously, bound the error —
has been applied at the tier-0 level (native front-end replicas vs the
store) and the cluster level (degraded envelopes vs the fleet) but
never ACROSS regions: a tenant budget held only within one cluster.
This module lifts the same composition one level up, to the shape
"Designing Scalable Rate Limiting Systems" (PAPERS.md) names as the
frontier past single-cluster designs:

- One **home** region hosts a :class:`FederationLedger` (ordinary
  ``BucketStore``-backed — the global tenant budget is a plain bucket
  in the home's store) that leases **slices** of each global tenant
  budget to regional clusters.
- A **slice is a live-mutable bucket config** ``(slice_cap,
  slice_rate)``: the regional cluster serves the tenant from it with
  the EXISTING data plane — same kernels, same tier-0, same envelopes;
  nothing below the config operands changes. Slice changes apply
  through the existing ``OP_CONFIG`` two-phase lane, so in-flight
  regional clients chase one routable "config moved" error exactly as
  they do for an operator limit change.
- Regions **renew** asynchronously over the WAN (``OP_FED_RENEW``),
  reporting their *monotonic* admitted-token total (the velocity
  tracker's ``totals()`` companion) and current demand; the home
  charges the delta against the global bucket through the saturating
  ``debit_many`` settle lane and re-sizes slices demand-proportionally
  — lending a low-demand region's freed share to a hot one at their
  next renews ("TokenScale"'s token-velocity signal driving the
  allocation).

**The robustness core — what happens when the WAN link fails:**

- Lease TTLs are measured in **monotonic local time** on BOTH ends:
  the home expires a lease on ITS monotonic clock, the region expires
  its copy on ITS OWN monotonic clock, and no absolute timestamp ever
  crosses the wire (``ttl_s`` is relative, the reservation-row-age
  discipline). WAN clock skew therefore cannot extend a lease — nor
  prematurely kill one (the ``utils/faults.py`` clock-skew seam is
  injected in tests, and drl-verify's ``fed-no-skew-extension``
  invariant holds the ``expire`` path to the monotonic clock
  statically).
- A region partitioned from the home keeps deciding locally from its
  current slice until the lease expires, then **degrades to a
  fair-share envelope** — the slice config is rewritten (same
  OP_CONFIG lane) to ``headroom_budget(slice_cap, fraction)`` refilled
  at ``fraction × slice_rate``: exactly the PR-5 breaker-quarantine /
  drain-window confidence policy, the same epsilon family. Never
  unlimited, never hard-down.
- The home **conservatively treats an unreachable region's slice as
  fully spent**: when a lease expires unrenewed, the unreported
  remainder of its entitlement is charged to the global bucket
  (:meth:`FederationLedger._conservative_charge`), so the global
  tenant bound Σ regional admits ≤ global cap + ε(RTT, lease_len)
  holds THROUGH the partition, not just after it.
- **Heal reconciles through the settle lane**: the partitioned
  region's next contact reports its true monotonic total; the home
  refunds the conservative over-charge via the saturating
  negative-debit (a refund can only under-credit — the safe
  direction) and any genuine overdraft (envelope grants past the
  charge) becomes per-(tenant, region) **debt** a new lease must pay
  down first — the PR-13 machinery, one mechanism for one job.

**Idempotency** (the OP_CONFIG / OP_RESERVE posture, post-send-retry-
safe end to end): ``lease`` replays a granted lease_id's recorded
grant; ``reclaim`` replays a recorded reclaim (at most one refund per
lease, audited); ``renew`` is absorbing — monotonic totals make a
replayed report a zero delta, and slice changes carry an epoch the
region adopts only forward (:meth:`RegionFederation._adopt`).

**The ε(RTT, lease_len) bound** (DESIGN.md §20 derives it): over a
window of length T, Σ regional admits ≤ global_cap + global_rate × T
+ ε where ε = Σ_regions [ report_staleness (≤ one renew period of
slice_rate, the tier-0 sync-staleness term with the WAN RTT folded
in) + partition envelope (headroom_budget(slice_cap, fraction) +
fraction × slice_rate × degraded_window) ] — every term is a knob the
operator already owns (:func:`federation_epsilon`).

Lease state **rides the v4 checkpoint chain**: the ledger attaches to
the home's store (``store.federation_ledger()``, the
``reservation_ledger`` pattern) and :mod:`~.checkpoint` snapshots /
restores its exported state beside the bucket tables — TTLs export as
remaining AGES and re-anchor against the restarted process's monotonic
clock, so a home crash/restart resumes every lease conservatively
(never extended)."""

from __future__ import annotations

import asyncio
import math
import time
from collections import OrderedDict
from typing import Callable, Mapping

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
)
from distributedratelimiting.redis_tpu.utils import faults, log

__all__ = [
    "FederationLedger", "RegionFederation", "Lease",
    "DEFAULT_LEASE_TTL_S", "DEFAULT_ENVELOPE_FRACTION",
    "DEFAULT_MIN_SHARE", "degraded_config", "federation_epsilon",
    "slice_applier",
]

#: Default lease term. Short enough that a partitioned region degrades
#: to its envelope within one operator glance; long enough that a WAN
#: renew every ``renew_fraction × ttl`` is control-plane cadence, not
#: data-plane load.
DEFAULT_LEASE_TTL_S = 10.0

#: Fair-share fraction of the degraded envelope — the SAME default as
#: the placement handoff envelope and the cluster's breaker-quarantine
#: fallback (one confidence-policy family, DESIGN.md §12/§20).
DEFAULT_ENVELOPE_FRACTION = 0.5

#: Smallest slice share a live region is ever squeezed to by the
#: demand-proportional sizing — a quiet region keeps a floor, so a
#: demand spike elsewhere can never zero it out (never hard-down).
DEFAULT_MIN_SHARE = 0.05

#: A slice resize below this relative change is suppressed — config
#: churn hysteresis: every resize is an OP_CONFIG mutation the region's
#: clients chase, so jittering demand must not thrash the gates.
DEFAULT_RESIZE_THRESHOLD = 0.2


def degraded_config(slice_cap: float, slice_rate: float,
                    fraction: float = DEFAULT_ENVELOPE_FRACTION
                    ) -> tuple[float, float]:
    """The partition-expiry envelope as a BUCKET CONFIG: a
    ``headroom_budget(slice_cap, fraction)`` burst (floored at one
    token — never hard-down) refilled at ``fraction × slice_rate`` —
    :func:`placement.envelope_step`'s exact arithmetic expressed as
    the ``(cap, rate)`` operands the existing data plane already
    serves, so degrading is just one more live config mutation."""
    cap = headroom_budget(slice_cap, fraction=fraction, min_budget=1.0)
    return (max(1.0, cap), max(0.0, slice_rate) * fraction)


def federation_epsilon(n_regions: int, slice_cap: float,
                       slice_rate: float, renew_period_s: float,
                       partition_s: float = 0.0,
                       fraction: float = DEFAULT_ENVELOPE_FRACTION
                       ) -> float:
    """Worst-case over-admission of the federated bound past
    ``global_cap + global_rate × T`` (module docstring; DESIGN.md §20
    derives it term by term): per region, one renew period of report
    staleness at the slice rate — the WAN edition of the tier-0
    sync-staleness term, with the RTT inside ``renew_period_s`` — plus,
    for a partition of length ``partition_s`` past lease expiry, the
    degraded envelope's burst and refill."""
    staleness = slice_rate * renew_period_s
    envelope = 0.0
    if partition_s > 0.0:
        env_cap, env_rate = degraded_config(slice_cap, slice_rate,
                                            fraction)
        envelope = env_cap + env_rate * partition_s
    return n_regions * (staleness + envelope)


class Lease:
    """One outstanding slice lease at the home ledger."""

    __slots__ = ("lease_id", "tenant", "region", "epoch", "share",
                 "slice_cap", "slice_rate", "expires_mono",
                 "last_report_mono", "reported_total", "demand",
                 "ttl_s")

    def __init__(self, lease_id: str, tenant: str, region: str,
                 epoch: int, share: float, slice_cap: float,
                 slice_rate: float, expires_mono: float,
                 last_report_mono: float, reported_total: float,
                 demand: float, ttl_s: float) -> None:
        self.lease_id = lease_id
        self.tenant = tenant
        self.region = region
        self.epoch = epoch
        self.share = share
        self.slice_cap = slice_cap
        self.slice_rate = slice_rate
        self.expires_mono = expires_mono
        self.last_report_mono = last_report_mono
        self.reported_total = reported_total
        self.demand = demand
        self.ttl_s = ttl_s

    def slice(self) -> tuple[float, float]:
        return (self.slice_cap, self.slice_rate)

    def to_row(self, now: float) -> dict:
        """Checkpoint row — ages, never absolute times (the two
        processes' clocks never compare; invariant 1)."""
        return {
            "lease_id": self.lease_id, "tenant": self.tenant,
            "region": self.region, "epoch": self.epoch,
            "share": self.share, "slice_cap": self.slice_cap,
            "slice_rate": self.slice_rate,
            "expires_in": max(0.0, self.expires_mono - now),
            "reported_in": max(0.0, now - self.last_report_mono),
            "reported_total": self.reported_total,
            "demand": self.demand, "ttl_s": self.ttl_s,
        }

    @classmethod
    def from_row(cls, row: Mapping, now: float) -> "Lease":
        return cls(str(row["lease_id"]), str(row["tenant"]),
                   str(row["region"]), int(row["epoch"]),
                   float(row["share"]), float(row["slice_cap"]),
                   float(row["slice_rate"]),
                   now + float(row.get("expires_in", 0.0)),
                   now - float(row.get("reported_in", 0.0)),
                   float(row.get("reported_total", 0.0)),
                   float(row.get("demand", 0.0)),
                   float(row.get("ttl_s", DEFAULT_LEASE_TTL_S)))


class _TenantPool:
    """One global tenant budget's federation state at the home."""

    __slots__ = ("cap", "rate", "leases", "epoch_seq")

    def __init__(self, cap: float, rate: float) -> None:
        self.cap = cap
        self.rate = rate
        self.leases: "dict[str, Lease]" = {}   # region → lease
        self.epoch_seq = 0

    def free_share(self, exclude: "str | None" = None) -> float:
        used = sum(l.share for r, l in self.leases.items()
                   if r != exclude)
        return max(0.0, 1.0 - used)


class FederationLedger:
    """The home side of the federation (module docstring): grants,
    renews, expires, and reclaims slice leases of global tenant
    budgets, charging reported (and conservatively presumed) spends
    against the home store's ordinary per-tenant buckets through the
    saturating ``debit_many`` lane. One asyncio lock serializes the
    control bodies (their dedup probes span store awaits — the
    placement ``_control_lock`` posture); :meth:`expire` is synchronous
    and piggybacks on every touch plus the stats scrape, keyed on the
    MONOTONIC clock only."""

    #: Bounded idempotency records (the reservations `_settled` cap
    #: posture): recorded grants by lease_id and recorded reclaims.
    _GRANTS_CAP = 4096
    _RECLAIMS_CAP = 4096

    def __init__(self, store, *,
                 default_ttl_s: float = DEFAULT_LEASE_TTL_S,
                 min_share: float = DEFAULT_MIN_SHARE,
                 resize_threshold: float = DEFAULT_RESIZE_THRESHOLD,
                 initial_share_fraction: float = 0.5,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 flight_recorder=None, velocity=None) -> None:
        if default_ttl_s <= 0:
            raise ValueError("default_ttl_s must be positive")
        if not 0.0 < min_share <= 1.0:
            raise ValueError("min_share must be in (0, 1]")
        if not 0.0 < initial_share_fraction <= 1.0:
            raise ValueError("initial_share_fraction must be in (0, 1]")
        self._store = store
        self.default_ttl_s = float(default_ttl_s)
        self.min_share = float(min_share)
        self.resize_threshold = float(resize_threshold)
        #: A NEW lease takes at most this fraction of the currently
        #: free pool: the first region to arrive must not grab the
        #: whole budget (later joiners would be denied until its next
        #: renew shrank it) — renews then converge every region to its
        #: demand-proportional share, which is where lending/borrowing
        #: actually happens.
        self.initial_share_fraction = float(initial_share_fraction)
        #: MONOTONIC lease clock — THE clock every expiry decision
        #: reads. ``wall`` exists for human-facing stats timestamps
        #: only and must never reach a TTL comparison (drl-verify's
        #: ``fed-no-skew-extension`` pins this statically; the
        #: clock-skew chaos tests pin it dynamically).
        self._clock = clock
        self._wall = wall
        self.flight_recorder = flight_recorder
        #: Optional TokenVelocity: reported regional spends feed it, so
        #: the home's drl_token_velocity reflects GLOBAL per-tenant
        #: spend across every region.
        self.velocity = velocity
        self._pools: "dict[str, _TenantPool]" = {}
        self._grants: "OrderedDict[str, dict]" = OrderedDict()
        self._reclaimed: "OrderedDict[str, dict]" = OrderedDict()
        #: Expired leases pending heal, by lease_id: the conservative
        #: charge stays reconcilable until the region reports its true
        #: total (bounded; oldest forfeited — their over-charge is
        #: never refunded, the conservative direction).
        self._expired: "OrderedDict[str, dict]" = OrderedDict()
        #: (tenant, region) → highest reported monotonic total. THE
        #: baseline that makes renew deltas correct ACROSS lease
        #: generations: a fresh lease after a heal (or a replacement)
        #: continues the region's counter instead of restarting at
        #: zero — restarting would re-charge everything the heal
        #: already reconciled (soak-caught double count). Rides the
        #: checkpoint with the leases.
        self._region_totals: "OrderedDict[tuple, float]" = OrderedDict()
        self._debts: "dict[tuple[str, str], float]" = {}
        self._lock = asyncio.Lock()
        # Visible counters (OP_STATS "federation" + drl_federation_*).
        # MONOTONIC — never cleared by stats(reset=True).
        self.leases_granted = 0
        self.lease_duplicates = 0
        self.lease_denied = 0
        self.renews = 0
        self.renew_unknown = 0
        self.resizes = 0
        self.reclaims = 0
        self.reclaim_duplicates = 0
        self.reclaim_unknown = 0
        self.leases_expired = 0
        self.heals = 0
        self.charged_tokens = 0.0
        self.conservative_tokens = 0.0
        self.refunded_tokens = 0.0
        self.debts_created = 0
        self.debt_tokens_created = 0.0
        self.debt_tokens_collected = 0.0
        self.restores = 0

    # -- introspection -------------------------------------------------------
    @property
    def active(self) -> bool:
        """True once the ledger has ever seen federation traffic
        (gates the OP_STATS section and the checkpoint section, so
        non-home servers keep their old shapes byte for byte)."""
        return bool(self._pools or self._grants or self._reclaimed
                    or self._debts)

    def outstanding_leases(self) -> int:
        return sum(len(p.leases) for p in self._pools.values())

    def shares(self) -> "list[tuple[str, str, float]]":
        """``(tenant, region, share)`` rows — the slice-utilization
        surface behind ``drl_federation_slice_share``."""
        return [(t, r, l.share)
                for t, p in sorted(self._pools.items())
                for r, l in sorted(p.leases.items())]

    def debts(self) -> "dict[tuple[str, str], float]":
        return dict(self._debts)

    def _set_region_total(self, tenant: str, region: str,
                          total: float) -> None:
        key = (tenant, region)
        cur = self._region_totals.get(key, 0.0)
        self._region_totals[key] = max(cur, float(total))
        self._region_totals.move_to_end(key)
        while len(self._region_totals) > self._GRANTS_CAP:
            self._region_totals.popitem(last=False)

    # -- store charging ------------------------------------------------------
    async def _charge(self, tenant: str, region: str, amount: float,
                      cap: float, rate: float) -> float:
        """Debit ``amount`` reported (or presumed) regional spend from
        the global tenant bucket; the part the bucket cannot cover
        becomes per-(tenant, region) debt. Returns the shortfall."""
        if amount <= 0:
            return 0.0
        self.charged_tokens += amount
        debit = getattr(self._store, "debit_many", None)
        if not callable(debit):   # pragma: no cover — every store has it
            return 0.0
        _rem, short = await debit([tenant], [amount], cap, rate)
        owed = float(short[0])
        if owed > 1e-9:
            key = (tenant, region)
            self._debts[key] = self._debts.get(key, 0.0) + owed
            self.debts_created += 1
            self.debt_tokens_created += owed
        return owed

    async def _refund(self, tenant: str, amount: float, cap: float,
                      rate: float) -> None:
        """Credit back an over-charge through the saturating
        negative-debit lane — the capacity clamp bounds any overshoot,
        so a refund can only under-credit (the PR-13 contract)."""
        if amount <= 0:
            return
        debit = getattr(self._store, "debit_many", None)
        if callable(debit):
            await debit([tenant], [-amount], cap, rate)
        self.refunded_tokens += amount

    async def _collect_debt(self, tenant: str, region: str,
                            cap: float, rate: float) -> float:
        """Pay down (tenant, region) debt from the global bucket; the
        remainder stays owed and blocks a new lease (the reservations
        debt-denial posture)."""
        key = (tenant, region)
        debt = self._debts.get(key, 0.0)
        if debt < 1.0:
            return debt
        debit = getattr(self._store, "debit_many", None)
        if not callable(debit):   # pragma: no cover
            return debt
        _rem, short = await debit([tenant], [debt], cap, rate)
        left = float(short[0])
        collected = debt - left
        if collected > 0:
            self.debt_tokens_collected += collected
        if left <= 1e-9:
            self._debts.pop(key, None)
            return 0.0
        self._debts[key] = left
        return left

    # -- monotonic expiry (sync; piggybacked on every touch) -----------------
    def _conservative_charge(self, lease: Lease) -> float:
        """What an unreachable region COULD have admitted since its
        last report: the full slice burst plus the slice rate over the
        unreported window — the fully-spent presumption the module
        docstring promises. An upper bound by construction, so heal's
        refund (conservative − true) is never negative."""
        window = max(0.0, lease.expires_mono - lease.last_report_mono)
        return lease.slice_cap + lease.slice_rate * window

    def expire(self, now: "float | None" = None) -> int:
        """Expire every lease whose TTL elapsed on the home's
        MONOTONIC clock (``self._clock`` — never ``self._wall``: a
        skewed wall clock must neither extend nor prematurely kill a
        lease). The expired lease's share returns to the pool and its
        conservative charge is recorded for the heal path; the store
        debit itself happens lazily at heal/stats time so this stays
        synchronous (the reservations ``expire`` posture). Returns the
        number expired."""
        now = self._clock() if now is None else now
        n = 0
        for tenant, pool in list(self._pools.items()):
            for region, lease in list(pool.leases.items()):
                if lease.expires_mono > now:
                    continue
                del pool.leases[region]
                charge = self._conservative_charge(lease)
                self.conservative_tokens += charge
                self._expired[lease.lease_id] = {
                    "tenant": tenant, "region": region,
                    "charge": charge, "charged": False,
                    "reported_total": lease.reported_total,
                    "cap": pool.cap, "rate": pool.rate,
                    "share": lease.share,
                }
                while len(self._expired) > self._RECLAIMS_CAP:
                    self._expired.popitem(last=False)
                self.leases_expired += 1
                n += 1
                if self.flight_recorder is not None:
                    self.flight_recorder.record(
                        "federation", event="lease_expired",
                        tenant=tenant, region=region,
                        lease_id=lease.lease_id,
                        conservative_charge=charge)
        return n

    async def _settle_expired(self) -> None:
        """Apply any pending conservative charges to the store (the
        async half of :meth:`expire`). Iterates a SNAPSHOT — the
        lock-free ``stats()`` → ``expire()`` path may insert/evict
        records while a charge awaits — and marks ``charged`` only
        AFTER the debit lands: a checkpoint cut at the await must
        never record a charge the bucket never saw (a restore would
        then refund it at heal — minting tokens), and a failed debit
        retries at the next touch (a double-applied retry at worst
        over-charges — the conservative direction)."""
        for rec in list(self._expired.values()):
            if rec["charged"]:
                continue
            await self._charge(rec["tenant"], rec["region"],
                               rec["charge"], rec["cap"], rec["rate"])
            rec["charged"] = True

    # -- demand-proportional slice sizing ------------------------------------
    def _target_share(self, pool: _TenantPool, region: str,
                      demand: float) -> float:
        """The requester's demand-proportional share. Only the
        REQUESTER's slice is resized at its own lease/renew — an
        absent region's slice is never shrunk in absentia (it may be
        partitioned and still serving from it; two-party consent, the
        conservative posture). Growth comes from the free pool."""
        demands = {r: max(0.0, l.demand)
                   for r, l in pool.leases.items()}
        demands[region] = max(0.0, demand)
        total = sum(demands.values())
        if total <= 0:
            target = 1.0 / max(1, len(demands))
        else:
            target = demands[region] / total
        target = max(self.min_share, target)
        if region not in pool.leases:
            return min(target, pool.free_share(exclude=region))
        # Growth is GRADUAL: one renew may borrow at most
        # initial_share_fraction of the free pool — a lone region
        # converges toward the whole budget geometrically instead of
        # grabbing it in one step, so a joining region always finds
        # room (shrinks apply in full — lending is immediate).
        current = pool.leases[region].share
        return min(target,
                   current + pool.free_share()
                   * self.initial_share_fraction)

    def _slice_of(self, pool: _TenantPool, share: float
                  ) -> tuple[float, float]:
        cap = max(1.0, math.floor(pool.cap * share))
        return (cap, pool.rate * share)

    # -- lease ---------------------------------------------------------------
    def _duplicate_lease(self, lease_id: str) -> "dict | None":
        """Recorded-grant replay — the OP_RESERVE duplicate-rid
        posture: a WAN retry of a granted lease must not re-size or
        re-debit anything."""
        return self._grants.get(lease_id)

    async def lease(self, req: Mapping) -> dict:
        """One OP_FED_LEASE body (wire.py documents the fields)."""
        region = str(req.get("region") or "")
        lease_id = str(req.get("lease_id") or "")
        tenant = str(req.get("tenant") or "")
        if not region or not lease_id or not tenant:
            raise ValueError(
                "fed lease requires region, lease_id, and tenant")
        cap = float(req.get("global_cap", 0.0))
        rate = float(req.get("global_rate", 0.0))
        if not math.isfinite(cap) or cap <= 0 or not math.isfinite(rate):
            raise ValueError("fed lease requires a finite global_cap "
                             "> 0 and a finite global_rate")
        demand = float(req.get("demand", 0.0))
        ttl = float(req.get("ttl_s") or self.default_ttl_s)
        async with self._lock:
            now = self._clock()
            self.expire(now)
            await self._settle_expired()
            dup = self._duplicate_lease(lease_id)
            if dup is not None:
                self.lease_duplicates += 1
                return dict(dup, duplicate=True)
            pool = self._pools.get(tenant)
            if pool is None:
                pool = self._pools[tenant] = _TenantPool(cap, rate)
            elif (pool.cap, pool.rate) != (cap, rate):
                raise ValueError(
                    f"global config mismatch for tenant {tenant!r}: "
                    f"ledger holds ({pool.cap}, {pool.rate}), lease "
                    f"asked ({cap}, {rate}) — one global truth per "
                    "tenant")
            debt = await self._collect_debt(tenant, region, cap, rate)
            if debt >= 1.0:
                self.lease_denied += 1
                return {"granted": False, "lease_id": lease_id,
                        "debt": debt, "duplicate": False}
            old = pool.leases.get(region)
            if old is not None:
                # A replacement lease (the region re-leased with a
                # fresh id while the home still held its old one —
                # heal raced the home expiry, or a region restarted):
                # the old lease's share returns, and the region's
                # monotonic-total BASELINE carries over — its next
                # renew's delta then covers the old lease's unreported
                # window exactly (charging conservatively here would
                # double-count it against that report; the new lease's
                # own expiry conservatism covers a region that
                # vanishes again).
                del pool.leases[region]
                self._set_region_total(tenant, region,
                                       old.reported_total)
            share = self._target_share(pool, region, demand)
            free = pool.free_share(exclude=region)
            # New-lease fairness: take at most initial_share_fraction
            # of the free pool (floored at min_share) — renews
            # converge everyone to demand-proportional from there.
            share = min(share, free,
                        max(self.min_share,
                            free * self.initial_share_fraction))
            if share < self.min_share:
                self.lease_denied += 1
                return {"granted": False, "lease_id": lease_id,
                        "debt": debt, "duplicate": False}
            pool.epoch_seq += 1
            slice_cap, slice_rate = self._slice_of(pool, share)
            # The report baseline CONTINUES the region's monotonic
            # counter across lease generations (see _region_totals).
            # When the ledger holds NO baseline for the pair (first
            # contact, or a bounded-LRU eviction of a long-idle
            # pair), the request's own reported total seeds it — a
            # zero seed would re-charge the region's whole lifetime
            # counter at its first renew (review-caught). A HELD
            # baseline always wins over the request: the gap between
            # them is unreported spend the next renew must charge.
            stored = self._region_totals.get((tenant, region))
            baseline = (float(req.get("total", 0.0))
                        if stored is None else stored)
            lease = Lease(lease_id, tenant, region, pool.epoch_seq,
                          share, slice_cap, slice_rate, now + ttl,
                          now, baseline, demand, ttl)
            pool.leases[region] = lease
            self.leases_granted += 1
            reply = {"granted": True, "lease_id": lease_id,
                     "epoch": lease.epoch, "share": share,
                     "slice": [slice_cap, slice_rate], "ttl_s": ttl,
                     "debt": debt, "duplicate": False}
            self._grants[lease_id] = reply
            while len(self._grants) > self._GRANTS_CAP:
                self._grants.popitem(last=False)
            if self.flight_recorder is not None:
                self.flight_recorder.record(
                    "federation", event="lease_granted", tenant=tenant,
                    region=region, lease_id=lease_id,
                    epoch=lease.epoch, share=round(share, 4),
                    slice_cap=slice_cap)
            return reply

    # -- renew ---------------------------------------------------------------
    async def renew(self, req: Mapping) -> dict:
        """One OP_FED_RENEW body: extend the lease TTL on the home's
        monotonic clock, charge the reported spend DELTA (monotonic
        totals — a replayed renew is a zero delta, which is the op's
        idempotency), update demand, and re-size the slice when the
        demand-proportional target moved past the resize threshold
        (new epoch; the region adopts it forward-only). A renew for an
        EXPIRED lease is the heal path: the true total reconciles the
        conservative charge (refund the difference, saturating) and
        the region is told to take a fresh lease."""
        region = str(req.get("region") or "")
        lease_id = str(req.get("lease_id") or "")
        tenant = str(req.get("tenant") or "")
        total = float(req.get("total", 0.0))
        demand = float(req.get("demand", 0.0))
        if not lease_id:
            raise ValueError("fed renew requires lease_id")
        async with self._lock:
            now = self._clock()
            self.expire(now)
            await self._settle_expired()
            pool = self._pools.get(tenant)
            lease = (pool.leases.get(region)
                     if pool is not None else None)
            if lease is None or lease.lease_id != lease_id:
                healed = await self._heal(lease_id, total)
                if healed is not None:
                    return healed
                self.renew_unknown += 1
                return {"outcome": "unknown", "charged": 0.0,
                        "refunded": 0.0, "debt": 0.0}
            self.renews += 1
            delta = max(0.0, total - lease.reported_total)
            # Charge BEFORE advancing the report baseline: if the
            # debit raises (device error, cancelled dispatch), the
            # baseline is unmoved and the region's retry re-charges
            # the same delta — advancing first would make the
            # absorbing retry's delta zero and lose the spend from
            # the global record entirely (review-caught). A debit
            # that executed before the raise double-charges on retry
            # at worst: over-charge, the conservative direction.
            owed = await self._charge(tenant, region, delta,
                                      pool.cap, pool.rate)
            lease.reported_total = max(lease.reported_total, total)
            self._set_region_total(tenant, region,
                                   lease.reported_total)
            lease.last_report_mono = now
            lease.expires_mono = now + lease.ttl_s
            lease.demand = demand
            if delta > 0 and self.velocity is not None:
                self.velocity.observe(tenant, delta)
            resized = self._maybe_resize(pool, lease, demand)
            reply = {"outcome": "ok", "epoch": lease.epoch,
                     "slice": [lease.slice_cap, lease.slice_rate],
                     "ttl_s": lease.ttl_s, "charged": delta,
                     "refunded": 0.0, "debt": owed,
                     "resized": resized}
            return reply

    def _maybe_resize(self, pool: _TenantPool, lease: Lease,
                      demand: float) -> bool:
        target = self._target_share(pool, lease.region, demand)
        current = lease.share
        if current > 0 and abs(target - current) / current \
                < self.resize_threshold:
            return False
        lease.share = target
        lease.slice_cap, lease.slice_rate = self._slice_of(pool,
                                                           target)
        pool.epoch_seq += 1
        lease.epoch = pool.epoch_seq
        self.resizes += 1
        if self.flight_recorder is not None:
            self.flight_recorder.record(
                "federation", event="lease_resized",
                tenant=lease.tenant, region=lease.region,
                lease_id=lease.lease_id, epoch=lease.epoch,
                share=round(target, 4), slice_cap=lease.slice_cap)
        return True

    async def _heal(self, lease_id: str, total: float
                    ) -> "dict | None":
        """Reconcile a late report against an expired lease's
        conservative charge (module docstring). Applies AT MOST once
        per lease — the record is popped — and the refund is
        ``conservative − true_unreported``, never negative (the charge
        was an upper bound); a true spend past the charge becomes
        debt through the ordinary charge lane."""
        rec = self._expired.pop(lease_id, None)
        if rec is None:
            return None
        self.heals += 1
        true_delta = max(0.0, total - rec["reported_total"])
        refund = max(0.0, rec["charge"] - true_delta)
        extra = max(0.0, true_delta - rec["charge"])
        was_charged = bool(rec["charged"])
        if not rec["charged"]:
            # Expiry recorded but its charge never reached the store
            # (heal won the race): charge the TRUE delta directly.
            owed = await self._charge(rec["tenant"], rec["region"],
                                      true_delta, rec["cap"],
                                      rec["rate"])
            rec["charged"] = True
            refund = 0.0
        else:
            # The over-charge cancels any DEBT the conservative charge
            # created first (the charge and its debt are one event —
            # refunding the bucket while the debt stood would both
            # block the region's next lease AND credit tokens back);
            # only the remainder is a bucket credit.
            key = (rec["tenant"], rec["region"])
            owed_now = self._debts.get(key, 0.0)
            cancel = min(refund, owed_now)
            if cancel > 0:
                left = owed_now - cancel
                if left <= 1e-9:
                    self._debts.pop(key, None)
                else:
                    self._debts[key] = left
                self.debt_tokens_collected += cancel
                self.refunded_tokens += cancel
                refund -= cancel
            await self._refund(rec["tenant"], refund, rec["cap"],
                               rec["rate"])
            owed = self._debts.get(key, 0.0)
            if extra > 0:
                owed = await self._charge(rec["tenant"], rec["region"],
                                          extra, rec["cap"],
                                          rec["rate"])
        # Baseline advances LAST: if a charge/refund above raised, the
        # stale baseline re-charges an already-conservatively-charged
        # window at worst — over-charge, the conservative direction.
        self._set_region_total(rec["tenant"], rec["region"],
                               max(total, rec["reported_total"]))
        total_refund = max(0.0, rec["charge"] - true_delta) \
            if was_charged else 0.0
        if true_delta > 0 and self.velocity is not None:
            self.velocity.observe(rec["tenant"], true_delta)
        if self.flight_recorder is not None:
            self.flight_recorder.record(
                "federation", event="heal", tenant=rec["tenant"],
                region=rec["region"], lease_id=lease_id,
                refunded=total_refund, debt=owed)
        return {"outcome": "expired", "charged": true_delta,
                "refunded": total_refund, "debt": owed}

    # -- reclaim -------------------------------------------------------------
    async def reclaim(self, req: Mapping) -> dict:
        """One OP_FED_RECLAIM body: the region returns its slice.
        Idempotent by lease id — a duplicate replays the recorded
        result with zero side effects (no second share free, no
        second charge or refund): the at-most-once audit
        tests/test_federation.py pins."""
        region = str(req.get("region") or "")
        lease_id = str(req.get("lease_id") or "")
        tenant = str(req.get("tenant") or "")
        total = float(req.get("total", 0.0))
        if not lease_id:
            raise ValueError("fed reclaim requires lease_id")
        async with self._lock:
            now = self._clock()
            self.expire(now)
            await self._settle_expired()
            recorded = self._reclaimed.get(lease_id)
            if recorded is not None:
                self.reclaim_duplicates += 1
                return dict(recorded, outcome="duplicate")
            pool = self._pools.get(tenant)
            lease = (pool.leases.get(region)
                     if pool is not None else None)
            if lease is None or lease.lease_id != lease_id:
                healed = await self._heal(lease_id, total)
                if healed is not None:
                    reply = dict(healed, outcome="reclaimed")
                    self._record_reclaim(lease_id, reply)
                    self.reclaims += 1
                    return reply
                self.reclaim_unknown += 1
                return {"outcome": "unknown", "charged": 0.0,
                        "refunded": 0.0, "debt": 0.0}
            delta = max(0.0, total - lease.reported_total)
            # Charge FIRST — before the lease leaves the pool and
            # before the baseline advance (the renew ordering
            # contract): a failed debit leaves the lease intact and
            # the retry re-charges instead of answering "unknown"
            # with the spend lost from the global record.
            owed = await self._charge(tenant, region, delta,
                                      pool.cap, pool.rate)
            del pool.leases[region]
            self._set_region_total(tenant, region,
                                   max(total, lease.reported_total))
            if delta > 0 and self.velocity is not None:
                self.velocity.observe(tenant, delta)
            self.reclaims += 1
            reply = {"outcome": "reclaimed", "charged": delta,
                     "refunded": 0.0, "debt": owed}
            self._record_reclaim(lease_id, reply)
            if self.flight_recorder is not None:
                self.flight_recorder.record(
                    "federation", event="reclaim", tenant=tenant,
                    region=region, lease_id=lease_id, charged=delta)
            return reply

    def _record_reclaim(self, lease_id: str, reply: dict) -> None:
        self._reclaimed[lease_id] = reply
        while len(self._reclaimed) > self._RECLAIMS_CAP:
            self._reclaimed.popitem(last=False)

    # -- checkpoint ride (runtime/checkpoint.py) -----------------------------
    def export_state(self) -> dict:
        """JSON-shaped lease state for the v4 checkpoint chain. TTLs
        export as remaining AGES against the ledger's monotonic clock
        — a restore re-anchors them, so a restart can only SHORTEN a
        lease's remaining term (conservative, never extended)."""
        now = self._clock()
        return {
            "pools": {
                t: {"cap": p.cap, "rate": p.rate,
                    "epoch_seq": p.epoch_seq,
                    "leases": [l.to_row(now)
                               for _r, l in sorted(p.leases.items())]}
                for t, p in sorted(self._pools.items())},
            "grants": dict(self._grants),
            "reclaimed": dict(self._reclaimed),
            "expired": {k: dict(v)
                        for k, v in self._expired.items()},
            "debts": [[t, r, amt]
                      for (t, r), amt in sorted(self._debts.items())],
            "region_totals": [
                [t, r, v]
                for (t, r), v in sorted(self._region_totals.items())],
        }

    def restore_state(self, state: Mapping) -> None:
        """Adopt a checkpointed lease state (the restart lane). The
        restored process re-anchors every TTL against ITS monotonic
        clock; idempotency records ride along so a post-restart WAN
        retry still dedups."""
        now = self._clock()
        self._pools = {}
        for tenant, pdata in (state.get("pools") or {}).items():
            pool = _TenantPool(float(pdata["cap"]),
                               float(pdata["rate"]))
            pool.epoch_seq = int(pdata.get("epoch_seq", 0))
            for row in pdata.get("leases", ()):
                lease = Lease.from_row(row, now)
                pool.leases[lease.region] = lease
            self._pools[str(tenant)] = pool
        self._grants = OrderedDict(
            (str(k), dict(v))
            for k, v in (state.get("grants") or {}).items())
        self._reclaimed = OrderedDict(
            (str(k), dict(v))
            for k, v in (state.get("reclaimed") or {}).items())
        self._expired = OrderedDict(
            (str(k), dict(v))
            for k, v in (state.get("expired") or {}).items())
        self._debts = {(str(t), str(r)): float(amt)
                       for t, r, amt in (state.get("debts") or ())}
        self._region_totals = OrderedDict(
            ((str(t), str(r)), float(v))
            for t, r, v in (state.get("region_totals") or ()))
        self.restores += 1

    # -- conservation (runtime/audit.py, DESIGN.md §22) ----------------------
    def conservation(self) -> dict:
        """The home's cover identity: everything the ledger has charged
        (or stands committed to charge — expired leases whose
        conservative debit hasn't landed yet), net of heal refunds,
        must COVER the regions' reported admissions:

            charged + pending_conservative − refunded  ≥  Σ reported

        ``residue`` is the left side minus the right. Positive residue
        is the documented conservative slack (fully-spent presumption,
        forfeited evictions) — tolerated by design. NEGATIVE residue
        means regions admitted tokens the global budget never paid
        for: global over-admission, the breach the audit plane pages
        on. The ε terms here are the conservative charges: budget =
        what every live lease could presume at expiry plus what
        already presumed, used = the presumed (pending) part — the
        ``source="federation"`` utilization gauge."""
        pending = sum(rec["charge"] for rec in self._expired.values()
                      if not rec["charged"])
        accounted = (self.charged_tokens + pending
                     - self.refunded_tokens)
        admitted = sum(self._region_totals.values())
        live_budget = sum(self._conservative_charge(lease)
                          for pool in self._pools.values()
                          for lease in pool.leases.values())
        return {
            "accounted": accounted,
            "admitted": admitted,
            "residue": accounted - admitted,
            "charged": self.charged_tokens,
            "pending_conservative": pending,
            "refunded": self.refunded_tokens,
            "epsilon_used": pending,
            "epsilon_budget": pending + live_budget,
        }

    # -- stats ---------------------------------------------------------------
    def numeric_stats(self) -> dict:
        """Flat numeric dict for ``register_numeric_dict`` — the
        ``drl_federation_*`` families."""
        return {
            "leases_granted": self.leases_granted,
            "lease_duplicates": self.lease_duplicates,
            "lease_denied": self.lease_denied,
            "renews": self.renews,
            "renew_unknown": self.renew_unknown,
            "resizes": self.resizes,
            "reclaims": self.reclaims,
            "reclaim_duplicates": self.reclaim_duplicates,
            "reclaim_unknown": self.reclaim_unknown,
            "leases_expired": self.leases_expired,
            "heals": self.heals,
            "charged_tokens": self.charged_tokens,
            "conservative_tokens": self.conservative_tokens,
            "refunded_tokens": self.refunded_tokens,
            "debts_created": self.debts_created,
            "debt_tokens_created": self.debt_tokens_created,
            "debt_tokens_collected": self.debt_tokens_collected,
            "restores": self.restores,
            "outstanding_leases": float(self.outstanding_leases()),
            "debt_tokens": sum(self._debts.values()),
        }

    def stats(self) -> dict:
        """JSON-shaped summary for OP_STATS embedding (piggybacks one
        expiry pass, so a scraped-but-idle home still expires)."""
        self.expire()
        out = self.numeric_stats()
        out["tenants"] = {
            t: {"cap": p.cap, "rate": p.rate,
                "leases": {r: {"lease_id": l.lease_id,
                               "epoch": l.epoch,
                               "share": round(l.share, 4),
                               "slice": [l.slice_cap, l.slice_rate],
                               "reported_total": l.reported_total,
                               "demand": l.demand}
                           for r, l in sorted(p.leases.items())}}
            for t, p in sorted(self._pools.items())}
        out["debts"] = {f"{t}/{r}": round(v, 3)
                        for (t, r), v in sorted(self._debts.items())}
        return out


# ===========================================================================
# Region side
# ===========================================================================

class _TenantLease:
    """One tenant's lease as the region knows it."""

    __slots__ = ("lease_id", "epoch", "slice_cap", "slice_rate",
                 "applied", "expires_mono", "renew_due_mono",
                 "degraded", "ttl_s")

    def __init__(self) -> None:
        self.lease_id: "str | None" = None
        self.epoch = 0
        self.slice_cap = 0.0
        self.slice_rate = 0.0
        #: The config currently live on the regional data plane
        #: (slice or degraded envelope) — the OP_CONFIG rule's `old`.
        self.applied: "tuple[float, float] | None" = None
        self.expires_mono = 0.0
        self.renew_due_mono = 0.0
        self.degraded = False
        self.ttl_s = DEFAULT_LEASE_TTL_S


def slice_applier(target):
    """An ``apply_slice(tenant, old_cfg, new_cfg)`` callback over the
    existing live-config machinery: a :class:`~.cluster.
    ClusterBucketStore` applies through ``mutate_config`` (two-phase
    across the fleet under the membership lock), a single node through
    ``config_announce`` (prepare + commit at the node's next version)
    — either way the slice change IS an ordinary OP_CONFIG mutation
    whose stale traffic chases one routable "config moved" error."""
    async def apply(tenant: str, old, new) -> None:
        del tenant  # the config operands are the identity on the wire
        if old is None or tuple(old) == tuple(new):
            return
        mutate = getattr(target, "mutate_config", None)
        if callable(mutate):
            await mutate("bucket", tuple(old), tuple(new))
            return
        announce = getattr(target, "config_announce", None)
        if callable(announce):
            fetch = getattr(target, "config_fetch", None)
            version = 0
            if callable(fetch):
                version = int((await fetch()).get("version", 0))
            rule = {"kind": "bucket", "old": list(old),
                    "new": list(new)}
            await announce({"prepare": rule, "version": version + 1})
            await announce({"commit": version + 1})
            return
        raise TypeError(
            "slice_applier target supports neither mutate_config nor "
            "config_announce")
    return apply


class RegionFederation:
    """The region side of the federation: holds one lease per tenant,
    renews on a deterministic cadence, applies slice changes through
    the OP_CONFIG lane, and — the robustness core — degrades to the
    fair-share envelope config when a lease expires unrenewed (module
    docstring). Drive it with :meth:`tick` (the controller's
    ``federation`` actuator does; soaks call it directly — cadence is
    an operational concern, not a semantic one)."""

    def __init__(self, region: str, home, *,
                 tenants: "Mapping[str, tuple[float, float]]",
                 apply_slice=None,
                 admitted_total: "Callable[[str], float] | None" = None,
                 demand: "Callable[[str], float] | None" = None,
                 ttl_s: float = DEFAULT_LEASE_TTL_S,
                 renew_fraction: float = 0.5,
                 envelope_fraction: float = DEFAULT_ENVELOPE_FRACTION,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 flight_recorder=None,
                 lease_id_factory: "Callable[[], str] | None" = None
                 ) -> None:
        if not tenants:
            raise ValueError("RegionFederation needs >= 1 tenant")
        if ttl_s <= 0 or not 0.0 < renew_fraction < 1.0:
            raise ValueError("ttl_s must be positive and "
                             "renew_fraction in (0, 1)")
        self.region = region
        #: The home handle: anything with async ``fed_lease`` /
        #: ``fed_renew`` / ``fed_reclaim`` — a RemoteBucketStore over
        #: the WAN, or a FederationLedger directly (in-process tests).
        self.home = home
        self.tenants = {str(t): (float(c), float(r))
                        for t, (c, r) in tenants.items()}
        self._apply_slice = apply_slice
        self._admitted_total = admitted_total or (lambda _t: 0.0)
        self._demand = demand or (lambda _t: 0.0)
        self.ttl_s = float(ttl_s)
        self.renew_fraction = float(renew_fraction)
        self.envelope_fraction = float(envelope_fraction)
        #: MONOTONIC lease clock — region-side expiry reads ONLY this
        #: (the no-skew-extension contract's other half). ``wall`` is
        #: for stats timestamps.
        self._clock = clock
        self._wall = wall
        self.flight_recorder = flight_recorder
        self._ids = lease_id_factory or self._default_ids()
        self._leases: "dict[str, _TenantLease]" = {
            t: _TenantLease() for t in self.tenants}
        # Visible counters (OP_STATS "federation_region" +
        # drl_federation_region_*). MONOTONIC.
        self.leases_acquired = 0
        self.lease_failures = 0
        self.renews = 0
        self.renew_failures = 0
        self.partition_errors = 0
        self.degraded_entries = 0
        self.heals = 0
        self.slice_updates = 0
        self.stale_slice_replies = 0
        self.reclaims = 0
        self.fed_fallbacks = 0

    def _default_ids(self) -> Callable[[], str]:
        seq = [0]

        def make() -> str:
            seq[0] += 1
            return f"{self.region}:{seq[0]}"
        return make

    # -- introspection -------------------------------------------------------
    def slice(self, tenant: str) -> "tuple[float, float] | None":
        """The config the region currently serves ``tenant`` from
        (slice, or the degraded envelope config mid-partition);
        ``None`` before the first lease."""
        lease = self._leases[tenant]
        return lease.applied

    def degraded(self, tenant: str) -> bool:
        return self._leases[tenant].degraded

    @property
    def any_degraded(self) -> bool:
        return any(l.degraded for l in self._leases.values())

    def renew_due(self, now: "float | None" = None) -> bool:
        """True when any tenant's renew (or first lease) is due — the
        controller's actuator condition."""
        now = self._clock() if now is None else now
        return any(l.lease_id is None or now >= l.renew_due_mono
                   for l in self._leases.values())

    # -- the drive -----------------------------------------------------------
    async def tick(self, demands: "Mapping[str, float] | None" = None,
                   now: "float | None" = None) -> dict:
        """One federation round for every tenant: lease when missing,
        renew when due, degrade when expired — in that priority order
        per tenant, one WAN call each. ``demands`` (per-tenant
        tokens/sec — the controller passes its velocity-delta rates)
        overrides the constructor's demand callable for this round.
        Partition failures are COUNTED and absorbed: the region keeps
        serving from its applied config; expiry is what degrades it,
        never an RPC error (never hard-down)."""
        now = self._clock() if now is None else now
        summary = {"renewed": 0, "leased": 0, "degraded": 0,
                   "healed": 0, "errors": 0}
        for tenant, lease in self._leases.items():
            demand = (float(demands[tenant])
                      if demands and tenant in demands
                      else float(self._demand(tenant)))
            # 1. Degrade on local monotonic expiry FIRST: renewals may
            # be failing precisely because the WAN is down.
            if (lease.lease_id is not None and not lease.degraded
                    and now >= lease.expires_mono):
                await self._degrade(tenant, lease)
                summary["degraded"] += 1
            if lease.lease_id is None:
                ok = await self._lease(tenant, lease, demand, now)
                summary["leased" if ok else "errors"] += 1
                continue
            if now >= lease.renew_due_mono or lease.degraded:
                ok, healed = await self._renew(tenant, lease, demand,
                                               now)
                if ok:
                    summary["renewed"] += 1
                    if healed:
                        summary["healed"] += 1
                else:
                    summary["errors"] += 1
        return summary

    async def _call_home(self, method: str, payload: dict):
        """One WAN control call through the chaos seam. The
        ``federation.renew`` / ``federation.lease`` /
        ``federation.reclaim`` seams are where the soak injects
        resets, delays, and blackholes — a fault here is a partition
        symptom the caller counts and absorbs."""
        seam_name = {"fed_lease": "federation.lease",
                     "fed_renew": "federation.renew",
                     "fed_reclaim": "federation.reclaim"}[method]
        await faults.seam(seam_name)
        fn = getattr(self.home, method, None)
        if fn is None:
            # A FederationLedger passed directly (in-process home).
            direct = {"fed_lease": "lease", "fed_renew": "renew",
                      "fed_reclaim": "reclaim"}[method]
            fn = getattr(self.home, direct)
        return await fn(payload)

    async def _lease(self, tenant: str, lease: _TenantLease,
                     demand: float, now: float) -> bool:
        cap, rate = self.tenants[tenant]
        lease_id = self._ids()
        try:
            reply = await self._call_home("fed_lease", {
                "region": self.region, "lease_id": lease_id,
                "tenant": tenant, "demand": demand,
                # The region's monotonic admitted total seeds the
                # home's report baseline for this lease generation.
                "total": float(self._admitted_total(tenant)),
                "global_cap": cap, "global_rate": rate,
                "ttl_s": self.ttl_s})
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            self.lease_failures += 1
            self.partition_errors += 1
            log.error_evaluating_kernel(exc)
            return False
        if reply.get("fallback"):
            self.fed_fallbacks += 1
            return False
        if not reply.get("granted"):
            self.lease_failures += 1
            return False
        was_degraded = lease.degraded
        lease.lease_id = lease_id
        lease.ttl_s = float(reply.get("ttl_s", self.ttl_s))
        lease.degraded = False   # BEFORE adoption: the fresh slice
        self._arm(lease, now)    # must replace a degraded envelope
        await self._adopt(tenant, lease, int(reply.get("epoch", 1)),
                          reply.get("slice") or [1.0, 0.0])
        self.leases_acquired += 1
        if was_degraded:
            self.heals += 1
            if self.flight_recorder is not None:
                self.flight_recorder.record(
                    "federation", event="region_healed",
                    region=self.region, tenant=tenant,
                    lease_id=lease_id)
        return True

    async def _renew(self, tenant: str, lease: _TenantLease,
                     demand: float, now: float
                     ) -> "tuple[bool, bool]":
        total = float(self._admitted_total(tenant))
        try:
            reply = await self._call_home("fed_renew", {
                "region": self.region, "lease_id": lease.lease_id,
                "tenant": tenant, "total": total, "demand": demand})
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            self.renew_failures += 1
            self.partition_errors += 1
            log.error_evaluating_kernel(exc)
            return False, False
        if reply.get("fallback"):
            self.fed_fallbacks += 1
            return False, False
        outcome = reply.get("outcome")
        if outcome == "ok":
            self.renews += 1
            lease.ttl_s = float(reply.get("ttl_s", lease.ttl_s))
            self._arm(lease, now)
            await self._adopt(tenant, lease,
                              int(reply.get("epoch", 0)),
                              reply.get("slice")
                              or [lease.slice_cap, lease.slice_rate])
            healed = lease.degraded
            if healed:
                # The home still held the lease (region-side expiry
                # fired first): re-apply the slice over the envelope.
                lease.degraded = False
                self.heals += 1
                await self._apply(tenant, lease,
                                  (lease.slice_cap, lease.slice_rate))
            return True, healed
        # "expired"/"unknown": the home already reconciled (heal) or
        # never knew us — drop the lease; the next tick re-leases with
        # a FRESH id (lease ids are single-use, the rid posture).
        lease.lease_id = None
        return True, outcome == "expired"

    def _arm(self, lease: _TenantLease, now: float) -> None:
        """Reset the lease windows from the MONOTONIC clock only: the
        next renew at ``renew_fraction × ttl``, expiry at ``ttl``."""
        lease.expires_mono = now + lease.ttl_s
        lease.renew_due_mono = now + lease.ttl_s * self.renew_fraction

    async def _adopt(self, tenant: str, lease: _TenantLease,
                     epoch: int, new_slice) -> None:
        """Adopt a slice reply FORWARD-ONLY: a stale (out-of-order WAN
        retry) reply carrying an older epoch must not roll the applied
        config back — the OP_CONFIG version discipline, and
        drl-verify's ``fed-lease-monotonic`` anchor."""
        if epoch <= lease.epoch:
            if epoch < lease.epoch:
                self.stale_slice_replies += 1
            return
        lease.epoch = epoch
        new_cfg = (float(new_slice[0]), float(new_slice[1]))
        lease.slice_cap, lease.slice_rate = new_cfg
        if not lease.degraded:
            await self._apply(tenant, lease, new_cfg)

    async def _apply(self, tenant: str, lease: _TenantLease,
                     new_cfg: "tuple[float, float]") -> None:
        old = lease.applied
        if old == new_cfg:
            return
        if self._apply_slice is not None:
            try:
                await self._apply_slice(tenant, old, new_cfg)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # A failed mutation leaves the OLD config serving —
                # bounded (it was a granted slice or its envelope),
                # counted, retried at the next adoption.
                self.renew_failures += 1
                log.error_evaluating_kernel(exc)
                return
        lease.applied = new_cfg
        self.slice_updates += 1

    async def _degrade(self, tenant: str, lease: _TenantLease) -> None:
        """Lease expired with the home unreachable: rewrite the
        tenant's config to the fair-share envelope — bounded local
        serving, the breaker-quarantine posture. The slice identity
        (lease_id/epoch) is kept so the eventual heal reconciles."""
        env = degraded_config(lease.slice_cap, lease.slice_rate,
                              self.envelope_fraction)
        lease.degraded = True
        self.degraded_entries += 1
        await self._apply(tenant, lease, env)
        if self.flight_recorder is not None:
            self.flight_recorder.record(
                "federation", event="region_degraded",
                region=self.region, tenant=tenant,
                lease_id=lease.lease_id, envelope_cap=env[0],
                envelope_rate=env[1])

    async def reclaim_all(self) -> int:
        """Graceful shutdown: return every slice to the pool (reports
        the final totals; idempotent server-side, so a retry after an
        ambiguous failure is safe). Returns leases reclaimed."""
        n = 0
        for tenant, lease in self._leases.items():
            if lease.lease_id is None:
                continue
            try:
                reply = await self._call_home("fed_reclaim", {
                    "region": self.region, "lease_id": lease.lease_id,
                    "tenant": tenant,
                    "total": float(self._admitted_total(tenant))})
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                self.partition_errors += 1
                log.error_evaluating_kernel(exc)
                continue
            if reply.get("outcome") in ("reclaimed", "duplicate"):
                lease.lease_id = None
                self.reclaims += 1
                n += 1
        return n

    # -- stats ---------------------------------------------------------------
    def numeric_stats(self) -> dict:
        """Flat numeric dict for ``register_numeric_dict`` — the
        ``drl_federation_region_*`` families (partition/degraded
        counters the satellite contract names)."""
        return {
            "leases_acquired": self.leases_acquired,
            "lease_failures": self.lease_failures,
            "renews": self.renews,
            "renew_failures": self.renew_failures,
            "partition_errors": self.partition_errors,
            "degraded_entries": self.degraded_entries,
            "heals": self.heals,
            "slice_updates": self.slice_updates,
            "stale_slice_replies": self.stale_slice_replies,
            "reclaims": self.reclaims,
            "fed_fallbacks": self.fed_fallbacks,
            "degraded_now": float(sum(
                1 for l in self._leases.values() if l.degraded)),
            "leases_held": float(sum(
                1 for l in self._leases.values()
                if l.lease_id is not None)),
        }

    def stats(self) -> dict:
        out = self.numeric_stats()
        out["region"] = self.region
        out["tenants"] = {
            t: {"lease_id": l.lease_id, "epoch": l.epoch,
                "slice": [l.slice_cap, l.slice_rate],
                "applied": list(l.applied) if l.applied else None,
                "degraded": l.degraded}
            for t, l in sorted(self._leases.items())}
        return out
