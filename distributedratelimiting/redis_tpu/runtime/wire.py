"""Wire protocol for the remote store — the framework's RESP/EVALSHA analogue.

The reference's entire comm stack is a multiplexed TCP connection carrying
``EVALSHA`` invocations of prepared scripts (SURVEY.md §5.8,
``StackExchange.Redis`` + ``ScriptEvaluateAsync``). Here the same star
topology is served by a compact length-prefixed binary protocol: clients
pipeline requests tagged with a sequence id over one connection; the server
executes each against its local :class:`BucketStore` (typically the
TPU-resident :class:`DeviceBucketStore`, whose micro-batcher coalesces
concurrent requests from all connections into single kernel launches) and
replies out of completion order.

Frame layout (all integers little-endian):

    [u32 length][u32 seq][u8 op][payload…]

Request payloads:
    ACQUIRE / WINDOW : [u16 klen][key utf-8][i32 count][f64 a][f64 b]
                       (a, b) = (capacity, fill_rate) / (limit, window_s)
    PEEK             : [u16 klen][key utf-8][f64 capacity][f64 fill_rate]
    SYNC             : [u16 klen][key utf-8][f64 local_count][f64 decay_rate]
    PING / SAVE / STATS : empty (SAVE writes the server-configured
                       checkpoint path — clients never supply paths)

Response payloads:
    OK_DECISION : [u8 granted][f64 remaining]
    OK_VALUE    : [f64 value]
    OK_PAIR     : [f64 a][f64 b]
    OK_EMPTY    : empty
    OK_TEXT     : [u16 mlen][text utf-8] (STATS reply: a JSON object)
    ERROR       : [u16 mlen][message utf-8]
"""

from __future__ import annotations

import struct

__all__ = [
    "OP_ACQUIRE", "OP_PEEK", "OP_SYNC", "OP_WINDOW", "OP_PING",
    "OP_SAVE", "OP_STATS", "OP_SEMA", "OP_FWINDOW",
    "RESP_DECISION", "RESP_VALUE", "RESP_PAIR", "RESP_EMPTY", "RESP_TEXT",
    "RESP_ERROR",
    "MAX_FRAME", "RemoteStoreError", "op_name",
    "encode_request", "decode_request", "encode_response", "decode_response",
    "read_frame", "write_frame",
]

OP_ACQUIRE = 1
OP_PEEK = 2
OP_SYNC = 3
OP_WINDOW = 4
OP_PING = 5
OP_SAVE = 6    # ≙ Redis BGSAVE: checkpoint the store server-side
OP_STATS = 7   # server + store metrics as JSON text
OP_SEMA = 8    # concurrency semaphore: count = signed delta, a = limit
OP_FWINDOW = 9  # fixed-window acquire: (a, b) = (limit, window_s)

_OP_NAMES = {
    OP_ACQUIRE: "acquire",
    OP_PEEK: "peek",
    OP_SYNC: "sync_counter",
    OP_WINDOW: "window_acquire",
    OP_PING: "ping",
    OP_SAVE: "save",
    OP_STATS: "stats",
    OP_SEMA: "sema",
    OP_FWINDOW: "fixed_window_acquire",
}


def op_name(op: int) -> str:
    """Human-readable op name (used by the wire-level profiler)."""
    return _OP_NAMES.get(op, f"op{op}")


RESP_DECISION = 64
RESP_VALUE = 65
RESP_PAIR = 66
RESP_EMPTY = 67
RESP_TEXT = 68
RESP_ERROR = 127

#: Upper bound on a frame body; a peer announcing more is protocol-broken
#: (or hostile) and the connection is dropped rather than buffered.
MAX_FRAME = 1 << 20

_HDR = struct.Struct("<IIB")          # length covers [seq][op][payload]
_DECISION = struct.Struct("<Bd")
_VALUE = struct.Struct("<d")
_PAIR = struct.Struct("<dd")
_KEYED = struct.Struct("<H")
_ACQ_TAIL = struct.Struct("<idd")
_F64x2 = struct.Struct("<dd")


class RemoteStoreError(RuntimeError):
    """Server-side failure relayed to the client (≙ a Redis script error
    surfaced through ``ScriptEvaluateAsync``)."""


def _keyed(key: str, tail: bytes) -> bytes:
    kb = key.encode("utf-8")
    if len(kb) > 0xFFFF:
        raise ValueError("key exceeds 65535 utf-8 bytes")
    return _KEYED.pack(len(kb)) + kb + tail


def _split_key(payload: bytes) -> tuple[str, bytes]:
    (klen,) = _KEYED.unpack_from(payload, 0)
    key = payload[2:2 + klen].decode("utf-8")
    return key, payload[2 + klen:]


def encode_request(seq: int, op: int, key: str = "", count: int = 0,
                   a: float = 0.0, b: float = 0.0) -> bytes:
    if op in (OP_ACQUIRE, OP_WINDOW, OP_SEMA, OP_FWINDOW):
        payload = _keyed(key, _ACQ_TAIL.pack(count, a, b))
    elif op in (OP_PEEK, OP_SYNC):
        payload = _keyed(key, _F64x2.pack(a, b))
    elif op in (OP_PING, OP_SAVE, OP_STATS):
        payload = b""
    else:
        raise ValueError(f"unknown op {op}")
    return _HDR.pack(5 + len(payload), seq, op) + payload


def decode_request(seq_op_payload: bytes) -> tuple[int, int, str, int, float, float]:
    """Returns ``(seq, op, key, count, a, b)``."""
    seq, op = struct.unpack_from("<IB", seq_op_payload, 0)
    body = seq_op_payload[5:]
    if op in (OP_ACQUIRE, OP_WINDOW, OP_SEMA, OP_FWINDOW):
        key, tail = _split_key(body)
        count, a, b = _ACQ_TAIL.unpack(tail)
        return seq, op, key, count, a, b
    if op in (OP_PEEK, OP_SYNC):
        key, tail = _split_key(body)
        a, b = _F64x2.unpack(tail)
        return seq, op, key, 0, a, b
    if op in (OP_PING, OP_SAVE, OP_STATS):
        return seq, op, "", 0, 0.0, 0.0
    raise RemoteStoreError(f"unknown op {op}")


def encode_response(seq: int, kind: int, *vals) -> bytes:
    if kind == RESP_DECISION:
        payload = _DECISION.pack(1 if vals[0] else 0, float(vals[1]))
    elif kind == RESP_VALUE:
        payload = _VALUE.pack(float(vals[0]))
    elif kind == RESP_PAIR:
        payload = _PAIR.pack(float(vals[0]), float(vals[1]))
    elif kind == RESP_EMPTY:
        payload = b""
    elif kind in (RESP_ERROR, RESP_TEXT):
        mb = str(vals[0]).encode("utf-8")[:0xFFFF]
        payload = _KEYED.pack(len(mb)) + mb
    else:
        raise ValueError(f"unknown response kind {kind}")
    return _HDR.pack(5 + len(payload), seq, kind) + payload


def decode_response(seq_kind_payload: bytes) -> tuple[int, int, tuple]:
    """Returns ``(seq, kind, values)``; raises nothing — errors travel as
    ``(RESP_ERROR, (message,))`` so the client can fail just that future."""
    seq, kind = struct.unpack_from("<IB", seq_kind_payload, 0)
    body = seq_kind_payload[5:]
    if kind == RESP_DECISION:
        granted, remaining = _DECISION.unpack(body)
        return seq, kind, (bool(granted), remaining)
    if kind == RESP_VALUE:
        return seq, kind, _VALUE.unpack(body)
    if kind == RESP_PAIR:
        return seq, kind, _PAIR.unpack(body)
    if kind == RESP_EMPTY:
        return seq, kind, ()
    if kind in (RESP_ERROR, RESP_TEXT):
        (mlen,) = _KEYED.unpack_from(body, 0)
        return seq, kind, (body[2:2 + mlen].decode("utf-8"),)
    raise RemoteStoreError(f"unknown response kind {kind}")


async def read_frame(reader) -> bytes | None:
    """Read one ``[seq][op][payload]`` body; ``None`` on clean EOF."""
    import asyncio

    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = struct.unpack("<I", hdr)
    if not 5 <= length <= MAX_FRAME:
        raise RemoteStoreError(f"bad frame length {length}")
    try:
        return await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None


def write_frame(writer, data: bytes) -> None:
    writer.write(data)
