"""Wire protocol for the remote store — the framework's RESP/EVALSHA analogue.

The reference's entire comm stack is a multiplexed TCP connection carrying
``EVALSHA`` invocations of prepared scripts (SURVEY.md §5.8,
``StackExchange.Redis`` + ``ScriptEvaluateAsync``). Here the same star
topology is served by a compact length-prefixed binary protocol: clients
pipeline requests tagged with a sequence id over one connection; the server
executes each against its local :class:`BucketStore` (typically the
TPU-resident :class:`DeviceBucketStore`, whose micro-batcher coalesces
concurrent requests from all connections into single kernel launches) and
replies out of completion order.

Frame layout (all integers little-endian):

    [u32 length][u8 version][u32 seq][u8 op][payload…]

``version`` is :data:`PROTOCOL_VERSION`; a mismatch raises before any
payload is interpreted, so any *future* revision (v2+, all carrying the
byte) is reliably detectable rather than silently misparsed. (A legacy
v1 peer — whose layout had no version byte — is detected probabilistically:
its seq's low byte sits where the version now is, so 1-in-256 v1 frames
can slip past the gate; v1 predates any release, so this is theoretical.)
The reference inherits version/auth negotiation from the Redis
``Configuration`` string (``RedisTokenBucketRateLimiterOptions.cs:30-40``);
see ``OP_HELLO`` for the auth analogue.

Request payloads:
    ACQUIRE / WINDOW : [u16 klen][key utf-8][i32 count][f64 a][f64 b]
                       (a, b) = (capacity, fill_rate) / (limit, window_s)
    PEEK             : [u16 klen][key utf-8][f64 capacity][f64 fill_rate]
    SYNC             : [u16 klen][key utf-8][f64 local_count][f64 decay_rate]
    HELLO            : [u16 tlen][token utf-8] (shared-secret auth; must be
                       the first frame when the server requires a token)
    PING / SAVE / STATS : empty (SAVE writes the server-configured
                       checkpoint path — clients never supply paths)

Response payloads:
    OK_DECISION : [u8 granted][f64 remaining]
    OK_VALUE    : [f64 value]
    OK_PAIR     : [f64 a][f64 b]
    OK_EMPTY    : empty
    OK_TEXT     : [u32 mlen][text utf-8] (STATS reply: a JSON object —
                  u32 so a large stats payload can never be truncated
                  mid-UTF-8; bounded by MAX_FRAME)
    ERROR       : [u16 mlen][message utf-8] (truncated on a codepoint
                  boundary if oversized)

Version history: v1 had no version byte and a u16 OK_TEXT length; v2
(current) added the version byte, HELLO, and the u32 OK_TEXT length.
"""

from __future__ import annotations

import struct

__all__ = [
    "OP_ACQUIRE", "OP_PEEK", "OP_SYNC", "OP_WINDOW", "OP_PING",
    "OP_SAVE", "OP_STATS", "OP_SEMA", "OP_FWINDOW", "OP_HELLO",
    "RESP_DECISION", "RESP_VALUE", "RESP_PAIR", "RESP_EMPTY", "RESP_TEXT",
    "RESP_ERROR",
    "MAX_FRAME", "PROTOCOL_VERSION", "RemoteStoreError",
    "ProtocolVersionError", "op_name",
    "encode_request", "decode_request", "encode_response", "decode_response",
    "read_frame", "write_frame",
]

PROTOCOL_VERSION = 2

OP_ACQUIRE = 1
OP_PEEK = 2
OP_SYNC = 3
OP_WINDOW = 4
OP_PING = 5
OP_SAVE = 6    # ≙ Redis BGSAVE: checkpoint the store server-side
OP_STATS = 7   # server + store metrics as JSON text
OP_SEMA = 8    # concurrency semaphore: count = signed delta, a = limit
OP_FWINDOW = 9  # fixed-window acquire: (a, b) = (limit, window_s)
OP_HELLO = 10  # shared-secret auth handshake (≙ Redis AUTH)

_OP_NAMES = {
    OP_ACQUIRE: "acquire",
    OP_PEEK: "peek",
    OP_SYNC: "sync_counter",
    OP_WINDOW: "window_acquire",
    OP_PING: "ping",
    OP_SAVE: "save",
    OP_STATS: "stats",
    OP_SEMA: "sema",
    OP_FWINDOW: "fixed_window_acquire",
    OP_HELLO: "hello",
}


def op_name(op: int) -> str:
    """Human-readable op name (used by the wire-level profiler)."""
    return _OP_NAMES.get(op, f"op{op}")


RESP_DECISION = 64
RESP_VALUE = 65
RESP_PAIR = 66
RESP_EMPTY = 67
RESP_TEXT = 68
RESP_ERROR = 127

#: Upper bound on a frame body; a peer announcing more is protocol-broken
#: (or hostile) and the connection is dropped rather than buffered.
MAX_FRAME = 1 << 20

_HDR = struct.Struct("<IBIB")         # length covers [version][seq][op][payload]
_VER_SEQ_OP = struct.Struct("<BIB")
_BODY_OFF = _VER_SEQ_OP.size          # payload offset inside a frame body
_DECISION = struct.Struct("<Bd")
_VALUE = struct.Struct("<d")
_PAIR = struct.Struct("<dd")
_KEYED = struct.Struct("<H")
_TEXTLEN = struct.Struct("<I")
_ACQ_TAIL = struct.Struct("<idd")
_F64x2 = struct.Struct("<dd")


class RemoteStoreError(RuntimeError):
    """Server-side failure relayed to the client (≙ a Redis script error
    surfaced through ``ScriptEvaluateAsync``)."""


class ProtocolVersionError(RemoteStoreError):
    """Peer speaks a different protocol revision; the frame was not
    interpreted past its version byte."""


def _check_version(ver: int) -> None:
    if ver != PROTOCOL_VERSION:
        raise ProtocolVersionError(
            f"protocol version mismatch: peer speaks v{ver}, "
            f"this build speaks v{PROTOCOL_VERSION}"
        )


def _keyed(key: str, tail: bytes) -> bytes:
    kb = key.encode("utf-8")
    if len(kb) > 0xFFFF:
        raise ValueError("key exceeds 65535 utf-8 bytes")
    return _KEYED.pack(len(kb)) + kb + tail


def _split_key(payload: bytes) -> tuple[str, bytes]:
    (klen,) = _KEYED.unpack_from(payload, 0)
    key = payload[2:2 + klen].decode("utf-8")
    return key, payload[2 + klen:]


def _codepoint_truncate(mb: bytes, limit: int) -> bytes:
    """Truncate utf-8 bytes to ``limit`` on a codepoint boundary."""
    if len(mb) <= limit:
        return mb
    return mb[:limit].decode("utf-8", "ignore").encode("utf-8")


def encode_request(seq: int, op: int, key: str = "", count: int = 0,
                   a: float = 0.0, b: float = 0.0) -> bytes:
    if op in (OP_ACQUIRE, OP_WINDOW, OP_SEMA, OP_FWINDOW):
        payload = _keyed(key, _ACQ_TAIL.pack(count, a, b))
    elif op in (OP_PEEK, OP_SYNC):
        payload = _keyed(key, _F64x2.pack(a, b))
    elif op == OP_HELLO:
        payload = _keyed(key, b"")  # key carries the auth token
    elif op in (OP_PING, OP_SAVE, OP_STATS):
        payload = b""
    else:
        raise ValueError(f"unknown op {op}")
    return _HDR.pack(_BODY_OFF + len(payload), PROTOCOL_VERSION, seq, op) + payload


def decode_request(frame: bytes) -> tuple[int, int, str, int, float, float]:
    """Returns ``(seq, op, key, count, a, b)``."""
    ver, seq, op = _VER_SEQ_OP.unpack_from(frame, 0)
    _check_version(ver)
    body = frame[_BODY_OFF:]
    if op in (OP_ACQUIRE, OP_WINDOW, OP_SEMA, OP_FWINDOW):
        key, tail = _split_key(body)
        count, a, b = _ACQ_TAIL.unpack(tail)
        return seq, op, key, count, a, b
    if op in (OP_PEEK, OP_SYNC):
        key, tail = _split_key(body)
        a, b = _F64x2.unpack(tail)
        return seq, op, key, 0, a, b
    if op == OP_HELLO:
        token, _ = _split_key(body)
        return seq, op, token, 0, 0.0, 0.0
    if op in (OP_PING, OP_SAVE, OP_STATS):
        return seq, op, "", 0, 0.0, 0.0
    raise RemoteStoreError(f"unknown op {op}")


def encode_response(seq: int, kind: int, *vals) -> bytes:
    if kind == RESP_DECISION:
        payload = _DECISION.pack(1 if vals[0] else 0, float(vals[1]))
    elif kind == RESP_VALUE:
        payload = _VALUE.pack(float(vals[0]))
    elif kind == RESP_PAIR:
        payload = _PAIR.pack(float(vals[0]), float(vals[1]))
    elif kind == RESP_EMPTY:
        payload = b""
    elif kind == RESP_ERROR:
        mb = _codepoint_truncate(str(vals[0]).encode("utf-8"), 0xFFFF)
        payload = _KEYED.pack(len(mb)) + mb
    elif kind == RESP_TEXT:
        # u32 length: a large payload (e.g. MeshBucketStore stats with many
        # tiers) must never be silently truncated into undecodable JSON —
        # oversize is a loud error instead, bounded by MAX_FRAME.
        mb = str(vals[0]).encode("utf-8")
        if _BODY_OFF + _TEXTLEN.size + len(mb) > MAX_FRAME:
            raise ValueError(
                f"text payload of {len(mb)} bytes exceeds MAX_FRAME"
            )
        payload = _TEXTLEN.pack(len(mb)) + mb
    else:
        raise ValueError(f"unknown response kind {kind}")
    return _HDR.pack(_BODY_OFF + len(payload), PROTOCOL_VERSION, seq, kind) + payload


def decode_response(frame: bytes) -> tuple[int, int, tuple]:
    """Returns ``(seq, kind, values)``; server-side failures travel as
    ``(RESP_ERROR, (message,))`` so the client can fail just that future.
    Raises only for protocol-level breakage (version mismatch)."""
    ver, seq, kind = _VER_SEQ_OP.unpack_from(frame, 0)
    _check_version(ver)
    body = frame[_BODY_OFF:]
    if kind == RESP_DECISION:
        granted, remaining = _DECISION.unpack(body)
        return seq, kind, (bool(granted), remaining)
    if kind == RESP_VALUE:
        return seq, kind, _VALUE.unpack(body)
    if kind == RESP_PAIR:
        return seq, kind, _PAIR.unpack(body)
    if kind == RESP_EMPTY:
        return seq, kind, ()
    if kind == RESP_ERROR:
        (mlen,) = _KEYED.unpack_from(body, 0)
        return seq, kind, (body[2:2 + mlen].decode("utf-8"),)
    if kind == RESP_TEXT:
        (mlen,) = _TEXTLEN.unpack_from(body, 0)
        return seq, kind, (body[4:4 + mlen].decode("utf-8"),)
    raise RemoteStoreError(f"unknown response kind {kind}")


async def read_frame(reader) -> bytes | None:
    """Read one ``[version][seq][op][payload]`` body; ``None`` on clean
    EOF."""
    import asyncio

    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = struct.unpack("<I", hdr)
    if not _BODY_OFF <= length <= MAX_FRAME:
        raise RemoteStoreError(f"bad frame length {length}")
    try:
        return await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None


def write_frame(writer, data: bytes) -> None:
    writer.write(data)
