"""Wire protocol for the remote store — the framework's RESP/EVALSHA analogue.

The reference's entire comm stack is a multiplexed TCP connection carrying
``EVALSHA`` invocations of prepared scripts (SURVEY.md §5.8,
``StackExchange.Redis`` + ``ScriptEvaluateAsync``). Here the same star
topology is served by a compact length-prefixed binary protocol: clients
pipeline requests tagged with a sequence id over one connection; the server
executes each against its local :class:`BucketStore` (typically the
TPU-resident :class:`DeviceBucketStore`, whose micro-batcher coalesces
concurrent requests from all connections into single kernel launches) and
replies out of completion order.

Frame layout (all integers little-endian):

    [u32 length][u8 version][u32 seq][u8 op][payload…]

``version`` is :data:`PROTOCOL_VERSION`; a mismatch raises before any
payload is interpreted, so any *future* revision (v2+, all carrying the
byte) is reliably detectable rather than silently misparsed. (A legacy
v1 peer — whose layout had no version byte — is detected probabilistically:
its seq's low byte sits where the version now is, so 1-in-256 v1 frames
can slip past the gate; v1 predates any release, so this is theoretical.)
The reference inherits version/auth negotiation from the Redis
``Configuration`` string (``RedisTokenBucketRateLimiterOptions.cs:30-40``);
see ``OP_HELLO`` for the auth analogue.

Request payloads:
    ACQUIRE / WINDOW : [u16 klen][key utf-8][i32 count][f64 a][f64 b]
                       (a, b) = (capacity, fill_rate) / (limit, window_s)
    PEEK             : [u16 klen][key utf-8][f64 capacity][f64 fill_rate]
    SYNC             : [u16 klen][key utf-8][f64 local_count][f64 decay_rate]
    HELLO            : [u16 tlen][token utf-8] (shared-secret auth; must be
                       the first frame when the server requires a token)
    PING / SAVE / STATS : empty (SAVE writes the server-configured
                       checkpoint path — clients never supply paths)
    ACQUIRE_MANY     : [u8 flags][f64 a][f64 b][u32 n]
                       [u16 klen × n][key blob utf-8][u32 count × n]
                       — one frame decides n keys' requests (the bulk path;
                       flags bit 0 = caller wants per-request remaining;
                       flags bits 1-2 = table kind: 0 token bucket with
                       (a, b) = (capacity, fill_rate), 1 sliding window /
                       2 fixed window with (a, b) = (limit, window_s)).
                       Length/count arrays are raw little-endian vectors so
                       both ends move them with numpy, not per-key packing.
                       Keys are byte strings end-to-end on the serving
                       path (the server resolves them from the frame blob
                       natively — ``KeyBlob``); invalid UTF-8 rate-limits
                       under its own stable identity rather than erroring
                       the frame, matching the native front-end's
                       per-request lane.
                       Clients split larger bulks into multiple frames via
                       :func:`bulk_chunk_spans` (every chunk ≤ MAX_FRAME)
                       and pipeline the chunks on one connection.

Response payloads:
    OK_DECISION : [u8 granted][f64 remaining]
    OK_VALUE    : [f64 value]
    OK_PAIR     : [f64 a][f64 b]
    OK_EMPTY    : empty
    OK_TEXT     : [u32 mlen][text utf-8] (STATS reply: a JSON object —
                  u32 so a large stats payload can never be truncated
                  mid-UTF-8; bounded by MAX_FRAME)
    OK_BULK     : [u8 flags][u32 n][granted bits, (n+7)//8 bytes, LSB-first]
                  [f32 remaining × n, present iff flags bit 0] — 1 bit per
                  verdict (+4B optional estimate), so a full MAX_FRAME
                  request's reply stays well under MAX_FRAME
    ERROR       : [u16 mlen][message utf-8] (truncated on a codepoint
                  boundary if oversized)

Version history: v1 had no version byte and a u16 OK_TEXT length; v2
added the version byte, HELLO, the u32 OK_TEXT length, and
ACQUIRE_MANY/OK_BULK; v3 gave ACQUIRE_MANY's flags byte the table-kind
bits; v4 (current) added the chained-chunk bit (chunk ordering became
opt-in per frame — a v3 client relying on the old serialize-all-bulk
behavior must not slip through). OP_METRICS (OpenMetrics exposition)
and the OP_STATS flag BITS (reset / flight-dump) arrived within v4: a
new op and a widened already-optional flag byte change no existing
frame's meaning, so an old server answers with a routable error rather
than a misread. The placement/migration control plane (OP_PLACEMENT /
OP_PLACEMENT_ANNOUNCE / OP_MIGRATE_PULL / OP_MIGRATE_PUSH, round 6)
arrived the same way — and every one of them is additionally
*application-idempotent* (epoch-monotonic announce, per-epoch cached
pull, batch-deduped push), so the client may retry them even post-send
without violating the at-most-once admission contract. Semantic changes to an existing frame always bump the
version: a silent misread loses decisions, the strict version check
fails loudly instead.

Trace context (within v4, same compatibility posture as OP_METRICS):
a sampled request may carry a 25-byte trace tail —
``[u64 trace_hi][u64 trace_lo][u64 parent span][u8 flags]`` — appended
after the payload. Scalar keyed frames signal it with :data:`TRACE_FLAG`
(bit 7) on the op byte; ACQUIRE_MANY signals it with flags bit 4. An
old peer stays safe on BOTH lanes: a flagged scalar op decodes as
"unknown op 129" — a routable error, never a misparse (clients latch
off stamping and retry bare on seeing it) — while an old bulk decoder
reads its arrays by explicit counts and simply never looks at the tail,
so traced bulk frames interoperate unchanged. OP_TRACES (Chrome-trace
JSON export) is a new op on the existing layout, routable-error on old
servers like OP_METRICS.

Deadline tail (within v4, same posture): a scalar request may carry an
8-byte relative deadline — ``[f64 deadline_s]`` — appended after the
payload (BEFORE any trace tail) and signalled with :data:`DEADLINE_FLAG`
(op-byte bit 6). The value is the client's remaining budget in seconds,
deliberately *relative*: client and server clocks never compare
(invariant 1). A server strips it and sheds the request — routable
"deadline exceeded" error, store untouched — when its own queueing has
already consumed the budget, instead of answering the dead. An old
server answers the flagged op with a routable "unknown op" error and
the client latches deadline stamping off for the connection (the trace
latch's posture); the native C front-end routes flagged scalar ops to
the Python passthrough lane, which speaks this dialect.

Attempt tail (within v4, same posture — the retry-storm defense,
docs/DESIGN.md §24): a RETRY of a scalar request may carry a 1-byte
attempt counter — ``[u8 attempt]`` — appended after the payload
(BEFORE the deadline tail, which rides before the trace tail) and
signalled with :data:`ATTEMPT_FLAG` (op-byte bit 5). First attempts
are never stamped, so the healthy path stays byte-identical to plain
v4; the counter saturates at 255. A server under retry-shed denies
flagged work with a routable error before the store is touched; an
old server answers the flagged op with a routable "unknown op" error
and the client latches attempt stamping off for the connection —
independently of the deadline latch, each tail degrades alone. The
bulk lane signals the SAME defense with ``BULK_FLAG_DEADLINE`` (flags
bit 5): a 9-byte ``[f64 deadline_s][u8 attempt]`` tail after the
tenant extension, before any trace tail — old bulk decoders read
their arrays by explicit counts and never look at it, so no latch is
needed on that lane.

Tenant extension (within v4, OP_METRICS posture — the token-denominated
admission plane, runtime/admission.py, DESIGN.md §15):

- ``OP_ACQUIRE_H`` is hierarchical (tenant → key) weighted-cost
  admission: the OP_ACQUIRE payload followed by a tenant extension —
  ``[u16 tlen][tenant utf-8][f64 tenant_a][f64 tenant_b][u8 priority]``
  (``_HIER_TAIL`` after the tenant id) — decided grant-iff-both-levels
  against the child ``(a, b)`` bucket AND the parent tenant
  ``(tenant_a, tenant_b)`` bucket in one fused kernel launch. A new op
  on the existing frame layout: an old server answers a routable
  "unknown op" error (never a misparse) and the client latches tenant
  stamping off for the connection, falling back to child-only flat
  admission (counted — availability over tenant-budget accuracy, the
  degraded-mode posture of invariant 9). The native C front-end routes
  op 19 to the Python passthrough lane (its scalar switch names only
  the ops it fast-paths — drl-check's ``wire-hier`` rule pins this).
- ``BULK_KIND_HBUCKET`` (table-kind bits value 3) is the bulk edition:
  one ACQUIRE_MANY frame carries ONE tenant's rows, with the same
  tenant extension appended after the counts array (before any trace
  tail). Old bulk decoders answer a routable "unknown bulk kind 3"
  error; the C bulk fast lane's kind gate routes it to Python.
- ``priority`` is the request's priority class
  (:mod:`~.runtime.admission`: 0 interactive / 1 batch / 2 scavenger).
  It never changes a healthy-path decision; envelope serving (drain
  windows, parked handoffs, degraded fallback) honors the shed order —
  scavenger sheds first, the envelope is spent on interactive.

Reservation lane (within v4, OP_METRICS posture — streaming token
costs, :mod:`~.runtime.reservations`, DESIGN.md §18): ``OP_RESERVE`` /
``OP_SETTLE`` carry u32-length-prefixed JSON like the other control
ops (``TEXT_OPS``) and reply RESP_TEXT JSON. Both are *application-
idempotent by reservation id* — a retried reserve of a granted id
replays the recorded decision without a second debit, a retried settle
replays the recorded reconciliation — so both sit in the client's
post-send-retryable set. An old server answers either with a routable
unknown-op error; the client latches once per connection and falls
back to plain ``acquire_hierarchical`` at the estimate (counted —
refunds are forgone against that peer, the conservative direction).

Federation lane (within v4, OP_METRICS posture — the WAN lease ledger,
:mod:`~.runtime.federation`, DESIGN.md §20): ``OP_FED_LEASE`` /
``OP_FED_RENEW`` / ``OP_FED_RECLAIM`` carry u32-length-prefixed JSON
(``TEXT_OPS``) and reply RESP_TEXT JSON. All three are *application-
idempotent* — lease and reclaim replay their per-lease-id recorded
results (the OP_RESERVE dedup posture), renew is absorbing by
construction (monotonic admitted totals + epoch-monotonic slice
adoption, the OP_CONFIG discipline) — so every one of them sits in the
client's post-send-retryable set: a WAN retry mid-partition can never
double-grant a slice or double-refund a reclaim. An old home answers
any of them with a routable unknown-op error; the regional client
latches once per connection (counted) and keeps serving from its
current slice until lease expiry, then degrades to its fair-share
envelope — federation unavailability is indistinguishable from a
partition, by design never unlimited and never hard-down.
"""

from __future__ import annotations

import struct

import numpy as np

from distributedratelimiting.redis_tpu.utils.tracing import TraceContext

__all__ = [
    "OP_ACQUIRE", "OP_PEEK", "OP_SYNC", "OP_WINDOW", "OP_PING",
    "OP_SAVE", "OP_STATS", "OP_SEMA", "OP_FWINDOW", "OP_HELLO",
    "OP_ACQUIRE_MANY", "OP_METRICS", "OP_TRACES",
    "OP_PLACEMENT", "OP_PLACEMENT_ANNOUNCE", "OP_MIGRATE_PULL",
    "OP_MIGRATE_PUSH", "OP_CONFIG", "OP_ACQUIRE_H", "OP_RESERVE",
    "OP_SETTLE", "OP_FED_LEASE", "OP_FED_RENEW", "OP_FED_RECLAIM",
    "OP_AUDIT",
    "TEXT_OPS",
    "TRACE_FLAG", "TRACE_TAIL_LEN", "BULK_FLAG_TRACED",
    "DEADLINE_FLAG", "DEADLINE_TAIL_LEN",
    "ATTEMPT_FLAG", "ATTEMPT_TAIL_LEN", "BULK_FLAG_DEADLINE",
    "BULK_DEADLINE_TAIL_LEN",
    "strip_trace", "bulk_trace_tail", "strip_deadline",
    "strip_attempt", "bulk_deadline_tail",
    "STATS_FLAG_RESET", "STATS_FLAG_FLIGHT_DUMP",
    "RESP_DECISION", "RESP_VALUE", "RESP_PAIR", "RESP_EMPTY", "RESP_TEXT",
    "RESP_BULK", "RESP_ERROR",
    "MAX_FRAME", "PROTOCOL_VERSION", "RemoteStoreError",
    "ProtocolVersionError", "op_name",
    "encode_request", "decode_request", "encode_response", "decode_response",
    "decode_hierarchical_request", "bulk_hier_tail",
    "encode_bulk_request", "decode_bulk_request", "encode_bulk_response",
    "bulk_chunk_spans", "KeyBlob", "decode_key_blob",
    "BULK_KIND_BUCKET", "BULK_KIND_WINDOW", "BULK_KIND_FWINDOW",
    "BULK_KIND_HBUCKET", "HIER_TAIL_LEN",
    "BULK_REQ_HEAD_LEN", "BULK_RESP_HEAD_LEN",
    "read_frame", "write_frame",
]

PROTOCOL_VERSION = 4

OP_ACQUIRE = 1
OP_PEEK = 2
OP_SYNC = 3
OP_WINDOW = 4
OP_PING = 5
OP_SAVE = 6    # ≙ Redis BGSAVE: checkpoint the store server-side
OP_STATS = 7   # server + store metrics as JSON text
OP_SEMA = 8    # concurrency semaphore: count = signed delta, a = limit
OP_FWINDOW = 9  # fixed-window acquire: (a, b) = (limit, window_s)
OP_HELLO = 10  # shared-secret auth handshake (≙ Redis AUTH)
OP_ACQUIRE_MANY = 11  # bulk acquire: n keys' decisions in one frame
OP_METRICS = 12  # OpenMetrics text exposition (RESP_TEXT reply). A new
# op on the existing frame layout needs no version bump: an older server
# answers it with a routable unknown-op error, never a misparse.
OP_TRACES = 13  # Chrome-trace-event JSON export of the server's kept
# traces (RESP_TEXT reply); optional one-byte flag: bit 0 drains the
# buffer after export. Same compatibility posture as OP_METRICS.

# -- placement / migration control plane (within v4, OP_METRICS posture:
# new ops on the existing frame layout — an old server answers each with
# a routable unknown-op error, never a misparse; see runtime/placement.py
# and docs/DESIGN.md §12).
OP_PLACEMENT = 14  # fetch the node's adopted placement map (empty
# request → RESP_TEXT JSON: epoch, node_id, slot_owner, overrides,
# parked handoff state; epoch -1 = placement-unaware node).
OP_PLACEMENT_ANNOUNCE = 15  # adopt a placement map (or abort a target
# epoch): [u32 mlen][json] → RESP_VALUE adopted epoch. Epoch-monotonic
# and idempotent at the current epoch; a stale epoch is a routable
# error, so announce retries are always safe.
OP_MIGRATE_PULL = 16  # old owner: export + park the listed slots/keys
# for a target epoch — [u32 mlen][json {target_epoch, slots|keys,
# window_s}] → RESP_TEXT JSON {entries, …}. Idempotent per target epoch
# (a re-delivered pull returns the cached, already-debited export).
OP_MIGRATE_PUSH = 17  # new owner: import one handoff batch —
# [u32 mlen][json {target_epoch, batch, entries}] → RESP_VALUE rows
# applied. Exactly-once per (target_epoch, batch): a re-delivered batch
# is a counted no-op, never a double-apply.
OP_CONFIG = 18  # live config mutation (runtime/liveconfig.py, round 7;
# OP_METRICS posture — a new op on the existing frame layout, routable
# unknown-op error from old servers): [u32 mlen][json] where {} fetches
# the committed rules (RESP_TEXT), {"prepare": rule, "version": v} /
# {"commit": v} / {"abort": v} drive the two-phase mutation
# (RESP_VALUE committed version). Version-monotonic and idempotent at
# every form — the OP_PLACEMENT_ANNOUNCE discipline — so post-send
# retries are always safe.

OP_ACQUIRE_H = 19  # hierarchical (tenant → key) weighted-cost acquire
# (runtime/admission.py; OP_METRICS posture — a new op on the existing
# frame layout, routable unknown-op error from old servers, never a
# misparse): the OP_ACQUIRE payload followed by the tenant extension
# [u16 tlen][tenant][_HIER_TAIL]. Decoded via
# decode_hierarchical_request; decided grant-iff-both-levels with
# parent refund on child deny (both-or-neither state change). The
# native C front-end names the op only to pin its Python-lane
# fallthrough (drl-check wire-hier).

OP_RESERVE = 20  # estimate-reserve-settle, phase 1 (runtime/
# reservations.py; OP_METRICS posture — a new op on the existing frame
# layout, routable unknown-op error from old servers, never a misparse;
# the client latches a fallback to plain acquire_hierarchical at the
# estimate): [u32 mlen][json {rid, tenant, key, estimate?, a, b, ta,
# tb, priority?, ttl_s?}] → RESP_TEXT JSON {granted, reserved,
# remaining, debt, duplicate}. Application-idempotent by reservation
# id (a granted rid's retry replays the recorded decision without a
# second debit), so post-send retries are always safe.
OP_SETTLE = 21  # estimate-reserve-settle, phase 3: [u32 mlen][json
# {rid, tenant, actual}] → RESP_TEXT JSON {outcome, delta, refunded,
# debt}. Idempotent by reservation id — a duplicate settle replays the
# recorded result (outcome "duplicate", zero side effects), which is
# what makes the op post-send-retry-safe. Routed by TENANT like
# OP_ACQUIRE_H (the ledger entry lives with the tenant's owner).

OP_FED_LEASE = 22  # global quota federation, phase 1 (runtime/
# federation.py; OP_METRICS posture — a new op on the existing frame
# layout, routable unknown-op error from old homes, never a misparse):
# [u32 mlen][json {region, lease_id, tenant, demand, total,
# global_cap, global_rate, ttl_s?}] → RESP_TEXT JSON {granted,
# lease_id, epoch, slice: [cap, rate], ttl_s, share, debt,
# duplicate} — `total` is the region's monotonic admitted counter,
# seeding the lease's report baseline. Application-
# idempotent by LEASE ID (a granted lease_id's retry replays the
# recorded grant without a second share debit — the OP_RESERVE
# posture), so WAN post-send retries are always safe.
OP_FED_RENEW = 23  # federation heartbeat + demand report:
# [u32 mlen][json {region, lease_id, tenant, total, demand}] →
# RESP_TEXT JSON {outcome, epoch, slice, ttl_s, charged, refunded,
# debt}. Naturally idempotent: `total` is the region's MONOTONIC
# admitted-token counter (a replayed renew's delta is zero) and slice
# changes carry an epoch the region adopts only forward (the OP_CONFIG
# version discipline) — post-send-retry-safe without a dedup ledger.
OP_FED_RECLAIM = 24  # return a slice to the federation pool:
# [u32 mlen][json {region, lease_id, tenant, total}] → RESP_TEXT JSON
# {outcome, charged, refunded, debt}. Idempotent by lease id — a
# duplicate reclaim replays the recorded result (outcome "duplicate",
# zero side effects: no second share free, no second refund), the
# at-most-once property tests/test_federation.py audits.

OP_AUDIT = 25  # conservation audit plane (runtime/audit.py; OP_METRICS
# posture — a new op on the existing frame layout, routable unknown-op
# error from old servers, never a misparse): [u32 mlen][json {}] or
# {"bundles": n} → RESP_TEXT JSON — the node's conservation-ledger
# snapshot (per-source ε-budget utilization, per-subsystem residues,
# watchdog state) plus, when asked, the newest n black-box incident
# bundles. Read-only (no store mutation, no window reset), so retries
# are trivially safe.

#: Control ops whose request payload is one u32-length-prefixed UTF-8
#: JSON text (rides in the ``key`` slot of encode/decode_request —
#: ensure_ascii JSON, so the strict codec never meets a surrogate).
TEXT_OPS = frozenset((OP_PLACEMENT_ANNOUNCE, OP_MIGRATE_PULL,
                      OP_MIGRATE_PUSH, OP_CONFIG, OP_RESERVE,
                      OP_SETTLE, OP_FED_LEASE, OP_FED_RENEW,
                      OP_FED_RECLAIM, OP_AUDIT))

#: Op-byte bit 7: a 25-byte trace tail (``_TRACE_TAIL``) follows the
#: payload. Only sampled requests carry it; an old server answers the
#: flagged op with a routable "unknown op" error (clients latch off).
TRACE_FLAG = 0x80
_TRACE_TAIL = struct.Struct("<QQQB")  # trace_hi, trace_lo, span_id, flags
TRACE_TAIL_LEN = _TRACE_TAIL.size
#: ACQUIRE_MANY flags bit 4: the same 25-byte tail follows the counts
#: array. Old bulk decoders read by explicit counts and ignore the tail.
BULK_FLAG_TRACED = 0b10000

#: Op-byte bit 6: an 8-byte relative-deadline tail (``_DEADLINE_TAIL``)
#: follows the payload (before any trace tail). Old servers answer the
#: flagged op with a routable "unknown op" error (clients latch off);
#: scalar ops only — the bulk lane stays deadline-free by design (a
#: bulk call is one caller's single decision batch, its timeout is its
#: own).
DEADLINE_FLAG = 0x40
_DEADLINE_TAIL = struct.Struct("<d")  # remaining budget, seconds
DEADLINE_TAIL_LEN = _DEADLINE_TAIL.size

#: Op-byte bit 5: a 1-byte attempt-counter tail (``_ATTEMPT_TAIL``)
#: follows the payload (before the deadline tail — tail order on the
#: wire is attempt, deadline, trace; servers strip trace → deadline →
#: attempt). Stamped only on RETRIES (attempt ≥ 1, saturating at 255):
#: first attempts stay byte-identical to plain v4, and an old server
#: answers the flagged op with a routable "unknown op" error — the
#: client latches attempt stamping off for the connection,
#: independently of the deadline latch.
ATTEMPT_FLAG = 0x20
_ATTEMPT_TAIL = struct.Struct("<B")  # attempt number, saturating u8
ATTEMPT_TAIL_LEN = _ATTEMPT_TAIL.size

#: ACQUIRE_MANY flags bit 5: a 9-byte ``[f64 deadline_s][u8 attempt]``
#: tail follows the payload (after any HBUCKET tenant extension, before
#: any trace tail). The bulk edition of the deadline + attempt tails in
#: one piece — old bulk decoders read their arrays by explicit counts
#: and never look at it, so the bulk lane needs no client latch (the
#: BULK_FLAG_TRACED posture).
BULK_FLAG_DEADLINE = 0b100000
_BULK_DEADLINE_TAIL = struct.Struct("<dB")  # deadline_s, attempt
BULK_DEADLINE_TAIL_LEN = _BULK_DEADLINE_TAIL.size

#: Tenant extension tail (after the ``[u16 tlen][tenant]`` id):
#: parent-bucket config operands + the request's priority class.
#: Rides OP_ACQUIRE_H (after the OP_ACQUIRE-shaped payload) and
#: BULK_KIND_HBUCKET ACQUIRE_MANY frames (after the counts array,
#: before any trace tail).
_HIER_TAIL = struct.Struct("<ddB")  # tenant_a, tenant_b, priority
HIER_TAIL_LEN = _HIER_TAIL.size

#: OP_STATS flag bits (the optional one-byte payload): bit 0 resets the
#: serving/stage latency windows after the snapshot; bit 1 asks the
#: flight recorder for an explicit JSONL dump (the ``OP_SAVE``-style
#: operator trigger — the dump path comes back in the stats payload).
STATS_FLAG_RESET = 1
STATS_FLAG_FLIGHT_DUMP = 2

_OP_NAMES = {
    OP_ACQUIRE: "acquire",
    OP_PEEK: "peek",
    OP_SYNC: "sync_counter",
    OP_WINDOW: "window_acquire",
    OP_PING: "ping",
    OP_SAVE: "save",
    OP_STATS: "stats",
    OP_SEMA: "sema",
    OP_FWINDOW: "fixed_window_acquire",
    OP_HELLO: "hello",
    OP_ACQUIRE_MANY: "acquire_many",
    OP_METRICS: "metrics",
    OP_TRACES: "traces",
    OP_PLACEMENT: "placement",
    OP_PLACEMENT_ANNOUNCE: "placement_announce",
    OP_MIGRATE_PULL: "migrate_pull",
    OP_MIGRATE_PUSH: "migrate_push",
    OP_CONFIG: "config",
    OP_ACQUIRE_H: "acquire_hierarchical",
    OP_RESERVE: "reserve",
    OP_SETTLE: "settle",
    OP_FED_LEASE: "fed_lease",
    OP_FED_RENEW: "fed_renew",
    OP_FED_RECLAIM: "fed_reclaim",
    OP_AUDIT: "audit",
}


def op_name(op: int) -> str:
    """Human-readable op name (used by the wire-level profiler)."""
    return _OP_NAMES.get(op, f"op{op}")


RESP_DECISION = 64
RESP_VALUE = 65
RESP_PAIR = 66
RESP_EMPTY = 67
RESP_TEXT = 68
RESP_BULK = 69
RESP_ERROR = 127

#: Upper bound on a frame body; a peer announcing more is protocol-broken
#: (or hostile) and the connection is dropped rather than buffered.
MAX_FRAME = 1 << 20

_HDR = struct.Struct("<IBIB")         # length covers [version][seq][op][payload]
_VER_SEQ_OP = struct.Struct("<BIB")
_BODY_OFF = _VER_SEQ_OP.size          # payload offset inside a frame body
_DECISION = struct.Struct("<Bd")
_VALUE = struct.Struct("<d")
_PAIR = struct.Struct("<dd")
_KEYED = struct.Struct("<H")
_TEXTLEN = struct.Struct("<I")
_ACQ_TAIL = struct.Struct("<idd")
_F64x2 = struct.Struct("<dd")


class RemoteStoreError(RuntimeError):
    """Server-side failure relayed to the client (≙ a Redis script error
    surfaced through ``ScriptEvaluateAsync``)."""


class ProtocolVersionError(RemoteStoreError):
    """Peer speaks a different protocol revision; the frame was not
    interpreted past its version byte."""


def _check_version(ver: int) -> None:
    if ver != PROTOCOL_VERSION:
        raise ProtocolVersionError(
            f"protocol version mismatch: peer speaks v{ver}, "
            f"this build speaks v{PROTOCOL_VERSION}"
        )


def _keyed(key: str, tail: bytes) -> bytes:
    # surrogateescape: byte-identity keys round-trip through str (the
    # serving side treats keys as bytes — see the ACQUIRE_MANY notes).
    kb = key.encode("utf-8", "surrogateescape")
    if len(kb) > 0xFFFF:
        raise ValueError("key exceeds 65535 utf-8 bytes")
    return _KEYED.pack(len(kb)) + kb + tail


def _split_key(payload: bytes) -> tuple[str, bytes]:
    (klen,) = _KEYED.unpack_from(payload, 0)
    # surrogateescape, matching _keyed: a byte-identity key admitted by
    # the bulk lane must round-trip through scalar ops (PEEK/SYNC/
    # single ACQUIRE) too, not error only there.
    key = payload[2:2 + klen].decode("utf-8", "surrogateescape")
    return key, payload[2 + klen:]


def _codepoint_truncate(mb: bytes, limit: int) -> bytes:
    """Truncate utf-8 bytes to ``limit`` on a codepoint boundary."""
    if len(mb) <= limit:
        return mb
    return mb[:limit].decode("utf-8", "ignore").encode("utf-8")


def encode_request(seq: int, op: int, key: str = "", count: int = 0,
                   a: float = 0.0, b: float = 0.0,
                   trace=None, deadline_s: "float | None" = None,
                   hier: "tuple[str, float, float, int] | None" = None,
                   attempt: int = 0) -> bytes:
    if op == OP_ACQUIRE_H:
        # Hierarchical acquire: the OP_ACQUIRE payload followed by the
        # tenant extension [u16 tlen][tenant][_HIER_TAIL]. `hier` is
        # (tenant, tenant_a, tenant_b, priority).
        if hier is None:
            raise ValueError("OP_ACQUIRE_H requires the tenant extension")
        tenant, ta, tb, priority = hier
        payload = (_keyed(key, _ACQ_TAIL.pack(count, a, b))
                   + _keyed(tenant, _HIER_TAIL.pack(ta, tb,
                                                    priority & 0xFF)))
    elif op in (OP_ACQUIRE, OP_WINDOW, OP_SEMA, OP_FWINDOW):
        payload = _keyed(key, _ACQ_TAIL.pack(count, a, b))
    elif op in (OP_PEEK, OP_SYNC):
        payload = _keyed(key, _F64x2.pack(a, b))
    elif op == OP_HELLO:
        payload = _keyed(key, b"")  # key carries the auth token
    elif op in (OP_STATS, OP_TRACES):
        # Optional one-byte flag bitmask. STATS (STATS_FLAG_*): bit 0
        # resets the serving/stage latency windows after snapshotting
        # (steady-state measurement), bit 1 triggers a flight-recorder
        # dump. TRACES: bit 0 drains the trace buffer after export.
        # Absent byte = plain snapshot/export.
        payload = bytes([count & 0xFF]) if count else b""
    elif op in TEXT_OPS:
        # Control-plane JSON rides in the key slot with the u32 length
        # prefix RESP_TEXT already uses (migration blobs outgrow the u16
        # keyed header); bounded by MAX_FRAME like every frame.
        mb = key.encode("utf-8")
        if _BODY_OFF + _TEXTLEN.size + len(mb) > MAX_FRAME:
            raise ValueError(
                f"control payload of {len(mb)} bytes exceeds MAX_FRAME; "
                "chunk the migration batch")
        payload = _TEXTLEN.pack(len(mb)) + mb
    elif op in (OP_PING, OP_SAVE, OP_METRICS, OP_PLACEMENT):
        payload = b""
    else:
        raise ValueError(f"unknown op {op}")
    if attempt:
        # Tail order is fixed: attempt first, then deadline, trace last
        # — the server strips trace (bit 7), then deadline (bit 6), then
        # attempt (bit 5). First attempts (attempt == 0) never stamp, so
        # the healthy path stays byte-identical to plain v4.
        op |= ATTEMPT_FLAG
        payload += _ATTEMPT_TAIL.pack(min(int(attempt), 0xFF))
    if deadline_s is not None:
        # Tail order is fixed: deadline first, trace last — the server
        # strips trace (bit 7), then deadline (bit 6). Frames without
        # either stay byte-identical to plain v4.
        op |= DEADLINE_FLAG
        payload += _DEADLINE_TAIL.pack(deadline_s)
    if trace is not None:
        # Sampled request: append the 25-byte trace tail and set the
        # op-byte flag. Untraced frames stay byte-identical to plain v4.
        op |= TRACE_FLAG
        payload += _TRACE_TAIL.pack(trace[0], trace[1], trace[2],
                                    trace[3] & 0xFF)
    return _HDR.pack(_BODY_OFF + len(payload), PROTOCOL_VERSION, seq, op) + payload


def strip_trace(body: bytes):
    """Split a scalar frame body's trace tail: returns ``(plain_body,
    TraceContext | None)`` where ``plain_body`` is byte-identical to the
    frame an untraced peer would have sent (op flag cleared, tail
    removed). The server calls this BEFORE :func:`decode_request`, which
    stays strict — on an old server the flagged op raises the routable
    "unknown op" error instead (never a misparse)."""
    if len(body) < _BODY_OFF or not body[5] & TRACE_FLAG:
        return body, None
    if len(body) < _BODY_OFF + TRACE_TAIL_LEN:
        raise RemoteStoreError("truncated trace tail")
    hi, lo, span, flags = _TRACE_TAIL.unpack_from(body,
                                                  len(body) - TRACE_TAIL_LEN)
    plain = (body[:5] + bytes([body[5] & ~TRACE_FLAG])
             + body[_BODY_OFF:len(body) - TRACE_TAIL_LEN])
    return plain, TraceContext(hi, lo, span, flags)


def strip_deadline(body: bytes) -> "tuple[bytes, float | None]":
    """Split a scalar frame body's deadline tail: ``(plain_body,
    deadline_s | None)``. Call AFTER :func:`strip_trace` (the trace tail
    rides last). Same strictness posture: an old server never reaches
    here — the flagged op raises its routable "unknown op" error."""
    if len(body) < _BODY_OFF or not body[5] & DEADLINE_FLAG:
        return body, None
    if len(body) < _BODY_OFF + DEADLINE_TAIL_LEN:
        raise RemoteStoreError("truncated deadline tail")
    (deadline_s,) = _DEADLINE_TAIL.unpack_from(
        body, len(body) - DEADLINE_TAIL_LEN)
    plain = (body[:5] + bytes([body[5] & ~DEADLINE_FLAG])
             + body[_BODY_OFF:len(body) - DEADLINE_TAIL_LEN])
    return plain, deadline_s


def strip_attempt(body: bytes) -> "tuple[bytes, int]":
    """Split a scalar frame body's attempt tail: ``(plain_body,
    attempt)`` — attempt 0 when the flag is clear (a first attempt, or
    a peer not speaking the dialect). Call AFTER :func:`strip_deadline`
    (the attempt tail is stamped first, so it sits innermost). Same
    strictness posture as the other tails: an old server never reaches
    here — the flagged op raises its routable "unknown op" error."""
    if len(body) < _BODY_OFF or not body[5] & ATTEMPT_FLAG:
        return body, 0
    if len(body) < _BODY_OFF + ATTEMPT_TAIL_LEN:
        raise RemoteStoreError("truncated attempt tail")
    (attempt,) = _ATTEMPT_TAIL.unpack_from(body,
                                           len(body) - ATTEMPT_TAIL_LEN)
    plain = (body[:5] + bytes([body[5] & ~ATTEMPT_FLAG])
             + body[_BODY_OFF:len(body) - ATTEMPT_TAIL_LEN])
    return plain, attempt


def decode_request(frame: bytes) -> tuple[int, int, str, int, float, float]:
    """Returns ``(seq, op, key, count, a, b)``."""
    ver, seq, op = _VER_SEQ_OP.unpack_from(frame, 0)
    _check_version(ver)
    body = frame[_BODY_OFF:]
    if op in (OP_ACQUIRE, OP_WINDOW, OP_SEMA, OP_FWINDOW):
        key, tail = _split_key(body)
        count, a, b = _ACQ_TAIL.unpack(tail)
        return seq, op, key, count, a, b
    if op in (OP_PEEK, OP_SYNC):
        key, tail = _split_key(body)
        a, b = _F64x2.unpack(tail)
        return seq, op, key, 0, a, b
    if op == OP_HELLO:
        token, _ = _split_key(body)
        return seq, op, token, 0, 0.0, 0.0
    if op in (OP_STATS, OP_TRACES):
        return seq, op, "", (body[0] if body else 0), 0.0, 0.0
    if op in TEXT_OPS:
        (mlen,) = _TEXTLEN.unpack_from(body, 0)
        return seq, op, body[4:4 + mlen].decode("utf-8"), 0, 0.0, 0.0
    if op in (OP_PING, OP_SAVE, OP_METRICS, OP_PLACEMENT):
        return seq, op, "", 0, 0.0, 0.0
    if op == OP_ACQUIRE_MANY:
        raise RemoteStoreError(
            "ACQUIRE_MANY frames decode via decode_bulk_request")
    if op == OP_ACQUIRE_H:
        raise RemoteStoreError(
            "ACQUIRE_H frames decode via decode_hierarchical_request")
    raise RemoteStoreError(f"unknown op {op}")


def decode_hierarchical_request(frame: bytes
                                ) -> tuple[int, str, int, float, float,
                                           str, float, float, int]:
    """Decode one OP_ACQUIRE_H frame body: returns ``(seq, key, count,
    a, b, tenant, tenant_a, tenant_b, priority)``. Strict like
    :func:`decode_request` — truncation raises the routable error, and
    the caller strips any deadline/trace tails first (the server does,
    in ``handle_frame_body``)."""
    ver, seq, op = _VER_SEQ_OP.unpack_from(frame, 0)
    _check_version(ver)
    if op != OP_ACQUIRE_H:
        raise RemoteStoreError(f"expected ACQUIRE_H, got op {op}")
    body = frame[_BODY_OFF:]
    key, tail = _split_key(body)
    if len(tail) < _ACQ_TAIL.size:
        raise RemoteStoreError("truncated ACQUIRE_H payload")
    count, a, b = _ACQ_TAIL.unpack_from(tail, 0)
    tenant, rest = _split_key(tail[_ACQ_TAIL.size:])
    if len(rest) != HIER_TAIL_LEN:
        raise RemoteStoreError("malformed ACQUIRE_H tenant extension")
    ta, tb, priority = _HIER_TAIL.unpack(rest)
    return seq, key, count, a, b, tenant, ta, tb, priority


def encode_response(seq: int, kind: int, *vals) -> bytes:
    if kind == RESP_DECISION:
        payload = _DECISION.pack(1 if vals[0] else 0, float(vals[1]))
    elif kind == RESP_VALUE:
        payload = _VALUE.pack(float(vals[0]))
    elif kind == RESP_PAIR:
        payload = _PAIR.pack(float(vals[0]), float(vals[1]))
    elif kind == RESP_EMPTY:
        payload = b""
    elif kind == RESP_ERROR:
        mb = _codepoint_truncate(str(vals[0]).encode("utf-8"), 0xFFFF)
        payload = _KEYED.pack(len(mb)) + mb
    elif kind == RESP_TEXT:
        # u32 length: a large payload (e.g. MeshBucketStore stats with many
        # tiers) must never be silently truncated into undecodable JSON —
        # oversize is a loud error instead, bounded by MAX_FRAME.
        mb = str(vals[0]).encode("utf-8")
        if _BODY_OFF + _TEXTLEN.size + len(mb) > MAX_FRAME:
            raise ValueError(
                f"text payload of {len(mb)} bytes exceeds MAX_FRAME"
            )
        payload = _TEXTLEN.pack(len(mb)) + mb
    else:
        raise ValueError(f"unknown response kind {kind}")
    return _HDR.pack(_BODY_OFF + len(payload), PROTOCOL_VERSION, seq, kind) + payload


def decode_response(frame: bytes) -> tuple[int, int, tuple]:
    """Returns ``(seq, kind, values)``; server-side failures travel as
    ``(RESP_ERROR, (message,))`` so the client can fail just that future.
    Raises only for protocol-level breakage (version mismatch)."""
    ver, seq, kind = _VER_SEQ_OP.unpack_from(frame, 0)
    _check_version(ver)
    body = frame[_BODY_OFF:]
    if kind == RESP_DECISION:
        granted, remaining = _DECISION.unpack(body)
        return seq, kind, (bool(granted), remaining)
    if kind == RESP_VALUE:
        return seq, kind, _VALUE.unpack(body)
    if kind == RESP_PAIR:
        return seq, kind, _PAIR.unpack(body)
    if kind == RESP_EMPTY:
        return seq, kind, ()
    if kind == RESP_ERROR:
        (mlen,) = _KEYED.unpack_from(body, 0)
        return seq, kind, (body[2:2 + mlen].decode("utf-8"),)
    if kind == RESP_TEXT:
        (mlen,) = _TEXTLEN.unpack_from(body, 0)
        return seq, kind, (body[4:4 + mlen].decode("utf-8"),)
    if kind == RESP_BULK:
        return seq, kind, _decode_bulk_response_body(body)
    raise RemoteStoreError(f"unknown response kind {kind}")


# -- bulk acquire (OP_ACQUIRE_MANY / RESP_BULK) -----------------------------

_BULK_REQ_HEAD = struct.Struct("<BddI")   # flags, capacity, fill_rate, n
_BULK_RESP_HEAD = struct.Struct("<BI")    # flags, n

#: Named head widths so the native bulk lane's C mirror (kBulkReqHead /
#: kBulkRespHead in frontend.cc) is diffable by drl-check — this module
#: stays the normative layout (docs/DESIGN.md §10).
BULK_REQ_HEAD_LEN = _BULK_REQ_HEAD.size
BULK_RESP_HEAD_LEN = _BULK_RESP_HEAD.size

#: Per-request wire overhead in an ACQUIRE_MANY frame: u16 klen + u32 count.
BULK_PER_KEY_OVERHEAD = 6
#: Default per-frame payload budget for client-side chunking — headroom
#: under MAX_FRAME for the frame header + bulk head.
BULK_CHUNK_BUDGET = MAX_FRAME - 64

_FLAG_WITH_REMAINING = 1

#: Bulk table kinds (flags bits 1-2): which table family decides the frame.
BULK_KIND_BUCKET = 0
BULK_KIND_WINDOW = 1
BULK_KIND_FWINDOW = 2
#: Hierarchical tenant → key buckets (runtime/admission.py): the frame
#: decides ONE tenant's rows — grant iff both the row's child bucket
#: and the shared parent tenant bucket admit. Carries the tenant
#: extension ``[u16 tlen][tenant][_HIER_TAIL]`` after the counts array
#: (before any trace tail; old decoders read arrays by explicit counts
#: and answer a routable "unknown bulk kind 3" error — never a
#: misparse; the C bulk fast lane's kind gate routes it to Python).
BULK_KIND_HBUCKET = 3
_KIND_SHIFT = 1
_KIND_MASK = 0b110
#: Flags bit 3: this frame is a continuation chunk of the immediately
#: preceding bulk frame on the connection — the server must decide it
#: AFTER that frame (duplicate keys spanning a chunk boundary keep request
#: order). Independent bulk frames (bit clear) run fully concurrent.
_FLAG_CHAINED = 0b1000


def bulk_chunk_spans(key_blob_lens: "np.ndarray",
                     budget: int | None = None) -> list[tuple[int, int]]:
    """Split a bulk call into contiguous ``[start, end)`` spans whose
    encoded ACQUIRE_MANY payloads each fit ``budget`` bytes (default
    :data:`BULK_CHUNK_BUDGET`, read at call time). Vectorized (cumsum +
    searchsorted per span) so a million-key bulk costs a handful of numpy
    ops, not a Python loop."""
    if budget is None:
        budget = BULK_CHUNK_BUDGET
    n = len(key_blob_lens)
    if n == 0:
        return []
    cum = np.cumsum(np.asarray(key_blob_lens, np.int64)
                    + BULK_PER_KEY_OVERHEAD)
    spans: list[tuple[int, int]] = []
    start, base = 0, 0
    while start < n:
        end = int(np.searchsorted(cum, base + budget, side="right"))
        if end == start:
            end = start + 1  # one oversized key still fits a frame alone
        spans.append((start, end))
        base = int(cum[end - 1])
        start = end
    return spans


def encode_bulk_request(seq: int, key_blobs: "Sequence[bytes]",
                        counts: "np.ndarray", capacity: float,
                        fill_rate: float, *,
                        with_remaining: bool = True,
                        kind: int = BULK_KIND_BUCKET,
                        chained: bool = False,
                        trace=None, hier=None,
                        deadline_s: "float | None" = None,
                        attempt: int = 0) -> bytes:
    """Encode one ACQUIRE_MANY frame from per-key byte blobs. A thin
    wrapper over :func:`encode_bulk_request_span` (ONE definition of the
    frame layout — the two entry points must stay wire-identical);
    ``kind`` selects the table family (bucket/window/fixed-window/
    hierarchical); for windows the (capacity, fill_rate) slots carry
    (limit, window_s); ``hier`` is the HBUCKET tenant extension
    ``(tenant, tenant_a, tenant_b, priority)``."""
    n = len(key_blobs)
    klens = np.fromiter((len(b) for b in key_blobs), np.int64, n)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum(klens, out=offsets[1:])
    return encode_bulk_request_span(
        seq, b"".join(key_blobs), offsets, klens,
        np.asarray(counts, np.uint32), 0, n, capacity, fill_rate,
        with_remaining=with_remaining, kind=kind, chained=chained,
        trace=trace, hier=hier, deadline_s=deadline_s, attempt=attempt)


def encode_bulk_request_span(seq: int, blob: bytes, offsets: "np.ndarray",
                             klens: "np.ndarray", counts: "np.ndarray",
                             start: int, end: int, capacity: float,
                             fill_rate: float, *,
                             with_remaining: bool = True,
                             kind: int = BULK_KIND_BUCKET,
                             chained: bool = False,
                             trace=None, hier=None,
                             deadline_s: "float | None" = None,
                             attempt: int = 0) -> bytes:
    """Encode one ACQUIRE_MANY chunk by SLICING a whole-call key blob —
    the client-side half of the zero-copy lane. ``_bulk_prepare`` joins
    and encodes the call's keys once; each chunk's payload is then two
    array casts and one bytes slice instead of a per-key join (the
    per-chunk ``b"".join(key_blobs[s:e])`` plus its length genexpr were
    the client's top profile entries at 131K keys/call). ``hier``
    (required iff ``kind == BULK_KIND_HBUCKET``) is the frame's tenant
    extension ``(tenant, tenant_a, tenant_b, priority)``."""
    n = end - start
    kl = klens[start:end]
    if n and int(kl.max()) > 0xFFFF:
        raise ValueError("key exceeds 65535 utf-8 bytes")
    if kind not in (BULK_KIND_BUCKET, BULK_KIND_WINDOW, BULK_KIND_FWINDOW,
                    BULK_KIND_HBUCKET):
        raise ValueError(f"unknown bulk kind {kind}")
    if (hier is not None) != (kind == BULK_KIND_HBUCKET):
        raise ValueError(
            "the tenant extension rides exactly the HBUCKET kind")
    flags = ((_FLAG_WITH_REMAINING if with_remaining else 0)
             | (kind << _KIND_SHIFT)
             | (_FLAG_CHAINED if chained else 0)
             | (BULK_FLAG_TRACED if trace is not None else 0)
             | (BULK_FLAG_DEADLINE if deadline_s is not None else 0))
    parts = [
        _BULK_REQ_HEAD.pack(flags, capacity, fill_rate, n),
        kl.astype("<u2").tobytes(),
        blob[offsets[start]:offsets[end]],
        np.asarray(counts[start:end], "<u4").tobytes(),
    ]
    if hier is not None:
        # Tenant extension AFTER the arrays (an old decoder reads them
        # by explicit counts and rejects the kind before reaching it),
        # BEFORE any trace tail (which always rides last).
        tenant, ta, tb, priority = hier
        parts.append(_keyed(tenant, _HIER_TAIL.pack(ta, tb,
                                                    priority & 0xFF)))
    if deadline_s is not None:
        # Deadline + attempt tail AFTER the tenant extension, BEFORE
        # any trace tail (which always rides last). Old decoders read
        # arrays by explicit counts and never reach it.
        parts.append(_BULK_DEADLINE_TAIL.pack(deadline_s,
                                              min(int(attempt), 0xFF)))
    if trace is not None:
        # The trace tail rides AFTER the arrays: an old decoder reads
        # them by explicit counts and never touches it.
        parts.append(_TRACE_TAIL.pack(trace[0], trace[1], trace[2],
                                      trace[3] & 0xFF))
    payload = b"".join(parts)
    length = _BODY_OFF + len(payload)
    if length > MAX_FRAME:
        raise ValueError(
            f"bulk frame of {length} bytes exceeds MAX_FRAME; chunk the "
            "call with bulk_chunk_spans()"
        )
    return _HDR.pack(length, PROTOCOL_VERSION, seq, OP_ACQUIRE_MANY) + payload


def decode_bulk_request(frame: bytes, *, as_view: bool = False
                        ) -> tuple[int, "list[str] | KeyBlob", "np.ndarray",
                                   float, float, bool, int]:
    """Returns ``(seq, keys, counts[i64], a, b, with_remaining, kind)``.

    ``as_view=True`` returns the keys as a :class:`KeyBlob` instead of a
    list — the server's hot path, where a device-backed store resolves
    keys straight from the blob in native code and Python never
    materializes per-key strings."""
    ver, seq, op = _VER_SEQ_OP.unpack_from(frame, 0)
    _check_version(ver)
    if op != OP_ACQUIRE_MANY:
        raise RemoteStoreError(f"expected ACQUIRE_MANY, got op {op}")
    body = frame[_BODY_OFF:]
    flags, capacity, fill_rate, n = _BULK_REQ_HEAD.unpack_from(body, 0)
    off = _BULK_REQ_HEAD.size
    klens = np.frombuffer(body, "<u2", n, off).astype(np.int64)
    off += 2 * n
    total = int(klens.sum())
    blob = body[off:off + total]
    if len(blob) != total:
        raise RemoteStoreError("truncated ACQUIRE_MANY key blob")
    counts = np.frombuffer(body, "<u4", n, off + total).astype(np.int64)
    if as_view:
        offsets = np.zeros(n + 1, np.int64)
        np.cumsum(klens, out=offsets[1:])
        keys: "list[str] | KeyBlob" = KeyBlob(blob, offsets)
    else:
        # surrogateescape, like the view's lazy decode: the documented
        # contract is byte-identity keys on every lane — the two decode
        # modes must not disagree about which frames are valid.
        keys = decode_key_blob(blob, klens, errors="surrogateescape")
    kind = (flags & _KIND_MASK) >> _KIND_SHIFT
    if kind not in (BULK_KIND_BUCKET, BULK_KIND_WINDOW, BULK_KIND_FWINDOW,
                    BULK_KIND_HBUCKET):
        # Unreachable while the 2-bit kind field stays fully assigned —
        # kept so narrowing the assignment can never silently misparse.
        raise RemoteStoreError(f"unknown bulk kind {kind}")
    return (seq, keys, counts, capacity, fill_rate,
            bool(flags & _FLAG_WITH_REMAINING), kind)


def bulk_request_chained(body: bytes) -> bool:
    """Peek a bulk frame body's chained bit (the server's dispatch gate —
    cheaper than a full decode). A truncated frame reads unchained; the
    full decode raises the routable error for it."""
    return len(body) > _BODY_OFF and bool(body[_BODY_OFF] & _FLAG_CHAINED)


def bulk_trace_tail(body: bytes) -> "TraceContext | None":
    """Read an ACQUIRE_MANY frame body's trace tail (flags bit 4), or
    ``None`` when absent. The tail sits at the very end of the payload;
    :func:`decode_bulk_request` reads its arrays by explicit counts, so
    the same frame decodes identically with the tail present — the
    old-peer compatibility property the fuzz tests pin down."""
    if (len(body) <= _BODY_OFF + TRACE_TAIL_LEN
            or not body[_BODY_OFF] & BULK_FLAG_TRACED):
        return None
    hi, lo, span, flags = _TRACE_TAIL.unpack_from(body,
                                                  len(body) - TRACE_TAIL_LEN)
    return TraceContext(hi, lo, span, flags)


def bulk_deadline_tail(body: bytes) -> "tuple[float, int] | None":
    """Read an ACQUIRE_MANY frame body's deadline + attempt tail (flags
    bit 5): ``(deadline_s, attempt)``, or ``None`` when absent. The
    tail rides immediately BEFORE any trace tail, so it parses from the
    end like :func:`bulk_trace_tail`; :func:`decode_bulk_request` reads
    its arrays by explicit counts, so the same frame decodes
    identically with the tail present — no old-peer latch on the bulk
    lane, same as traced bulk frames."""
    if (len(body) <= _BODY_OFF + BULK_DEADLINE_TAIL_LEN
            or not body[_BODY_OFF] & BULK_FLAG_DEADLINE):
        return None
    end = len(body)
    if body[_BODY_OFF] & BULK_FLAG_TRACED:
        end -= TRACE_TAIL_LEN
    if end - BULK_DEADLINE_TAIL_LEN < _BODY_OFF:
        raise RemoteStoreError("truncated bulk deadline tail")
    deadline_s, attempt = _BULK_DEADLINE_TAIL.unpack_from(
        body, end - BULK_DEADLINE_TAIL_LEN)
    return deadline_s, attempt


def bulk_hier_tail(body: bytes) -> tuple[str, float, float, int]:
    """Parse an HBUCKET ACQUIRE_MANY frame body's tenant extension:
    ``(tenant, tenant_a, tenant_b, priority)``. The extension sits at a
    FIXED offset — right after the counts array, before any trace tail
    — so it parses forward (the trace tail still parses from the end,
    :func:`bulk_trace_tail`). Truncation raises the routable error; the
    arrays themselves were already validated by
    :func:`decode_bulk_request`."""
    flags, _a, _b, n = _BULK_REQ_HEAD.unpack_from(body, _BODY_OFF)
    off = _BODY_OFF + _BULK_REQ_HEAD.size
    klens = np.frombuffer(body, "<u2", n, off)
    off += 2 * n + int(klens.astype(np.int64).sum()) + 4 * n
    if len(body) < off + _KEYED.size:
        raise RemoteStoreError("truncated HBUCKET tenant extension")
    (tlen,) = _KEYED.unpack_from(body, off)
    off += _KEYED.size
    if len(body) < off + tlen + HIER_TAIL_LEN:
        raise RemoteStoreError("truncated HBUCKET tenant extension")
    tenant = body[off:off + tlen].decode("utf-8", "surrogateescape")
    ta, tb, priority = _HIER_TAIL.unpack_from(body, off + tlen)
    return tenant, ta, tb, priority


class KeyBlob:
    """Zero-copy view of a bulk frame's keys: the concatenated utf-8
    blob plus ``i64[n+1]`` boundary offsets. The serving path hands this
    straight to the native key directory (``dir_resolve_batch`` probes
    the blob in C), so a 100K-key frame costs ZERO Python string
    objects on the device-store hot path. Sequence duck-typing
    (``len``/iteration/indexing, decoding lazily with surrogateescape —
    the same stable-identity-for-any-bytes rule as the native
    front-end's batch lane) keeps every other store working unchanged:
    serial stores just iterate it like the list they used to get."""

    __slots__ = ("blob", "offsets")

    def __init__(self, blob: bytes, offsets: "np.ndarray") -> None:
        self.blob = blob
        # Contiguous i64 is a hard requirement: four native lanes
        # reinterpret this buffer as int64* — a stray int32/strided
        # array would read garbage offsets in C (no Python-level error).
        self.offsets = np.ascontiguousarray(offsets, np.int64)

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i: int) -> str:
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self.blob[self.offsets[i]:self.offsets[i + 1]].decode(
            "utf-8", "surrogateescape")

    def __iter__(self):
        o = self.offsets.tolist()
        blob = self.blob
        for s, e in zip(o, o[1:]):
            yield blob[s:e].decode("utf-8", "surrogateescape")

    def tolist(self) -> list[str]:
        return decode_key_blob(self.blob,
                               np.diff(self.offsets),
                               errors="surrogateescape")


def decode_key_blob(blob: bytes, klens: "np.ndarray", *,
                    errors: str = "strict") -> list[str]:
    """Split a concatenated key blob into strings by per-key lengths —
    one decode for the whole blob on the (overwhelming) ascii fast path.
    Shared by the bulk-frame decoder and the native front-end's batch
    handoff — both pass ``errors="surrogateescape"`` (byte-identity
    keys: a hostile key rate-limits under its own stable identity
    rather than poisoning its batch)."""
    ends = np.cumsum(np.asarray(klens, np.int64))
    starts = ends - klens
    if blob.isascii():
        text = blob.decode("ascii")
        return [text[s:e] for s, e in zip(starts.tolist(), ends.tolist())]
    return [blob[s:e].decode("utf-8", errors)
            for s, e in zip(starts.tolist(), ends.tolist())]


def encode_bulk_response(seq: int, granted: "np.ndarray",
                         remaining: "np.ndarray | None") -> bytes:
    n = len(granted)
    flags = 0 if remaining is None else _FLAG_WITH_REMAINING
    parts = [
        _BULK_RESP_HEAD.pack(flags, n),
        np.packbits(np.asarray(granted, bool), bitorder="little").tobytes(),
    ]
    if remaining is not None:
        parts.append(np.asarray(remaining, "<f4").tobytes())
    payload = b"".join(parts)
    return _HDR.pack(_BODY_OFF + len(payload), PROTOCOL_VERSION, seq,
                     RESP_BULK) + payload


def _decode_bulk_response_body(body: bytes) -> tuple["np.ndarray",
                                                     "np.ndarray | None"]:
    flags, n = _BULK_RESP_HEAD.unpack_from(body, 0)
    off = _BULK_RESP_HEAD.size
    nbits = (n + 7) // 8
    granted = np.unpackbits(
        np.frombuffer(body, np.uint8, nbits, off), bitorder="little",
    )[:n].astype(bool)
    remaining = None
    if flags & _FLAG_WITH_REMAINING:
        remaining = np.frombuffer(body, "<f4", n, off + nbits).astype(
            np.float32)
    return granted, remaining


async def read_frame(reader) -> bytes | None:
    """Read one ``[version][seq][op][payload]`` body; ``None`` on clean
    EOF."""
    import asyncio

    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = struct.unpack("<I", hdr)
    if not _BODY_OFF <= length <= MAX_FRAME:
        raise RemoteStoreError(f"bad frame length {length}")
    try:
        return await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None


def write_frame(writer, data: bytes) -> None:
    writer.write(data)
