"""Python half of the native serving front-end (``native/frontend.cc``).

The C++ side owns the sockets: an epoll IO thread accepts connections,
parses the v4 wire protocol, answers PING itself, accumulates per-request
ACQUIRE/WINDOW/FWINDOW frames into micro-batches (timerfd deadline +
flush-on-idle + max-batch, mirroring :class:`~.batcher.MicroBatcher`'s
policy), and encodes/writes every reply natively. This module is the
*decision* half: a pump thread blocks in ``fe_wait`` (GIL released) and
dispatches each batch onto the server's asyncio loop as ONE store bulk
call — so Python cost is per-flush, not per-request. The hot set is the
four per-request decision ops — ACQUIRE, WINDOW, FWINDOW, and SEMA
(signed-delta semaphore rows batch into ``concurrency_acquire_many``) —
plus, since round 8, OP_ACQUIRE_MANY: bulk frames parse, tier-0-decide,
and encode RESP_BULK in C, and only the residue rows cross here as one
zero-copy KeyBlob batch (``_serve_bulk``). Non-hot ops (HELLO, PEEK,
SYNC, STATS, SAVE, control ops, …) and MALFORMED bulk frames arrive as
passthrough frames and are served by the same
:class:`~.server.BucketStoreServer` handler the asyncio path uses;
:mod:`~.wire` stays the single protocol authority for those shapes.

Why this exists: the per-request serving ceiling of the asyncio socket
path is ~13K req/s/core even with a zero-cost kernel — per-request
framing plus task scheduling, measured in benchmarks/RESULTS.md
("Per-request socket ceiling isolated"). The reference's answer to that
class of cost is the Redis *server* — a C epoll loop. This is that
component for the TPU store.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import threading
import time
from dataclasses import dataclass

import numpy as np

from distributedratelimiting.redis_tpu.runtime import (
    liveconfig,
    placement,
    wire,
)
from distributedratelimiting.redis_tpu.runtime.store import BucketStore
from distributedratelimiting.redis_tpu.utils import faults, log, tracing
from distributedratelimiting.redis_tpu.utils.metrics import (
    LatencyHistogram,
    Tier0Metrics,
)
from distributedratelimiting.redis_tpu.utils.native import (
    URING_OFF,
    URING_ON,
    URING_SQPOLL,
    load_frontend_lib,
)

__all__ = ["NativeFrontend", "Tier0Config", "native_loadgen",
           "native_bulk_loadgen", "uring_probe"]

logger = logging.getLogger(__name__)

#: Accepted spellings of the uring knob (constructor param, env var,
#: CLI) → fe_start_sharded2 transport mode. The C side accepts the same
#: strings in DRL_TPU_URING (uring_mode_from_env) so the two resolution
#: paths can never disagree on a spelling.
_URING_SPELLINGS = {
    "": URING_OFF, "0": URING_OFF, "off": URING_OFF,
    "1": URING_ON, "on": URING_ON, "uring": URING_ON,
    "2": URING_SQPOLL, "sqpoll": URING_SQPOLL,
}


def _resolve_uring_mode(uring: "str | bool | int | None") -> int:
    """Constructor/CLI knob → transport mode. ``None`` defers to the
    ``DRL_TPU_URING`` env var (off when unset) — the conservative
    default that keeps every existing caller on the epoll lane unless
    the operator opts in."""
    import os

    if uring is None:
        uring = os.environ.get("DRL_TPU_URING", "")
    if isinstance(uring, bool):
        return URING_ON if uring else URING_OFF
    if isinstance(uring, int):
        if uring not in (URING_OFF, URING_ON, URING_SQPOLL):
            raise ValueError(f"unknown uring mode {uring!r}")
        return uring
    key = str(uring).strip().lower()
    if key not in _URING_SPELLINGS:
        raise ValueError(
            f"unknown uring mode {uring!r}; use off/on/sqpoll")
    return _URING_SPELLINGS[key]


def uring_probe() -> tuple[bool, str]:
    """Runtime io_uring availability: ``(available, reason)``. Reason is
    human-readable either way (the probe's success string names the
    feature level it verified; failure names the refusing syscall or
    gate — OPERATIONS.md §17 shows the table)."""
    lib = load_frontend_lib()
    if lib is None or not getattr(lib, "has_uring", False):
        return False, ("native front-end library unavailable or "
                       "predates the uring ABI")
    buf = ctypes.create_string_buffer(256)
    ok = lib.fe_uring_probe(buf, len(buf))
    return bool(ok), buf.value.decode("utf-8", "replace")


@dataclass(frozen=True)
class Tier0Config:
    """Knobs of the front-end's tier-0 admission cache (see
    docs/OPERATIONS.md "Tier-0 approximate admission" for the decision
    table and the epsilon derivation). The budget policy mirrors
    :func:`~.models.approximate.headroom_budget`; the documented
    over-admission bound per key is
    ``overadmit_epsilon(headroom_budget(capacity, ...),
    fill_rate, sync_interval_s)``."""

    #: Replica table slots PER SHARD SLICE (rounded up to a power of
    #: two; any shard can see any key, so each slice is full-size).
    #: Memory is bounded: shards × slots × (entry + key ≤ 256 B) —
    #: ~1.5 MB per shard at the default.
    slots: int = 4096
    #: Fraction of the last-synced balance granted as local headroom.
    budget_fraction: float = 0.5
    #: Below this budget a key is not hosted locally (small buckets keep
    #: exact per-request semantics — also what keeps tier-0 semantically
    #: invisible to low-capacity workloads like the parity fuzz).
    min_budget: float = 64.0
    #: Budget ceiling (bounds epsilon for huge-capacity buckets).
    max_budget: float = float(1 << 20)
    #: Sync pump cadence: how often local grants drain into the store.
    sync_interval_s: float = 0.02
    #: Max age of the envelope a local decision may be served from.
    #: Generous relative to the sync interval on purpose: during a device
    #: outage this is how long tier-0 keeps answering from its last-known
    #: envelope instead of stalling behind the dead store.
    max_stale_s: float = 2.0
    #: Idle replica eviction.
    ttl_s: float = 30.0

# Bound to locals for the batch-group dispatch; wire.py stays the single
# source of the values (frontend.cc mirrors them and is covered by the
# protocol-parity tests).
_OP_BUCKET = wire.OP_ACQUIRE
_OP_WINDOW = wire.OP_WINDOW
_OP_FWINDOW = wire.OP_FWINDOW
_OP_SEMA = wire.OP_SEMA

#: fe_complete's kRowSkip sentinel (frontend.cc): the row was already
#: answered from Python via fe_send (per-row placement error on the
#: batch lane) — C sends no decision reply and skips the tier-0 install.
_ROW_SKIP = 2


class NativeFrontend:
    """Own the C++ listener for a :class:`~.server.BucketStoreServer`.

    Lifecycle: constructed inside ``server.start()`` on the running loop;
    ``aclose()`` (from ``server.aclose()``) stops the IO thread, fails the
    pump out of its wait, and frees the handle.
    """

    def __init__(self, server, *, host: str, port: int,
                 max_batch: int = 4096, deadline_us: int = 300,
                 tier0: "Tier0Config | bool | None" = None,
                 bulk: bool = True, shards: int = 1,
                 pin_shards: bool = False,
                 uring: "str | bool | int | None" = None) -> None:
        lib = load_frontend_lib()
        if lib is None:
            raise RuntimeError(
                "native front-end unavailable (no compiler or "
                "DRL_TPU_NO_NATIVE=1) — use the asyncio server")
        self._lib = lib
        self._server = server
        self._loop = asyncio.get_running_loop()
        # The C side binds numeric IPv4 only — resolve names here so
        # --host localhost works exactly like the asyncio listener.
        # (IPv6 listeners are asyncio-path-only for now.)
        import socket

        try:
            infos = socket.getaddrinfo(host, port, socket.AF_INET,
                                       socket.SOCK_STREAM)
            numeric_host = infos[0][4][0]
        except socket.gaierror as exc:
            raise OSError(
                f"native front-end cannot resolve {host!r} as IPv4: {exc}"
            ) from exc
        # Multi-shard serving (round 11): N epoll shards accepting on
        # SO_REUSEPORT listeners bound to ONE port, each with its own IO
        # thread, connection table, and pump thread below; the tier-0
        # replica table is partitioned by key hash so the node keeps ONE
        # envelope (docs/DESIGN.md §16). A stale .so without the shard
        # ABI serves single-shard — availability over scale, loudly.
        shards = max(1, int(shards))
        has_shards = getattr(lib, "has_shards", False)
        if shards > 1 and not has_shards:
            logger.warning(
                "native front-end shards=%d requested but the loaded "
                "binary predates the shard ABI; serving single-shard",
                shards)
            shards = 1
        # io_uring transport (round 16): the data plane swaps under the
        # same reply bytes — DESIGN.md §21. Default is epoll unless the
        # knob (param > DRL_TPU_URING env > off) asks otherwise; a
        # stale .so or failed runtime probe falls back loudly, never
        # fails the bind (availability over throughput, same posture as
        # the shard fallback above).
        mode = _resolve_uring_mode(uring)
        has_uring = getattr(lib, "has_uring", False)
        if mode != URING_OFF and not has_uring:
            logger.warning(
                "io_uring transport requested but the loaded binary "
                "predates the uring ABI; serving on epoll")
            mode = URING_OFF
        self.uring_mode = mode
        if has_uring:
            self._h = lib.fe_start_sharded2(
                numeric_host.encode(), port, max_batch, deadline_us,
                1 if server.auth_token is not None else 0, shards,
                1 if pin_shards else 0, mode)
        elif has_shards:
            self._h = lib.fe_start_sharded(
                numeric_host.encode(), port, max_batch, deadline_us,
                1 if server.auth_token is not None else 0, shards,
                1 if pin_shards else 0)
        else:
            self._h = lib.fe_start(
                numeric_host.encode(), port, max_batch, deadline_us,
                1 if server.auth_token is not None else 0)
        if not self._h:
            raise OSError(f"native front-end failed to bind {host}:{port}")
        if mode != URING_OFF:
            # Per-shard fallback is graceful but never silent: name
            # every shard that could not get a ring and why.
            n_uring = int(lib.fe_uring_shards(self._h))
            n_total = int(lib.fe_shard_count(self._h)) if has_shards else 1
            if n_uring < n_total:
                buf = ctypes.create_string_buffer(256)
                for i in range(n_total):
                    if lib.fe_uring_reason(self._h, i, buf,
                                           len(buf)) == 0:
                        logger.warning(
                            "io_uring requested but shard %d fell back "
                            "to epoll: %s", i,
                            buf.value.decode("utf-8", "replace")
                            or "no reason recorded")
            self.uring_shards = n_uring
        else:
            self.uring_shards = 0
        self.port = lib.fe_port(self._h)
        self.host = host
        self._stopping = False
        # Per-shard sub-handles: every fe_* call a pump or serve task
        # makes goes through the shard handle its work arrived on (conn
        # ids, batch ids, and bulk job ids are all per-shard).
        if has_shards:
            self.n_shards = int(lib.fe_shard_count(self._h))
            self._shards = [int(lib.fe_shard(self._h, i))
                            for i in range(self.n_shards)]
        else:
            self.n_shards = 1
            self._shards = [self._h]
        # Per-(shard, connection) tail task for chained ACQUIRE_MANY
        # chunks — the same request-order contract the asyncio server
        # keeps (server.py `bulk_tail`); a connection lives on one shard
        # for its whole life, so the contract stays shard-local. Entries
        # drop when their task is still the tail at completion, so the
        # dict tracks active bulk conns only.
        self._bulk_tails: dict[tuple[int, int], asyncio.Task] = {}
        # Loop tasks still holding the C handle: aclose must drain these
        # BETWEEN fe_stop (no new work) and fe_free (handle invalid) — a
        # straggler batch completing after fe_free would call
        # fe_complete through a dangling pointer.
        self._loop_tasks: set[asyncio.Task] = set()
        # Tier-0 admission cache: decisions served from the C-side replica
        # table; this side runs the sync pump (harvest → bulk debit →
        # ack) that keeps every replica's envelope honest.
        self._tier0: Tier0Config | None = None
        self._t0_task: asyncio.Task | None = None
        self.t0_metrics = Tier0Metrics()
        # Consecutive failed sync rounds — the degraded-mode streak that
        # trips the server's flight recorder (0 while healthy).
        self._t0_fail_streak = 0
        #: drained-but-unreconciled amounts surviving a failed sync round
        #: (degraded mode: carried into the next round, never dropped).
        self._t0_carry: dict[tuple[str, float, float], float] = {}
        if tier0:
            self._tier0_setup(
                tier0 if isinstance(tier0, Tier0Config) else Tier0Config())
        # Native bulk lane (round 8): OP_ACQUIRE_MANY parses, tier-0
        # decides hot rows, and RESP_BULK encodes in C; only the residue
        # crosses here (fe_wait kind 3). Armed explicitly — a new .so
        # under an older pump keeps the round-7 passthrough behavior,
        # and a stale .so under this pump falls back the same way.
        self._bulk_native = bool(bulk) and getattr(lib, "has_bulk", False)
        if bulk and not self._bulk_native:
            logger.warning(
                "native bulk lane requested but the loaded front-end "
                "binary predates the fe_bulk ABI; ACQUIRE_MANY stays on "
                "the passthrough lane")
        self._hot_task: asyncio.Task | None = None
        if self._bulk_native:
            hh = getattr(server, "heavy_hitters", None)
            # One call arms the lane on EVERY shard (the C side fans
            # out) — a frame can never race a half-armed shard mix.
            lib.fe_bulk_configure(self._h, 1, 1, 1 if hh is not None
                                  else 0)
            if hh is not None:
                # The bulk lane's keys never materialize in Python, so
                # the C side aggregates per-frame top-K and this pump
                # offers the survivors to the sketch (the scalar batch
                # lane's offer_many discipline, re-hosted below the
                # ABI). One pump drains every shard's ring, so the
                # sketch keeps whole-node ranks.
                self._hot_task = asyncio.get_running_loop().create_task(
                    self._hot_harvest_loop())
        # One pump thread per shard: fe_wait and its batch/bulk/pt
        # cursors are per-shard state, so each pump drives exactly one
        # shard and the shards never contend a shared C queue.
        self._pumps = [
            threading.Thread(target=self._pump_loop, args=(sh,),
                             daemon=True,
                             name=f"native-frontend-pump-{i}")
            for i, sh in enumerate(self._shards)
        ]
        for p in self._pumps:
            p.start()

    def _tier0_setup(self, cfg: Tier0Config) -> None:
        if not getattr(self._lib, "has_tier0", False):
            logger.warning("tier-0 requested but the loaded front-end "
                           "binary predates the tier-0 ABI; serving "
                           "without it")
            return
        store = self._server.store
        if type(store).debit_many is BucketStore.debit_many:
            # No reconciliation entry point on this store: local grants
            # could never drain back, so the envelope would be a lie.
            logger.warning(
                "tier-0 requested but %s has no debit_many "
                "reconciliation path; serving without tier-0",
                type(store).__name__)
            return
        slots = self._lib.fe_t0_configure(
            self._h, int(cfg.slots), float(cfg.budget_fraction),
            float(cfg.min_budget), float(cfg.max_budget),
            max(1, int(cfg.max_stale_s * 1e3)),
            max(1, int(cfg.ttl_s * 1e3)))
        self._tier0 = cfg
        # Harvest buffers, allocated once: sized so even a full table of
        # max-length (256 B) keys drains in one round, and the pump's
        # per-round cost is the C call, not buffer churn.
        self._t0_blob = ctypes.create_string_buffer(slots * 256)
        self._t0_klens = np.zeros(slots, np.int32)
        self._t0_amounts = np.zeros(slots, np.float64)
        self._t0_caps = np.zeros(slots, np.float64)
        self._t0_rates = np.zeros(slots, np.float64)
        self._t0_task = asyncio.get_running_loop().create_task(
            self._t0_sync_loop())

    def _track_task(self, coro) -> asyncio.Task:
        """Start ``coro`` as a loop task tracked for shutdown draining
        (every task holding the C handle must finish before fe_free).
        Loop-thread only."""
        # Loop-thread only: the pump thread reaches this exclusively
        # through call_soon_threadsafe (_track).
        # drl-check: ok(task-off-loop)
        task = asyncio.ensure_future(coro)
        self._loop_tasks.add(task)
        task.add_done_callback(self._loop_tasks.discard)
        return task

    def _track(self, coro) -> None:
        """Schedule ``coro`` on the loop from the pump thread, tracked
        for shutdown draining."""
        self._loop.call_soon_threadsafe(self._track_task, coro)

    # -- pump thread -------------------------------------------------------

    def _pump_loop(self, sh: int) -> None:
        """One shard's pump: blocks in fe_wait (GIL released) on the
        SHARD handle and dispatches that shard's work onto the loop."""
        lib = self._lib
        while not self._stopping:
            kind = lib.fe_wait(sh, 200)
            if kind == -1:
                break
            try:
                if kind == 1:
                    self._dispatch_batch(sh)
                elif kind == 2:
                    self._dispatch_passthrough(sh)
                elif kind == 3:
                    self._dispatch_bulk(sh)
            except Exception as exc:  # noqa: BLE001 — the pump is the one
                # thread every connection on its shard depends on: it
                # must survive any single bad batch/frame (the items get
                # error replies via fe_fail where possible; the
                # connections stay up).
                log.error_evaluating_kernel(exc)
                if kind == 1:
                    try:
                        self._lib.fe_fail(sh, self._lib.fe_batch_id(sh),
                                          repr(exc)[:200].encode())
                    # the batch failure above was already logged;
                    # fe_fail itself dying adds nothing
                    # drl-check: ok(swallowed-exception)
                    except Exception:  # noqa: BLE001
                        pass
                elif kind == 3:
                    try:
                        self._lib.fe_bulk_fail(
                            sh, self._lib.fe_bulk_id(sh),
                            repr(exc)[:200].encode())
                    # same posture as fe_fail above
                    # drl-check: ok(swallowed-exception)
                    except Exception:  # noqa: BLE001
                        pass

    def _dispatch_batch(self, sh: int) -> None:
        lib, h = self._lib, sh
        bid = lib.fe_batch_id(h)
        n = lib.fe_batch_n(h)
        if n <= 0:
            return
        kb = lib.fe_batch_key_bytes(h)
        blob = ctypes.create_string_buffer(max(int(kb), 1))
        klens = np.empty(n, np.int32)
        counts = np.empty(n, np.int32)
        ops = np.empty(n, np.uint8)
        seqs = np.empty(n, np.uint32)
        conn_ids = np.empty(n, np.uint64)
        a_arr = np.empty(n, np.float64)
        b_arr = np.empty(n, np.float64)
        c = ctypes
        lib.fe_batch_copy(
            h, blob,
            klens.ctypes.data_as(c.POINTER(c.c_int32)),
            counts.ctypes.data_as(c.POINTER(c.c_int32)),
            ops.ctypes.data_as(c.POINTER(c.c_uint8)),
            seqs.ctypes.data_as(c.POINTER(c.c_uint32)),
            conn_ids.ctypes.data_as(c.POINTER(c.c_uint64)),
            a_arr.ctypes.data_as(c.POINTER(c.c_double)),
            b_arr.ctypes.data_as(c.POINTER(c.c_double)))
        # Decode keys off-loop (the pump has idle time while the loop
        # runs store calls); ascii fast path matches wire.py's.
        # surrogateescape: wire keys are bytes; invalid UTF-8 still maps
        # 1:1 to a stable str key, so a hostile/corrupt key rate-limits
        # under its own identity instead of poisoning its whole batch.
        keys = wire.decode_key_blob(blob.raw[:int(kb)], klens,
                                    errors="surrogateescape")
        traces = None
        if (getattr(lib, "has_trace", False)
                and tracing.get_tracer().enabled
                and lib.fe_batch_traced_n(h) > 0):
            # Trace contexts ride as parallel arrays (flag bit 0 marks
            # traced rows) — feature-detected like fe_stage_hist, so a
            # stale binary just serves untraced. The traced_n gate keeps
            # the common all-untraced batch (at 1% head sampling, ~99%
            # of them) at one C int call, no allocations.
            tr_hi = np.zeros(n, np.uint64)
            tr_lo = np.zeros(n, np.uint64)
            tr_par = np.zeros(n, np.uint64)
            tr_fl = np.zeros(n, np.uint8)
            lib.fe_batch_traces(
                h, tr_hi.ctypes.data_as(c.POINTER(c.c_uint64)),
                tr_lo.ctypes.data_as(c.POINTER(c.c_uint64)),
                tr_par.ctypes.data_as(c.POINTER(c.c_uint64)),
                tr_fl.ctypes.data_as(c.POINTER(c.c_uint8)))
            traces = (tr_hi, tr_lo, tr_par, tr_fl)
        self._track(self._serve_batch(sh, bid, keys, counts, ops, a_arr,
                                      b_arr, traces, seqs, conn_ids))

    def _dispatch_passthrough(self, sh: int) -> None:
        lib, h = self._lib, sh
        conn_id = lib.fe_pt_conn(h)
        ln = lib.fe_pt_len(h)
        buf = ctypes.create_string_buffer(max(ln, 1))
        lib.fe_pt_copy(h, buf)
        body = buf.raw[:ln]
        self._track(self._serve_passthrough(sh, int(conn_id), body))

    def _dispatch_bulk(self, sh: int) -> None:
        """Hand one bulk residue job to the loop. The key blob, offsets,
        counts, and residue arrays are ZERO-COPY views into the C-held
        job (the ``wire.KeyBlob`` → ``dir_resolve_batch`` lane on the
        Python side): valid until fe_bulk_complete/discard/fail erases
        the job, which only ``_serve_bulk`` does — after its last read."""
        lib, h = self._lib, sh
        c = ctypes
        u = np.zeros(11, np.uint64)
        f = np.zeros(2, np.float64)
        lib.fe_bulk_meta(h, u.ctypes.data_as(c.POINTER(c.c_uint64)),
                         f.ctypes.data_as(c.POINTER(c.c_double)))
        jid = int(u[0])
        if jid == 0:
            return
        n, blob_len, res_n = int(u[4]), int(u[5]), int(u[6])
        ptrs = np.zeros(4, np.uint64)
        lib.fe_bulk_ptrs(h, ptrs.ctypes.data_as(c.POINTER(c.c_uint64)))
        # A (c_char × len) view passes anywhere the KeyBlob contract
        # needs it: c_char_p args (dir_resolve_batch, dir_route_batch,
        # dir_fp64_batch) take it directly and slicing yields bytes for
        # the serial stores' lazy per-key decode. No blob copy, no
        # Python strings.
        blob = ((c.c_char * blob_len).from_address(int(ptrs[0]))
                if blob_len else b"")
        offsets = np.ctypeslib.as_array(
            c.cast(int(ptrs[1]), c.POINTER(c.c_int64)), (n + 1,))
        counts = np.ctypeslib.as_array(
            c.cast(int(ptrs[2]), c.POINTER(c.c_int64)), (n,))
        residue = np.ctypeslib.as_array(
            c.cast(int(ptrs[3]), c.POINTER(c.c_int32)), (res_n,))
        tctx = None
        if int(u[10]) & 1 and tracing.get_tracer().enabled:
            tctx = tracing.TraceContext(int(u[7]), int(u[8]), int(u[9]),
                                        1 if int(u[10]) & 2 else 0)
        self._track(self._serve_bulk(
            sh, jid, int(u[1]), int(u[2]), int(u[3]), float(f[0]),
            float(f[1]), wire.KeyBlob(blob, offsets), counts, residue,
            tctx))

    # -- loop-side serving -------------------------------------------------

    async def _serve_batch(self, sh: int, bid: int, keys: list[str],
                           counts: np.ndarray, ops: np.ndarray,
                           a_arr: np.ndarray, b_arr: np.ndarray,
                           traces=None, seqs: np.ndarray | None = None,
                           conn_ids: np.ndarray | None = None) -> None:
        n = len(keys)
        t_start = time.perf_counter()
        pgate = full = None
        try:
            hh = getattr(self._server, "heavy_hitters", None)
            if hh is not None:
                # Keys are already materialized for the store call; one
                # C-speed Counter pass + a bounded top-2K merge
                # (utils/heavy_hitters.py overhead discipline). Offers
                # are COST-weighted — an N-token acquire weighs N, so
                # the sketch ranks hot-cost keys (the split-candidate
                # feed), not just hot-count keys. Rows with count <= 0
                # (SEMA releases/probes) are not admission demand —
                # filter only when any exist (rare outside semaphore
                # traffic; the mask check is one vector op).
                if (counts <= 0).any():
                    mask = counts > 0
                    hh.offer_many([k for k, keep in zip(keys, mask)
                                   if keep], counts[mask])
                elif int(counts.max(initial=0)) <= 1:
                    # All-unit batch (the overwhelmingly common shape):
                    # weights are identical, keep the Counter fast path.
                    hh.offer_many(keys)
                else:
                    hh.offer_many(keys, counts)
            # Placement gate (runtime/placement.py): the C batch lane
            # must honor keyspace ownership exactly like the asyncio
            # lane's scalar gate. Dormant (None) until a map is
            # announced; mid-handoff rows serve their fair-share
            # envelope, moved rows answer the routable MOVED error and
            # parked rows with no envelope value (SEMA, releases) answer
            # the transient handoff deferral — both pre-encoded here and
            # pushed through fe_send, with the kRowSkip sentinel telling
            # fe_complete those rows are already answered. A stale .so
            # without the row-skip ABI falls back to denying them (deny
            # is admission-safe but strands stale clients and leaks SEMA
            # permits — the loader rebuilds on source change, so the
            # fallback is a transient condition, not a mode).
            ps = self._server.placement
            pgate = ps.bulk_gate(keys) if ps.active else None
            # Config gate (runtime/liveconfig.py): the C batch lane must
            # honor retired configs exactly like the asyncio lane —
            # mirror of the placement-gate treatment above. Dormant (one
            # attribute read) until a rule commits; then rows carrying a
            # retired (op-kind, a, b) are answered per-row with the
            # routable "config moved" error (fe_send + kRowSkip) so the
            # per-request client chases once and re-sends translated.
            # One forward() probe per distinct config in the batch — the
            # overwhelmingly common single-config batch probes once.
            lc = self._server.liveconfig
            cmoved: "list[tuple[int, tuple, tuple]] | None" = None
            if lc.active and n:
                # One forward() probe per DISTINCT config in the batch
                # (numpy grouping — the rules dict stays populated
                # forever once a mutation commits, so this path is
                # steady-state for mutated fleets and must not pay a
                # per-row Python loop on the fast lane). OP_KINDS is
                # THE shared op→kind table; PEEK never rides a batch.
                ckinds = liveconfig.OP_KINDS
                rec = np.empty(n, dtype=[("op", np.uint8),
                                         ("a", np.float64),
                                         ("b", np.float64)])
                rec["op"], rec["a"], rec["b"] = ops, a_arr, b_arr
                uniq, inverse = np.unique(rec, return_inverse=True)
                rows = []
                for gi, u in enumerate(uniq):
                    ck = ckinds.get(int(u["op"]))
                    if ck is None:
                        continue
                    pk = (ck, float(u["a"]), float(u["b"]))
                    fwd = lc.forward(*pk)
                    if fwd is not None:
                        rows.extend((int(i), pk, fwd) for i in
                                    np.nonzero(inverse == gi)[0])
                cmoved = rows or None
            if pgate is not None or cmoved is not None:
                full = (n, keys, counts, ops, a_arr, b_arr)
                serve_mask = (pgate[0].copy() if pgate is not None
                              else np.ones(n, bool))
                if cmoved is not None:
                    for i, _pk, _fwd in cmoved:
                        serve_mask[i] = False
                serve_idx = np.nonzero(serve_mask)[0]
                keys = [keys[int(i)] for i in serve_idx]
                counts, ops = counts[serve_idx], ops[serve_idx]
                a_arr, b_arr = a_arr[serve_idx], b_arr[serve_idx]
                n = len(keys)
            granted = np.zeros(n, np.uint8)
            remaining = np.zeros(n, np.float64)
            # SEMA rows go as ONE store call in arrival order with
            # per-row limits: grouping them by (a, b) like the bucket
            # ops would execute releases (a=0) in a separate group from
            # acquires (a=limit), reordering same-key pipelined
            # acquire→release pairs and leaking held permits.
            sema_mask = ops == _OP_SEMA
            groups: list = []
            if sema_mask.any():
                groups.append((_OP_SEMA, 0.0, 0.0,
                               np.nonzero(sema_mask)[0]))
            rest = np.nonzero(~sema_mask)[0]
            if n and len(rest) == n and ((ops == ops[0]).all()
                                   and (a_arr == a_arr[0]).all()
                                   and (b_arr == b_arr[0]).all()):
                # Single-config fast path: every frame carries the same
                # (op, capacity, rate) — the overwhelmingly common shape
                # (one limiter config per fleet). One bulk call.
                groups = [(int(ops[0]), float(a_arr[0]), float(b_arr[0]),
                           None)]
            elif len(rest):
                rec = np.empty(len(rest), dtype=[("op", np.uint8),
                                                 ("a", np.float64),
                                                 ("b", np.float64)])
                rec["op"] = ops[rest]
                rec["a"] = a_arr[rest]
                rec["b"] = b_arr[rest]
                uniq, inverse = np.unique(rec, return_inverse=True)
                groups.extend(
                    (int(u["op"]), float(u["a"]), float(u["b"]),
                     rest[np.nonzero(inverse == gi)[0]])
                    for gi, u in enumerate(uniq))
            # Elected dispatch span (first traced row): the store-level
            # profiler spans of this batch's bulk calls nest under it,
            # so a native-lane trace decomposes like the asyncio lane's.
            espan = tracing._NULL_SPAN
            if traces is not None:
                tr_hi, tr_lo, tr_par, tr_fl = traces
                idxs = np.nonzero(tr_fl & 1)[0]
                tracer = tracing.get_tracer()
                if len(idxs) and tracer.enabled:
                    i0 = int(idxs[0])
                    espan = tracer.start_span(
                        "fe.dispatch",
                        parent=tracing.TraceContext(
                            int(tr_hi[i0]), int(tr_lo[i0]),
                            int(tr_par[i0]), 1 if tr_fl[i0] & 2 else 0),
                        attrs={"n": n})
            with espan:
                for op, a, b, idx in groups:
                    if idx is None:
                        gkeys, gcounts = keys, counts
                    else:
                        gkeys = [keys[i] for i in idx.tolist()]
                        gcounts = counts[idx]
                    if op == _OP_BUCKET:
                        res = await self._server.store.acquire_many(
                            gkeys, gcounts, a, b, with_remaining=True)
                    elif op == _OP_SEMA:
                        # Signed deltas; each row's `a` carries its permit
                        # limit (releases wire a=0, ignored per-row).
                        res = await self._server.store.concurrency_acquire_many(
                            gkeys, gcounts,
                            a_arr[idx].astype(np.int64))
                    else:
                        res = await self._server.store.window_acquire_many(
                            gkeys, gcounts, a, b,
                            fixed=(op == _OP_FWINDOW),
                            with_remaining=True)
                    g = np.asarray(res.granted, np.uint8)
                    r = (np.zeros(len(gkeys), np.float64)
                         if res.remaining is None
                         else np.asarray(res.remaining, np.float64))
                    if idx is None:
                        granted, remaining = g, r
                    else:
                        granted[idx] = g
                        remaining[idx] = r
            if pgate is not None or cmoved is not None:
                # Scatter the served subset back into the full batch,
                # decide the parked rows from their handoff envelopes,
                # and answer moved / retired-config / non-envelope
                # parked rows per-row.
                n, keys, counts, ops, a_arr, b_arr = full
                g_full = np.zeros(n, np.uint8)
                r_full = np.zeros(n, np.float64)
                g_full[serve_idx] = granted
                r_full[serve_idx] = remaining
                row_skip = (getattr(self._lib, "has_row_skip", False)
                            and seqs is not None and conn_ids is not None)
                ekinds = {_OP_BUCKET: "bucket", _OP_WINDOW: "window",
                          _OP_FWINDOW: "fwindow"}
                if cmoved is not None:
                    for i, pk, fwd in cmoved:
                        if row_skip:
                            # The moved() counter + message — the same
                            # routable error the asyncio lanes answer;
                            # the store was never touched for this row,
                            # so the client's translated re-send is not
                            # a replay.
                            self._send(sh, int(conn_ids[i]),
                                       wire.encode_response(
                                           int(seqs[i]), wire.RESP_ERROR,
                                           lc.moved(pk[0], pk[1], pk[2],
                                                    fwd)))
                            g_full[i] = _ROW_SKIP
                        # Without the row-skip ABI (stale .so — a
                        # transient condition, the loader rebuilds on
                        # source change): deny. Admission-safe; the
                        # stale client converges on its next scalar
                        # call through the asyncio gate.
                for i, handoff in (pgate[1] if pgate is not None else ()):
                    if g_full[i] == _ROW_SKIP:
                        continue  # already answered config-moved
                    ekind = ekinds.get(int(ops[i]))
                    if ekind is not None and counts[i] >= 0:
                        gr, rem = ps.envelope_acquire(
                            handoff, keys[i], int(counts[i]),
                            float(a_arr[i]), float(b_arr[i]), ekind)
                        g_full[i] = gr
                        r_full[i] = rem
                    elif row_skip:
                        # Parked SEMA / release rows have no envelope
                        # value: a denied decision would silently eat a
                        # permit release (leaking held permits for the
                        # migrated semaphore) — answer the same typed
                        # transient error the asyncio lane does so the
                        # caller retries after the window.
                        ps.handoff_deferrals += 1
                        self._send(sh, int(conn_ids[i]),
                                   wire.encode_response(
                            int(seqs[i]), wire.RESP_ERROR,
                            f"{placement.HANDOFF_DEFERRAL_PREFIX} for "
                            f"this key (target epoch "
                            f"{handoff.target_epoch}); retry shortly"))
                        g_full[i] = _ROW_SKIP
                if row_skip and pgate is not None and pgate[2].any():
                    # Moved rows answer the routable MOVED error — the
                    # signal the client's chase / background refresh
                    # converges on (bulk_gate already counted them).
                    for i in np.nonzero(pgate[2])[0].tolist():
                        if g_full[i] == _ROW_SKIP:
                            continue  # already answered config-moved
                        self._send(sh, int(conn_ids[i]),
                                   wire.encode_response(
                            int(seqs[i]), wire.RESP_ERROR,
                            ps.moved_message(
                                keys[i],
                                int(ps.pmap.node_of(keys[i])))))
                        g_full[i] = _ROW_SKIP
                granted, remaining = g_full, r_full
            if traces is not None:
                self._record_batch_spans(traces, granted, ops, t_start)
            c = ctypes
            self._lib.fe_complete(
                sh, bid,
                np.ascontiguousarray(granted).ctypes.data_as(
                    c.POINTER(c.c_uint8)),
                np.ascontiguousarray(remaining).ctypes.data_as(
                    c.POINTER(c.c_double)))
        except Exception as exc:  # noqa: BLE001 — every request must get
            log.error_evaluating_kernel(exc)  # a routable error reply
            if traces is not None:
                # The gates slice `ops` to the served subset; the trace
                # arrays are full-batch, so restore the full ops before
                # attributing error spans.
                self._record_batch_spans(
                    traces, None, ops if full is None else full[3],
                    t_start)
            self._lib.fe_fail(sh, bid, repr(exc)[:200].encode())

    def _record_batch_spans(self, traces, granted, ops: np.ndarray,
                            t_start: float) -> None:
        """One ``fe.batch`` span per traced row of a native micro-batch,
        parented on the row's wire context (the sampled minority — rows
        without the trace flag cost nothing here). ``granted=None``
        marks the whole batch errored."""
        tracer = tracing.get_tracer()
        if not tracer.enabled:
            return
        tr_hi, tr_lo, tr_par, tr_fl = traces
        t_end = time.perf_counter()
        for i in np.nonzero(tr_fl & 1)[0].tolist():
            if i >= len(ops):
                # Defensive bound only: both call sites hand the
                # full-batch ops (the error path restores them after
                # the placement gate's subset slice).
                break
            ctx = tracing.TraceContext(int(tr_hi[i]), int(tr_lo[i]),
                                       int(tr_par[i]),
                                       1 if tr_fl[i] & 2 else 0)
            if granted is None or granted[i] == _ROW_SKIP:
                # Whole-batch failure, or a row pre-answered with a
                # per-row placement error (MOVED / handoff deferral).
                status = "error"
            else:
                status = "ok" if granted[i] else "denied"
            tracer.record_span(
                "fe.batch", ctx, t_start, t_end, status=status,
                attrs={"op": wire.op_name(int(ops[i]))})

    async def _serve_bulk(self, sh: int, jid: int, conn_id: int,
                          seq: int, flags: int, a: float, b: float,
                          keys: "wire.KeyBlob", counts: np.ndarray,
                          residue: np.ndarray, tctx=None) -> None:
        """Loop half of the native bulk lane: decide the residue rows
        the C side could not (cold keys, windows, probes), mirroring the
        asyncio server's ACQUIRE_MANY branch gate for gate — config,
        drain, placement, in that order — so the two lanes stay
        reply-for-reply identical; then ``fe_bulk_complete`` merges the
        verdicts and encodes RESP_BULK in C. Frame-level gate errors are
        answered via fe_send + fe_bulk_discard (the kRowSkip posture,
        whole-frame edition). Rows tier-0 already granted in a frame
        that then hits a gate stay debited through the sync/retire lane
        — the documented ≤-one-interval epsilon family, same as the
        scalar lanes' commit races."""
        srv = self._server
        lib, h = self._lib, sh
        n = len(keys)
        try:
            # wire.py stays the single layout authority for the flags
            # byte (the C mirror is drl-check-diffed; a third hand-coded
            # copy here would sit outside that conformance net).
            with_rem = bool(flags & wire._FLAG_WITH_REMAINING)
            kind = (flags & wire._KIND_MASK) >> wire._KIND_SHIFT
            ckind = liveconfig.BULK_KINDS.get(kind)
            lc = srv.liveconfig
            if lc.active and ckind is not None:
                fwd = lc.forward(ckind, a, b)
                if fwd is not None:
                    # Retired config: the frame-level routable moved
                    # error, byte-identical to the asyncio gate (no
                    # residue row was applied, so the translated
                    # re-send is not a replay).
                    self._send(sh, conn_id, wire.encode_response(
                        seq, wire.RESP_ERROR,
                        lc.moved(ckind, a, b, fwd)))
                    lib.fe_bulk_discard(h, jid)
                    return
            env = srv._drain_envelope
            if env is not None:
                resp = srv._serve_bulk_draining(seq, keys, counts, a, b,
                                                with_rem, kind, env)
                self._send(sh, conn_id, resp)
                lib.fe_bulk_discard(h, jid)
                return
            gate = (srv.placement.bulk_gate(keys)
                    if srv.placement.active else None)
            if gate is not None and gate[2].any():
                # Misrouted rows: frame-level moved error (the asyncio
                # lane's posture — a bulk-only client needs the refresh
                # trigger; no row was applied).
                i = int(np.nonzero(gate[2])[0][0])
                key = keys[i]
                self._send(sh, conn_id, wire.encode_response(
                    seq, wire.RESP_ERROR, srv.placement.moved_message(
                        key, int(srv.placement.pmap.node_of(key)))))
                lib.fe_bulk_discard(h, jid)
                return
            rn = len(residue)
            granted = np.zeros(rn, np.uint8)
            remaining = np.zeros(rn, np.float64)
            espan = tracing._NULL_SPAN
            if tctx is not None:
                tracer = tracing.get_tracer()
                if tracer.enabled:
                    espan = tracer.start_span(
                        "fe.bulk", parent=tctx,
                        attrs={"n": n, "residue": rn})
            with espan:
                if gate is None:
                    # Whole-frame residue keeps the zero-copy KeyBlob
                    # (the common tier-0-cold / window-kind shape); a
                    # partial residue decodes only its own minority.
                    sub_keys = (keys if rn == n
                                else [keys[int(i)] for i in residue])
                    sub_counts = (counts if rn == n
                                  else np.asarray(counts)[residue])
                    res = await self._bulk_store_call(
                        sub_keys, sub_counts, a, b, kind, with_rem)
                    granted = np.asarray(res.granted, np.uint8)
                    if res.remaining is not None:
                        remaining = np.asarray(res.remaining, np.float64)
                else:
                    serve_mask, envelope_rows, _moved = gate
                    env_rows = dict(envelope_rows)
                    store_pos = [p for p in range(rn)
                                 if serve_mask[int(residue[p])]]
                    if store_pos:
                        sub_keys = [keys[int(residue[p])]
                                    for p in store_pos]
                        sub_counts = np.asarray(counts)[
                            np.asarray(residue)[store_pos]]
                        res = await self._bulk_store_call(
                            sub_keys, sub_counts, a, b, kind, with_rem)
                        granted[store_pos] = np.asarray(res.granted,
                                                        np.uint8)
                        if res.remaining is not None:
                            remaining[store_pos] = np.asarray(
                                res.remaining, np.float64)
                    # Parked rows serve their handoff envelope, exactly
                    # like _serve_bulk_gated's rows (same helper, same
                    # order: store first, envelopes after).
                    for p in range(rn):
                        i = int(residue[p])
                        handoff = env_rows.get(i)
                        if handoff is not None:
                            g, rem = srv.placement.envelope_acquire(
                                handoff, keys[i], int(counts[i]), a, b,
                                liveconfig.BULK_KINDS[kind])
                            granted[p] = g
                            remaining[p] = rem
            c = ctypes
            lib.fe_bulk_complete(
                h, jid,
                np.ascontiguousarray(granted).ctypes.data_as(
                    c.POINTER(c.c_uint8)),
                np.ascontiguousarray(remaining).ctypes.data_as(
                    c.POINTER(c.c_double)))
        except Exception as exc:  # noqa: BLE001 — every frame must get
            log.error_evaluating_kernel(exc)  # a routable error reply
            lib.fe_bulk_fail(h, jid, repr(exc)[:200].encode())

    async def _bulk_store_call(self, keys, counts, a: float, b: float,
                               kind: int, with_rem: bool):
        """The same store entry the asyncio ACQUIRE_MANY branch calls —
        shared shape, shared semantics (the differential fuzz pins the
        two lanes reply-for-reply)."""
        if kind == wire.BULK_KIND_BUCKET:
            return await self._server.store.acquire_many(
                keys, counts, a, b, with_remaining=with_rem)
        return await self._server.store.window_acquire_many(
            keys, counts, a, b,
            fixed=(kind == wire.BULK_KIND_FWINDOW),
            with_remaining=with_rem)

    async def _serve_passthrough(self, sh: int, conn_id: int,
                                 body: bytes) -> None:
        try:
            op = body[5] if len(body) >= 6 else 0
            if op == wire.OP_HELLO:
                await self._serve_hello(sh, conn_id, body)
                return
            if op != wire.OP_ACQUIRE_MANY:
                await self._serve_passthrough_inner(sh, conn_id, body)
                return
            # Only MALFORMED bulk frames (or a disabled/stale bulk lane)
            # reach this path since round 8 — well-formed ones are
            # native. They still run as their own tasks so a long store
            # call can't stall the pump's other passthrough work;
            # chained chunks order behind the connection's tail (conn
            # ids are per-shard, so the tail key carries the shard).
            prev = (self._bulk_tails.get((sh, conn_id))
                    if wire.bulk_request_chained(body) else None)
            task = self._track_task(
                self._serve_passthrough_inner(sh, conn_id, body,
                                              after=prev))
            self._bulk_tails[(sh, conn_id)] = task

            def _clear(t, key=(sh, conn_id)):
                if self._bulk_tails.get(key) is t:
                    del self._bulk_tails[key]

            task.add_done_callback(_clear)
        except Exception as exc:  # noqa: BLE001
            log.error_evaluating_kernel(exc)

    async def _serve_passthrough_inner(self, sh: int, conn_id: int,
                                       body: bytes,
                                       after: "asyncio.Task | None" = None
                                       ) -> None:
        if after is not None:
            await asyncio.gather(after, return_exceptions=True)
        resp = await self._server.handle_frame_body(body)
        self._send(sh, conn_id, resp)

    async def _serve_hello(self, sh: int, conn_id: int,
                           body: bytes) -> None:
        import hmac

        try:
            seq, _, token, _, _, _ = wire.decode_request(body)
        except Exception:
            self._send(sh, conn_id, wire.encode_response(
                0, wire.RESP_ERROR, "malformed HELLO frame"))
            self._lib.fe_close_conn(sh, conn_id)
            return
        auth_token = self._server.auth_token
        # surrogateescape mirrors the wire decode: a token with invalid
        # UTF-8 must compare (and fail) cleanly — a raising .encode()
        # here left the connection stuck in auth_pending forever.
        if auth_token is not None and not hmac.compare_digest(
                token.encode("utf-8", "surrogateescape"),
                auth_token.encode()):
            self._send(sh, conn_id, wire.encode_response(
                seq, wire.RESP_ERROR, "authentication failed"))
            self._lib.fe_close_conn(sh, conn_id)
            return
        self._lib.fe_set_authed(sh, conn_id, 1)
        self._send(sh, conn_id, wire.encode_response(seq, wire.RESP_EMPTY))

    def _send(self, sh: int, conn_id: int, resp: bytes) -> None:
        self._lib.fe_send(sh, conn_id, resp, len(resp))

    # -- tier-0 sync pump --------------------------------------------------

    def _t0_harvest(self) -> dict[tuple[str, float, float], float]:
        """Drain accumulated local grants out of the C replica table:
        ``{(key, capacity, rate): amount}``. Buffers are preallocated in
        ``_tier0_setup`` (the pump runs this every ``sync_interval_s``)."""
        c = ctypes
        blob, klens = self._t0_blob, self._t0_klens
        amounts, caps, rates = (self._t0_amounts, self._t0_caps,
                                self._t0_rates)
        n = self._lib.fe_t0_harvest(
            self._h, blob, len(blob),
            klens.ctypes.data_as(c.POINTER(c.c_int32)),
            amounts.ctypes.data_as(c.POINTER(c.c_double)),
            caps.ctypes.data_as(c.POINTER(c.c_double)),
            rates.ctypes.data_as(c.POINTER(c.c_double)), len(klens))
        if n <= 0:
            return {}
        # string_at copies only the used prefix (blob.raw would
        # materialize the whole preallocated buffer every round).
        used = ctypes.string_at(blob, int(klens[:n].sum()))
        keys = wire.decode_key_blob(used, klens[:n],
                                    errors="surrogateescape")
        # SUM duplicate idents: with N shards each hosts its own replica
        # slice, so one harvest can return the same key once per shard —
        # the merged amount is the node's whole drained grant, debited
        # once and acked back into every slice (fe_t0_ack fans out).
        out: dict[tuple[str, float, float], float] = {}
        for i, k in enumerate(keys):
            ident = (k, float(caps[i]), float(rates[i]))
            out[ident] = out.get(ident, 0.0) + float(amounts[i])
        return out

    def _t0_retire(self, cap: float, rate: float
                   ) -> list[tuple[str, float]]:
        """Kill every C replica of one retired (cap, rate) config and
        return its un-harvested ``(key, amount)`` grants — one locked
        ABI call (``fe_t0_retire``), so no grant slips between the
        harvest and the kill (runtime/liveconfig.py)."""
        c = ctypes
        blob, klens = self._t0_blob, self._t0_klens
        amounts = self._t0_amounts
        n = self._lib.fe_t0_retire(
            self._h, cap, rate, blob, len(blob),
            klens.ctypes.data_as(c.POINTER(c.c_int32)),
            amounts.ctypes.data_as(c.POINTER(c.c_double)), len(klens))
        if n <= 0:
            return []
        used = ctypes.string_at(blob, int(klens[:n].sum()))
        keys = wire.decode_key_blob(used, klens[:n],
                                    errors="surrogateescape")
        return [(k, float(amounts[i])) for i, k in enumerate(keys)]

    def _t0_ack(self, keys: list[str], cap: float, rate: float,
                remaining: np.ndarray) -> None:
        c = ctypes
        n = len(keys)
        kb = [k.encode("utf-8", "surrogateescape") for k in keys]
        blob = b"".join(kb)
        klens = np.fromiter((len(b) for b in kb), np.int32, n)
        caps = np.full(n, cap, np.float64)
        rates = np.full(n, rate, np.float64)
        rem = np.ascontiguousarray(remaining, np.float64)
        self._lib.fe_t0_ack(
            self._h, blob,
            klens.ctypes.data_as(c.POINTER(c.c_int32)),
            caps.ctypes.data_as(c.POINTER(c.c_double)),
            rates.ctypes.data_as(c.POINTER(c.c_double)),
            rem.ctypes.data_as(c.POINTER(c.c_double)), n)

    async def _t0_sync_loop(self) -> None:
        """Reconciliation pump: every ``sync_interval_s``, harvest each
        replica's locally-granted permits, debit them from the
        authoritative store in one bulk launch per (capacity, rate)
        config, and ack the fresh balances back into the replica table
        (which re-sizes every key's budget). A failed round (device
        unhealthy — the r04/r05 outage mode) carries its amounts into the
        next round instead of dropping them; meanwhile the C side keeps
        serving within each key's last-acked envelope."""
        cfg = self._tier0
        assert cfg is not None
        store = self._server.store
        recorder = getattr(self._server, "flight_recorder", None)
        hh = getattr(self._server, "heavy_hitters", None)
        while True:
            await asyncio.sleep(cfg.sync_interval_s)
            self._harvest_tier0_traces()
            # Everything harvested was already zeroed out of the C table:
            # from here until it is debited it exists ONLY in `merged`,
            # so every exit path — per-config failure, unexpected error,
            # cancellation mid-await (aclose) — must route the undrained
            # remainder back into the carry dict. The finally below is
            # that single restore point; successful groups pop themselves
            # out of `merged` first.
            merged = self._t0_carry
            self._t0_carry = {}
            round_failures = 0
            round_keys = 0
            round_shortfall = 0.0
            try:
                for ident, amount in self._t0_harvest().items():
                    merged[ident] = merged.get(ident, 0.0) + amount
                    if hh is not None:
                        # The keys the sync pump drains ARE the tier-0 hot
                        # set — the telemetry that explains hit rate.
                        hh.offer(ident[0], amount)
                if not merged:
                    self._t0_fail_streak = 0
                    continue
                if faults._INJECTOR is not None:  # chaos seam: a fault
                    # here fails the round — harvested rows re-carry via
                    # the finally, the degraded streak advances.
                    await faults._INJECTOR.on_event("t0.sync")
                by_cfg: dict[tuple[float, float], list[tuple[str, float]]] = {}
                for (key, cap, rate), amount in merged.items():
                    by_cfg.setdefault((cap, rate), []).append((key, amount))
                lc = self._server.liveconfig
                for (cap, rate), rows in by_cfg.items():
                    # Retired config (live mutation committed since these
                    # replicas were installed): kill the C replicas via
                    # fe_t0_retire — one locked call that also returns
                    # any grants admitted since the harvest above — and
                    # debit EVERYTHING through the REPLACEMENT config's
                    # table, the one the rebase carried the balances
                    # into. Dead replicas make later stale frames fall
                    # through to the batch lane's routable "config
                    # moved" error (and _ROW_SKIP keeps them from
                    # re-installing). Over-admission is bounded by one
                    # sync interval's headroom — the same epsilon family
                    # as the tier-0 budget itself. A stale .so without
                    # the retire ABI falls back to a zero ack: admission-
                    # safe (confident local denies), converging once the
                    # loader rebuilds.
                    fwd = (lc.forward("bucket", cap, rate)
                           if lc.active else None)
                    if fwd is not None and getattr(self._lib,
                                                   "has_t0_retire",
                                                   False):
                        for key, amount in self._t0_retire(cap, rate):
                            ident = (key, cap, rate)
                            merged[ident] = merged.get(ident, 0.0) \
                                + amount
                        rows = [(k, a) for (k, c2, r2), a
                                in merged.items()
                                if (c2, r2) == (cap, rate)]
                    keys = [k for k, _ in rows]
                    amounts = [a for _, a in rows]
                    dcap, drate = (cap, rate) if fwd is None else \
                        (fwd[0], fwd[1])
                    try:
                        remaining, shortfall = await store.debit_many(
                            keys, amounts, dcap, drate)
                    except asyncio.CancelledError:
                        raise
                    except Exception as exc:  # degraded: rows stay in
                        # `merged` and re-carry via the finally
                        log.error_evaluating_kernel(exc)
                        self.t0_metrics.sync_failures += 1
                        round_failures += 1
                        continue
                    if fwd is not None:
                        self.t0_metrics.retired_config_rows += len(keys)
                        if not getattr(self._lib, "has_t0_retire",
                                       False):
                            remaining = np.zeros(len(keys), np.float64)
                            self._t0_ack(keys, cap, rate, remaining)
                    else:
                        self._t0_ack(keys, cap, rate, remaining)
                    self.t0_metrics.record_sync(len(keys), shortfall,
                                                time.monotonic())
                    round_keys += len(keys)
                    round_shortfall += float(sum(shortfall))
                    for k, _ in rows:
                        merged.pop((k, cap, rate), None)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # the pump must outlive any bad round
                log.error_evaluating_kernel(exc)
                self.t0_metrics.sync_failures += 1
                round_failures += 1
            finally:
                for ident, amount in merged.items():
                    if amount > 0.0:
                        self._t0_carry[ident] = (
                            self._t0_carry.get(ident, 0.0) + amount)
                self._t0_record_round(recorder, round_keys,
                                      round_shortfall, round_failures)

    def _harvest_tier0_traces(self) -> None:
        """Drain the C-side ring of traced tier-0 local decisions into
        the tracer (one completed ``fe.tier0`` span each) — this is how
        a request that never left the epoll loop still contributes its
        hop to the exported trace. Start/duration were stamped in C on
        CLOCK_MONOTONIC, the same epoch ``perf_counter`` reads."""
        lib = self._lib
        if not getattr(lib, "has_trace", False):
            return
        tracer = tracing.get_tracer()
        if not tracer.enabled:
            return
        buf = np.zeros(6 * 256, np.uint64)
        while True:
            got = lib.fe_trace_harvest(
                self._h, buf.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint64)), 256)
            if got <= 0:
                return
            recs = buf[:6 * got].reshape(got, 6)
            for hi, lo, parent, start_ns, dur_ns, meta in recs.tolist():
                ctx = tracing.TraceContext(
                    int(hi), int(lo), int(parent),
                    1 if int(meta) & 2 else 0)
                granted = bool(int(meta) & 0x100)
                tracer.record_span(
                    "fe.tier0", ctx, start_ns * 1e-9,
                    (start_ns + dur_ns) * 1e-9,
                    status="ok" if granted else "denied",
                    attrs={"op": wire.op_name((int(meta) >> 16) & 0xFF),
                           "local": True})
            if got < 256:
                return

    #: Cadence of the bulk-lane hot-key harvest (C ring → sketch). The
    #: ring is bounded (oldest drop), so a slower drain costs tail
    #: fidelity, never memory.
    _HOT_HARVEST_S = 0.5

    async def _hot_harvest_loop(self) -> None:
        """Drain the C bulk lane's per-frame top-K ring into the
        server's heavy-hitter sketch. This closes the PR-2 exemption for
        the native lane: zero-copy bulk keys never materialize in
        Python, so the C side aggregates (top-K per frame) and this pump
        offers only the survivors — exactly the traffic tier-0 bulk
        needs surfaced for hot-row identification."""
        hh = self._server.heavy_hitters
        blob = ctypes.create_string_buffer(256 * 256)
        klens = np.zeros(256, np.int32)
        weights = np.zeros(256, np.float64)
        c = ctypes
        while True:
            await asyncio.sleep(self._HOT_HARVEST_S)
            while True:
                got = self._lib.fe_hot_harvest(
                    self._h, blob, len(blob),
                    klens.ctypes.data_as(c.POINTER(c.c_int32)),
                    weights.ctypes.data_as(c.POINTER(c.c_double)), 256)
                if got <= 0:
                    break
                used = ctypes.string_at(blob, int(klens[:got].sum()))
                keys = wire.decode_key_blob(used, klens[:got],
                                            errors="surrogateescape")
                for k, w in zip(keys, weights[:got].tolist()):
                    hh.offer(k, w)
                if got < 256:
                    break

    def bulk_stats(self) -> dict | None:
        """C-side native-bulk gauges (``None`` when the lane is off).
        ``rows_local`` are per-row tier-0 decisions (grant or confident
        deny) made without leaving C; ``permits_local`` is the granted
        amount the tier-0 sync pump debits — the "bulk sync debits"
        gauge of the epsilon audit."""
        if not self._bulk_native:
            return None
        counts = (ctypes.c_longlong * 7)()
        self._lib.fe_bulk_counts(self._h, counts)
        (frames, frames_local, rows, rows_local, rows_residue,
         permits_local, hot_dropped) = (int(v) for v in counts)
        return {
            "frames": frames,
            "frames_local": frames_local,
            "rows": rows,
            "rows_local": rows_local,
            "rows_residue": rows_residue,
            "permits_local": permits_local,
            "hot_ring_dropped": hot_dropped,
        }

    #: Consecutive failed sync rounds that count as a degraded-mode
    #: streak and trip the flight recorder.
    T0_STREAK_DUMP = 3

    def _t0_record_round(self, recorder, n_keys: int, shortfall: float,
                         failures: int) -> None:
        """Per-sync flight-recorder frame + the degraded-mode triggers:
        a dump on entry into a failure streak of :data:`T0_STREAK_DUMP`
        rounds (rate-limited inside the recorder), so the outage window
        leaves captured state instead of prose."""
        if failures:
            self._t0_fail_streak += 1
        else:
            self._t0_fail_streak = 0
        if recorder is None:
            return
        recorder.record("t0_sync", keys=n_keys, shortfall=shortfall,
                        failures=failures,
                        streak=self._t0_fail_streak,
                        carry_keys=len(self._t0_carry))
        if self._t0_fail_streak == self.T0_STREAK_DUMP:
            recorder.auto_dump(
                "t0_sync_streak",
                {"streak": self._t0_fail_streak,
                 "carry_keys": len(self._t0_carry)})

    def tier0_stats(self) -> dict | None:
        """Merged C + pump-side tier-0 gauges (``None`` when disabled)."""
        if self._tier0 is None:
            return None
        counts = (ctypes.c_longlong * 6)()
        self._lib.fe_t0_counts(self._h, counts)
        hits, denies, misses, installs, evictions, entries = (
            int(v) for v in counts)
        eligible = hits + denies + misses
        out = {
            "hits": hits,
            "local_denies": denies,
            "misses": misses,
            "hit_rate": (hits + denies) / eligible if eligible else 0.0,
            "installs": installs,
            "evictions": evictions,
            "entries": entries,
            "carry_keys": len(self._t0_carry),
            **self.t0_metrics.snapshot(time.monotonic()),
        }
        eps = self.t0_eps_tokens()
        if eps is not None:
            # C-side ε-consumption witness (round 18): cumulative
            # locally-granted tokens, summed over slices — the audit
            # plane's tier-0 "admitted" side.
            out["grant_tokens"] = sum(eps)
        return out

    def t0_eps_tokens(self) -> "list[float] | None":
        """Per-slice cumulative locally-granted tokens (fe_t0_eps) —
        ``None`` when tier-0 is off or the binary predates the ABI."""
        if self._tier0 is None or not getattr(self._lib, "has_t0_eps",
                                              False):
            return None
        buf = (ctypes.c_double * max(1, self.n_shards))()
        n = self._lib.fe_t0_eps(self._h, buf, len(buf))
        return [float(buf[i]) for i in range(int(n))]

    def shard_stats(self) -> "list[dict] | None":
        """Per-shard breakdown of the serving / tier-0 / bulk gauges
        (``None`` single-shard — the merged top-level gauges already
        tell the whole story there). Every row reads through that
        shard's sub-handle; the top-level OP_STATS gauges stay the
        whole-node SUM the C side computes over the same state, so
        ``sum(shards[*].x) == merged x`` is an invariant the tests
        pin. Tier-0 rows read the shard's own replica SLICE (each
        shard hosts its own replicas with a split budget share —
        docs/DESIGN.md §16), so `entries` counts replicas, not
        distinct keys."""
        if self.n_shards <= 1:
            return None
        c = ctypes
        out: list[dict] = []
        for i, sh in enumerate(self._shards):
            req = c.c_longlong()
            conns = c.c_longlong()
            batches = c.c_longlong()
            self._lib.fe_counts(sh, c.byref(req), c.byref(conns),
                                c.byref(batches))
            row: dict = {
                "shard": i,
                "requests_served": req.value,
                "connections_served": conns.value,
                "batches_flushed": batches.value,
            }
            if self._tier0 is not None:
                t0 = (c.c_longlong * 6)()
                self._lib.fe_t0_counts(sh, t0)
                row["tier0"] = {
                    "hits": int(t0[0]), "local_denies": int(t0[1]),
                    "misses": int(t0[2]), "installs": int(t0[3]),
                    "evictions": int(t0[4]), "entries": int(t0[5]),
                }
                if getattr(self._lib, "has_t0_eps", False):
                    # This shard's own slice ε-consumption (round 18):
                    # one row per shard handle, so the per-slice
                    # breakdown rides the same shards=[...] surface.
                    eps = (c.c_double * 1)()
                    if self._lib.fe_t0_eps(sh, eps, 1) == 1:
                        row["tier0"]["grant_tokens"] = float(eps[0])
            if self._bulk_native:
                bk = (c.c_longlong * 7)()
                self._lib.fe_bulk_counts(sh, bk)
                row["native_bulk"] = {
                    "frames": int(bk[0]), "frames_local": int(bk[1]),
                    "rows": int(bk[2]), "rows_local": int(bk[3]),
                    "rows_residue": int(bk[4]),
                    "permits_local": int(bk[5]),
                }
            out.append(row)
        return out

    def transport_stats(self) -> dict | None:
        """Uring transport gauges (``None`` when the loaded binary
        predates the uring ABI): shard counts by transport, ring
        counters (enter syscalls, SQEs submitted, CQEs reaped), the
        self-instrumented data-plane syscall counter both transports
        maintain (the ``syscalls/frame`` numerator in
        benchmarks/RESULTS.md §r16), and per-shard fallback reasons
        when uring was requested but a shard serves on epoll."""
        if not getattr(self._lib, "has_uring", False) or self._h is None:
            return None
        c = ctypes
        counts = (c.c_longlong * 8)()
        self._lib.fe_uring_counts(self._h, counts)
        out = {
            "mode": {URING_OFF: "epoll", URING_ON: "uring",
                     URING_SQPOLL: "uring+sqpoll"}[self.uring_mode],
            "uring_shards": int(counts[0]),
            "sqpoll_shards": int(counts[1]),
            "enters": int(counts[2]),
            "sqes_submitted": int(counts[3]),
            "cqes_seen": int(counts[4]),
            "io_syscalls": int(counts[5]),
            "fallbacks": int(counts[6]),
        }
        if self.uring_mode != URING_OFF and out["fallbacks"]:
            buf = ctypes.create_string_buffer(256)
            reasons = {}
            for i in range(self.n_shards):
                if self._lib.fe_uring_reason(self._h, i, buf,
                                             len(buf)) == 0:
                    reasons[i] = (buf.value.decode("utf-8", "replace")
                                  or "no reason recorded")
            if reasons:
                out["fallback_reasons"] = reasons
        return out

    # -- stats / lifecycle -------------------------------------------------

    def counts(self) -> tuple[int, int, int]:
        """One locked C call for ``(requests_served, connections_served,
        batches_flushed)`` — stats readers take the front-end mutex once,
        not once per counter."""
        c = ctypes
        req = c.c_longlong()
        conns = c.c_longlong()
        batches = c.c_longlong()
        self._lib.fe_counts(self._h, c.byref(req), c.byref(conns),
                            c.byref(batches))
        return req.value, conns.value, batches.value

    def latency_histogram(self) -> LatencyHistogram:
        """Snapshot the C-side serving histogram into the shared Python
        class (same 82 log-1.25 buckets, so quantiles read identically)."""
        if getattr(self._lib, "has_stage_hist", False):
            hist = self._stage_histogram(0)
            if hist is not None:
                return hist
        counts = np.zeros(LatencyHistogram.N_BUCKETS, np.uint64)
        total = self._lib.fe_hist(
            self._h, counts.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint64)))
        hist = LatencyHistogram()
        hist.counts = [int(x) for x in counts]
        hist.total = int(total)
        return hist

    def _stage_histogram(self, stage: int) -> LatencyHistogram | None:
        counts = np.zeros(LatencyHistogram.N_BUCKETS, np.uint64)
        sum_s = ctypes.c_double()
        total = self._lib.fe_stage_hist(
            self._h, stage,
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.byref(sum_s))
        if total < 0:
            return None
        hist = LatencyHistogram()
        hist.counts = [int(x) for x in counts]
        hist.total = int(total)
        hist.sum_s = float(sum_s.value)
        return hist

    def stage_histograms(self) -> "dict[str, LatencyHistogram] | None":
        """The C side's per-stage decomposition of the serving span:
        ``queue`` (frame parsed → batch cut) and ``exec`` (batch cut →
        completion = Python dispatch + store + kernel). ``None`` when the
        loaded binary predates the stage-hist ABI."""
        if not getattr(self._lib, "has_stage_hist", False):
            return None
        out: dict[str, LatencyHistogram] = {}
        for stage, name in ((1, "native_queue"), (2, "native_exec")):
            hist = self._stage_histogram(stage)
            if hist is None:
                return None
            out[name] = hist
        return out

    def reset_latency(self) -> None:
        self._lib.fe_hist_reset(self._h)  # stage hists reset with it

    async def aclose(self) -> None:
        if self._stopping:
            return
        self._stopping = True
        # Order matters: (0) stop the tier-0 sync pump — it reads the C
        # handle (harvest/ack); (1) fe_stop joins the IO thread — no new
        # frames; (2) the pump sees -1 from fe_wait and exits — no new
        # loop tasks; (3) drain the loop tasks still in flight, whose
        # fe_complete/fe_send calls need the handle alive (the sockets
        # are gone, so completions just fall into the void); only then
        # (4) free the handle.
        if self._t0_task is not None:
            self._t0_task.cancel()
            try:
                await self._t0_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._t0_task = None
        if self._hot_task is not None:
            # Same handle discipline as the t0 pump: fe_hot_harvest
            # reads the C handle, so the drain must die before fe_free.
            self._hot_task.cancel()
            try:
                await self._hot_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._hot_task = None
        await asyncio.to_thread(self._lib.fe_stop, self._h)
        for pump in self._pumps:
            await asyncio.to_thread(pump.join, 5.0)
        while self._loop_tasks:
            # Loop, not a one-shot gather: a bulk passthrough parent can
            # spawn its _serve_passthrough_inner child AFTER the snapshot
            # was taken — the child also holds the handle.
            await asyncio.gather(*list(self._loop_tasks),
                                 return_exceptions=True)
        if any(pump.is_alive() for pump in self._pumps):
            # A pump blew past the join timeout: it may still be inside
            # fe_wait/fe_batch_copy holding the handle. Freeing now would
            # be a use-after-free on its next C call — leak the handle
            # (one struct + sockets already closed) and say so instead.
            logger.error(
                "native front-end pump thread still alive after 5s; "
                "leaking the C handle instead of freeing under it")
            self._h = None
            return
        self._lib.fe_free(self._h)
        self._h = None


#: Ops the load generator can drive (all share the keyed-request frame
#: layout; (a, b) mean (capacity, rate) / (limit, window_s) / (limit, -)).
_LOADGEN_OPS = {
    "acquire": wire.OP_ACQUIRE,
    "window": wire.OP_WINDOW,
    "fixed_window": wire.OP_FWINDOW,
    "sema": wire.OP_SEMA,
}


def native_loadgen(host: str, port: int, *, conns: int = 4, depth: int = 32,
                   reqs_per_conn: int = 10000, keyspace: int = 1000,
                   capacity: float = 1e7, fill_rate: float = 1e7,
                   op: str = "acquire") -> tuple[int, int, float]:
    """Closed-loop native measurement client: ``conns`` connections each
    keeping ``depth`` pipelined requests of ``op`` (acquire / window /
    fixed_window / sema) in flight. Returns ``(replies, granted,
    elapsed_s)``. Runs in C (one epoll thread) so a Python client's
    ~14µs/request scheduling floor doesn't bound the measurement — the
    asymmetric rig the per-request ceiling analysis called for
    (benchmarks/RESULTS.md)."""
    if op not in _LOADGEN_OPS:
        raise ValueError(
            f"unknown loadgen op {op!r}; choose from "
            f"{sorted(_LOADGEN_OPS)}")
    lib = load_frontend_lib()
    if lib is None:
        raise RuntimeError("native front-end library unavailable")
    c = ctypes
    elapsed = c.c_double()
    replies = c.c_longlong()
    granted = c.c_longlong()
    rc = lib.fe_loadgen(host.encode(), port, conns, depth, reqs_per_conn,
                        keyspace, capacity, fill_rate, _LOADGEN_OPS[op],
                        c.byref(elapsed), c.byref(replies),
                        c.byref(granted))
    if rc != 0:
        raise OSError("native loadgen failed to connect")
    return replies.value, granted.value, elapsed.value


def native_bulk_loadgen(host: str, port: int, *, conns: int = 8,
                        depth: int = 4, frames_per_conn: int = 200,
                        rows_per_frame: int = 4096, keyspace: int = 64,
                        capacity: float = 1e8, fill_rate: float = 1e8,
                        uring: bool = False
                        ) -> tuple[int, int, int, float]:
    """Closed-loop native BULK measurement client: ``conns`` connections
    each keeping ``depth`` pipelined OP_ACQUIRE_MANY frames of
    ``rows_per_frame`` rows in flight, frames built and replies counted
    in C (``fe_lg_bulk``). Returns ``(frames, rows, granted_rows,
    elapsed_s)``. This is the shard-sweep rig's client: at multi-shard
    bulk rates even a per-frame Python client bounds the node, and the
    kernel's SO_REUSEPORT hash spreads the ``conns`` across shards.
    Requires a front-end binary with the shard ABI.

    ``uring=True`` drives the frames through the loadgen's own
    submission ring (``fe_lg_bulk_uring`` — one ``io_uring_enter`` per
    burst instead of one send/recv syscall pair per frame) so the
    client stops being the syscall bottleneck it was in the r11 sweep;
    when the ring is unavailable (kernel, seccomp, or a stale .so) the
    call falls back to the epoll-era client loudly and the measurement
    still happens."""
    lib = load_frontend_lib()
    if lib is None or not getattr(lib, "has_shards", False):
        raise RuntimeError(
            "native bulk loadgen unavailable (library missing or "
            "predates the fe_lg_bulk ABI)")
    c = ctypes
    elapsed = c.c_double()
    frames = c.c_longlong()
    rows = c.c_longlong()
    granted = c.c_longlong()
    if uring and getattr(lib, "has_uring", False):
        rc = lib.fe_lg_bulk_uring(
            host.encode(), port, conns, depth, frames_per_conn,
            rows_per_frame, keyspace, capacity, fill_rate,
            c.byref(elapsed), c.byref(frames), c.byref(rows),
            c.byref(granted))
        if rc == 0:
            return frames.value, rows.value, granted.value, elapsed.value
        if rc != -2:
            raise OSError("native uring bulk loadgen failed to connect")
        logger.warning("uring bulk loadgen requested but no ring is "
                       "available on this host; using the syscall client")
    elif uring:
        logger.warning("uring bulk loadgen requested but the loaded "
                       "binary predates the uring ABI; using the "
                       "syscall client")
    rc = lib.fe_lg_bulk(host.encode(), port, conns, depth,
                        frames_per_conn, rows_per_frame, keyspace,
                        capacity, fill_rate, c.byref(elapsed),
                        c.byref(frames), c.byref(rows), c.byref(granted))
    if rc != 0:
        raise OSError("native bulk loadgen failed to connect")
    return frames.value, rows.value, granted.value, elapsed.value
