"""Remote store client — the framework's ``ConnectionMultiplexer``.

:class:`RemoteBucketStore` lets limiter instances on any host share a
:class:`~.server.BucketStoreServer` the way the reference's limiters share
one Redis (SURVEY.md §2 #6, §5.8). Behaviors carried over:

- **Config precedence** ``connection_factory > address > url`` — mirroring
  the reference's ``ConnectionMultiplexerFactory > ConfigurationOptions >
  Configuration``-string ladder (``RedisTokenBucketRateLimiter.cs:127-141``).
  The factory seam is also the test fake's injection point (§4 implication
  (b)).
- **Lazy, double-checked connect**; a failed connect is logged (event id 1)
  and retried on next use (``ConnectAsync`` ``:111-151``; invariant 9's
  recovery posture).
- **Multiplexed pipelining**: one TCP connection carries any number of
  in-flight requests tagged with sequence ids; a background reader resolves
  them in completion order — the StackExchange.Redis model.
- **Client-side frame coalescing** (``coalesce_requests``, default on):
  concurrent single-key acquires against one bucket config share
  ``ACQUIRE_MANY`` frames (a MicroBatcher on the I/O loop), so a server
  is loaded by its clients' FLUSH rate, not their request rate — measured
  10-100× fewer frames/tasks per request at moderate client concurrency,
  with bulk-path decision semantics (same-key requests in one flush
  serialize conservatively). Turn off for strict per-request framing
  (e.g. per-request server-side latency accounting).
- **Time stays with the store.** The wire protocol carries no client
  timestamps anywhere; all refill arithmetic runs against the server's
  clock (invariant 1 — the property the reference gets from Lua ``TIME``).

All socket I/O runs on a dedicated background event loop thread, so the
same client instance serves both ``async`` callers (from any event loop)
and blocking callers (from any thread).
"""

from __future__ import annotations

import asyncio
import random
import threading
from typing import Awaitable, Callable, Sequence

import numpy as np

from distributedratelimiting.redis_tpu.runtime import liveconfig, wire
from distributedratelimiting.redis_tpu.runtime.clock import Clock, MonotonicClock
from distributedratelimiting.redis_tpu.runtime.store import (
    AcquireResult,
    BucketStore,
    BulkAcquireResult,
    SyncResult,
)
from distributedratelimiting.redis_tpu.utils import faults, log, tracing
from distributedratelimiting.redis_tpu.utils.resilience import RetryPolicy
from distributedratelimiting.redis_tpu.utils.tracing import Profiler, ProfilingSession

__all__ = ["RemoteBucketStore", "StoreTimeoutError"]

ConnectionFactory = Callable[
    [], Awaitable[tuple[asyncio.StreamReader, asyncio.StreamWriter]]
]


class StoreTimeoutError(asyncio.TimeoutError):
    """The store did not answer within the request timeout.

    Typed so callers can tell "the STORE went quiet" apart from their
    own ``asyncio.wait_for`` deadlines (it still subclasses
    :class:`asyncio.TimeoutError`, so existing catches keep working).
    Never retried by the client: the frame was sent, and whether it was
    executed is unknowable — the at-most-once contract (docs/DESIGN.md
    §11) forbids replaying it."""


#: Ops safe to retry even after their frame may have reached the wire:
#: executing them twice changes no admission state. Everything else —
#: ACQUIRE, WINDOW, FWINDOW, SEMA, SYNC, mutating STATS/TRACES flags —
#: retries only on provably-never-sent failures (connect phase). The
#: placement/migration/config control ops are *application-idempotent
#: by design* (epoch-monotonic announce, per-epoch cached pull,
#: batch-deduped push, version-monotonic OP_CONFIG — wire.py), so a
#: coordinator's retry mid-chaos can never double-apply a handoff.
#:
#: EVERY ``wire.OP_*`` must appear in exactly one of these two sets —
#: drl-check's ``wire-idempotency`` rule enforces it, so a future op
#: cannot silently become post-send-retry-unsafe by omission.
_IDEMPOTENT_OPS = frozenset((
    wire.OP_PEEK, wire.OP_PING, wire.OP_METRICS, wire.OP_PLACEMENT,
    wire.OP_PLACEMENT_ANNOUNCE, wire.OP_MIGRATE_PULL,
    wire.OP_MIGRATE_PUSH, wire.OP_CONFIG,
    # Reservation lane: application-idempotent BY RESERVATION ID — a
    # retried reserve of a granted rid replays the recorded decision
    # (no second debit), a retried settle replays the recorded
    # reconciliation (outcome "duplicate", zero side effects) — the
    # MIGRATE_PUSH dedup posture, so post-send retries are safe.
    wire.OP_RESERVE, wire.OP_SETTLE,
    # Federation lane (runtime/federation.py): lease and reclaim
    # replay their per-lease-id recorded results (the OP_RESERVE
    # posture); renew is absorbing by construction — monotonic
    # admitted totals make a replayed report a zero delta, and slice
    # changes carry an epoch the region adopts only forward (the
    # OP_CONFIG version discipline). A WAN retry mid-partition can
    # never double-grant a slice or double-refund a reclaim.
    wire.OP_FED_LEASE, wire.OP_FED_RENEW, wire.OP_FED_RECLAIM,
    # Audit plane: a pure read of the conservation snapshot (bundles
    # ship copies out of a bounded deque; nothing drains) — retrying a
    # lost reply re-reads, never mutates.
    wire.OP_AUDIT))

#: The explicit NOT-idempotent half of the classification: admission
#: ops double-debit on replay; HELLO re-auth mid-stream is a protocol
#: error; STATS/TRACES flags mutate measurement windows; SAVE re-queues
#: a device pull; ACQUIRE_MANY is the bulk admission lane (its retry
#: surface is connect-phase only, _bulk_io).
_NON_IDEMPOTENT_OPS = frozenset((
    wire.OP_ACQUIRE, wire.OP_WINDOW, wire.OP_FWINDOW, wire.OP_SEMA,
    wire.OP_SYNC, wire.OP_HELLO, wire.OP_SAVE, wire.OP_STATS,
    wire.OP_TRACES, wire.OP_ACQUIRE_MANY, wire.OP_ACQUIRE_H))


class RemoteBucketStore(BucketStore):
    """Client for a :class:`BucketStoreServer`.

    Exactly one of ``connection_factory``, ``address``, or ``url`` must be
    given (highest-precedence one wins if several are)::

        store = RemoteBucketStore(address=("tpu-host", 6380))
        store = RemoteBucketStore(url="tpu-host:6380")
        store = RemoteBucketStore(connection_factory=my_open_fn)  # tests
    """

    def __init__(
        self,
        *,
        connection_factory: ConnectionFactory | None = None,
        address: tuple[str, int] | None = None,
        url: str | None = None,
        request_timeout_s: float = 30.0,
        clock: Clock | None = None,
        profiling_session: Callable[[], ProfilingSession | None] | None = None,
        auth_token: str | None = None,
        coalesce_requests: bool = True,
        coalesce_max_batch: int = 512,
        coalesce_max_delay_s: float = 200e-6,
        retry_policy: "RetryPolicy | None" = RetryPolicy(),
        reconnect_backoff_base_s: float = 0.05,
        reconnect_backoff_max_s: float = 5.0,
        propagate_deadlines: bool = False,
        resilience_seed: int | None = None,
    ) -> None:
        if connection_factory is None and address is None and url is None:
            # ≙ the reference's ctor validation "some Redis config present"
            # (…RateLimiter.cs:49-67).
            raise ValueError(
                "one of connection_factory, address, or url is required"
            )
        self._factory = connection_factory
        if address is None and url is not None:
            host, _, port = url.rpartition(":")
            address = (host or "127.0.0.1", int(port))
        self._address = address
        self._request_timeout_s = request_timeout_s
        # Shared secret presented in a HELLO as the first frame after
        # connect (≙ the AUTH in a Redis Configuration string).
        self._auth_token = auth_token
        # The client clock exists only to satisfy the BucketStore interface
        # (e.g. local diagnostics); the SERVER is the time authority.
        self.clock = clock or MonotonicClock()
        # ≙ Func<ProfilingSession> on the connection (TryRegisterProfiler,
        # RedisTokenBucketRateLimiter.cs:166-174): here each profiled
        # command is one wire round-trip to the store server.
        self.profiler = Profiler(profiling_session)
        # Distributed tracing: when the process-global tracer samples a
        # request, the client span's context rides the frame as the
        # version-gated trace tail (wire.py). Latched off for this
        # connection the first time an old server answers a stamped
        # frame with its routable "unknown op" error — the OP_METRICS
        # compatibility posture, feature-detected instead of negotiated.
        self._peer_traces = True
        # Live-config forwarding (runtime/liveconfig.py): translations
        # learned from "config moved" errors — a call carrying a retired
        # (a, b) chases exactly one routable error, then every later
        # call translates up front. {(kind, a, b) → (a, b)}.
        self._config_fwd: dict[tuple, tuple[float, float]] = {}
        # Tenant-extension latch (OP_ACQUIRE_H / BULK_KIND_HBUCKET): an
        # old server answers either with a routable unknown-op /
        # unknown-bulk-kind error — latch off once per connection
        # lifetime and fall back to FLAT child-only admission (counted:
        # the tenant level goes unenforced against that peer —
        # availability over tenant-budget accuracy, logged once).
        self._peer_hier = True
        self._hier_fallbacks = 0
        # Reservation-lane latch (OP_RESERVE/OP_SETTLE): an old server
        # answers the routable unknown-op error — latch off once per
        # connection lifetime and fall back to plain
        # acquire_hierarchical at the estimate (no server-side hold:
        # refunds are forgone against that peer — the conservative
        # direction, logged once + counted).
        self._peer_reserve = True
        self._reserve_fallbacks = 0
        # Route-to-pool redirects chased (budget-aware pool routing,
        # docs/DESIGN.md §24) — one count per re-send, not per answer.
        self._reserves_routed = 0
        # Federation-lane latch (OP_FED_LEASE/RENEW/RECLAIM): an old
        # home answers the routable unknown-op error — latch off once
        # per connection lifetime; the region then treats federation
        # as partitioned (keep serving the current slice, degrade to
        # the envelope at expiry — never unlimited, never hard-down).
        self._peer_fed = True
        self._fed_fallbacks = 0

        # -- resilience (docs/OPERATIONS.md §8, DESIGN.md §11) ---------
        # Bounded, jittered retries. At-most-once for admission: an op
        # outside _IDEMPOTENT_OPS retries ONLY when the failure happened
        # before its frame could have been sent (the connect phase) — a
        # replayed ACQUIRE double-debits. retry_policy=None disables.
        self._retry_policy = retry_policy
        # Reconnect backoff: after a failed dial, further dial attempts
        # fail fast until the (jittered, exponentially growing) window
        # passes — the retry-amplification damper: a dead server costs
        # each client one dial per window, not one per request.
        self._backoff_base_s = reconnect_backoff_base_s
        self._backoff_max_s = reconnect_backoff_max_s
        self._backoff_until = 0.0          # I/O-loop time()
        self._connect_failures = 0
        # Deadline propagation: stamp every scalar request with this
        # call's remaining budget so a backlogged server sheds expired
        # work instead of answering the dead. Off by default — stamped
        # scalar ops leave the native front-end's C fast lane for the
        # passthrough lane. Latched off per connection on the first
        # "unknown op" answer from a pre-deadline peer.
        self._propagate_deadlines = propagate_deadlines
        self._peer_deadlines = True
        # Attempt propagation (retry-storm defense, docs/DESIGN.md
        # §24): re-sends carry a saturating attempt counter so an
        # armed server sheds retries before first-attempt work. Same
        # old-peer posture as the deadline tail, latched independently.
        self._peer_attempts = True
        # Seedable rng (jitter): deterministic under the chaos harness.
        self._rng = random.Random(resilience_seed)
        # Resilience counters (resilience_stats()).
        self._retries = 0
        self._timeouts = 0

        # Client-side frame coalescing: concurrent single-key acquires
        # against one bucket config share ACQUIRE_MANY frames — one frame
        # and one server task carry a whole flush instead of per-request
        # frames, so a fleet of clients loads the server by its FLUSH
        # rate, not its request rate. Decisions are the store's bulk
        # semantics (same-key requests in one flush serialize
        # conservatively; over-admission impossible).
        self._coalesce = coalesce_requests
        self._coalesce_max_batch = coalesce_max_batch
        self._coalesce_max_delay_s = coalesce_max_delay_s
        self._acquire_batchers: dict = {}  # (cap, rate) → MicroBatcher

        self._io_loop: asyncio.AbstractEventLoop | None = None
        self._io_thread: threading.Thread | None = None
        self._thread_gate = threading.Lock()

        # Connection state — touched only from the I/O loop.
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._reader_task: asyncio.Task | None = None
        self._connect_gate: asyncio.Lock | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._seq = 0
        self._closed = False

    # -- background I/O loop ------------------------------------------------
    def _ensure_loop(self) -> asyncio.AbstractEventLoop:
        with self._thread_gate:
            return self._ensure_loop_locked()

    def _ensure_loop_locked(self) -> asyncio.AbstractEventLoop:
        # _thread_gate held by the caller.
        if self._closed:
            # Post-close use must fail fast, not resurrect a loop
            # thread that nothing would ever stop.
            raise ConnectionError("store client is closed")
        if self._io_loop is None:
            loop = asyncio.new_event_loop()
            ready = threading.Event()

            def run() -> None:
                asyncio.set_event_loop(loop)
                self._connect_gate = asyncio.Lock()
                ready.set()
                loop.run_forever()
                # aclose stopped the loop with _closed already latched.
                # Anything still here — a task suspended in a retry
                # backoff at stop time, a coroutine a racing _submit
                # enqueued behind the stop — would leave its caller
                # waiting FOREVER on a future nothing resolves (the
                # rolling-restart replace_node lane acloses LIVE nodes
                # mid-traffic, where this race is routine, not
                # theoretical). Flush the callback queue, cancel what
                # remains, and let the cancellations deliver: every
                # waiter gets a terminal result instead of a hang.
                for _ in range(8):
                    loop.run_until_complete(asyncio.sleep(0))
                    leftovers = asyncio.all_tasks(loop)
                    if not leftovers:
                        break
                    for task in leftovers:
                        task.cancel()
                    loop.run_until_complete(asyncio.gather(
                        *leftovers, return_exceptions=True))

            t = threading.Thread(
                target=run, name="remote-bucket-store-io", daemon=True
            )
            t.start()
            ready.wait()
            self._io_loop = loop
            self._io_thread = t
        return self._io_loop

    def _submit(self, coro) -> "asyncio.Future":
        # The whole submit runs under the gate aclose takes to latch
        # _closed: a submission either sees _closed (fast-fail below)
        # or lands in the loop's queue BEFORE aclose's shutdown+stop
        # callbacks — never behind the stop, where it would sit
        # unstarted forever.
        with self._thread_gate:
            try:
                loop = self._ensure_loop_locked()
            except Exception:
                coro.close()  # never-awaited otherwise (post-close
                raise         # fast-fail)
            return asyncio.run_coroutine_threadsafe(coro, loop)

    async def _await_on_io(self, coro):
        fut = self._submit(coro)
        try:
            return await asyncio.wrap_future(fut)
        except asyncio.CancelledError:
            # The I/O loop's shutdown drain cancels work it abandoned
            # (see _ensure_loop_locked): surface that as the same typed
            # connection error every other post-close path raises, not
            # a bare cancellation the caller never asked for. A
            # genuinely caller-driven cancel (client still open)
            # re-raises untouched.
            if self._closed and fut.cancelled():
                raise ConnectionError(
                    "store client is closed") from None
            raise

    # -- connection lifecycle (on the I/O loop) -----------------------------
    async def connect(self) -> None:
        """Idempotent lazy connect; public so callers can front-load the
        dial, but every request path calls it anyway (lazy as in the
        reference)."""
        await self._await_on_io(self._connect_io())

    def _dial_failed(self, exc: Exception) -> None:
        """Bookkeeping for a failed dial/handshake: log it and arm the
        jittered exponential reconnect-backoff window."""
        self._connect_failures += 1
        delay = min(self._backoff_max_s,
                    self._backoff_base_s
                    * 2.0 ** (self._connect_failures - 1))
        delay *= 0.5 + 0.5 * self._rng.random()  # jitter: [½, 1]×
        assert self._io_loop is not None
        self._backoff_until = self._io_loop.time() + delay
        log.could_not_connect_to_store(exc)

    async def _connect_io(self) -> None:
        if self._writer is not None:
            return
        assert self._connect_gate is not None
        async with self._connect_gate:  # double-checked (≙ SemaphoreSlim(1,1))
            if self._writer is not None or self._closed:
                return
            now = asyncio.get_running_loop().time()
            if now < self._backoff_until:
                # Fail fast inside the backoff window instead of
                # hammering a dead peer — concurrent requests shed here
                # rather than amplifying the dial storm.
                raise ConnectionError(
                    f"reconnect backing off for another "
                    f"{self._backoff_until - now:.2f}s "
                    f"({self._connect_failures} failed dials)")
            try:
                if faults._INJECTOR is not None:  # chaos seam; no-op in prod
                    await faults._INJECTOR.on_event("client.connect")
                if self._factory is not None:
                    reader, writer = await self._factory()
                else:
                    assert self._address is not None
                    reader, writer = await asyncio.open_connection(
                        self._address[0], self._address[1]
                    )
                if faults._INJECTOR is not None:
                    reader, writer = faults._INJECTOR.wrap_connection(
                        reader, writer)
            except Exception as exc:
                self._dial_failed(exc)
                raise
            reader_task = asyncio.ensure_future(self._read_loop(reader))
            if self._auth_token is not None:
                # HELLO must complete before the connection is published —
                # no other request can slip ahead of the auth handshake
                # (requests gate on self._writer, still None here).
                self._seq = (self._seq + 1) & 0xFFFFFFFF
                seq = self._seq
                fut: asyncio.Future = asyncio.get_running_loop().create_future()
                self._pending[seq] = fut
                try:
                    wire.write_frame(writer, wire.encode_request(
                        seq, wire.OP_HELLO, self._auth_token))
                    await writer.drain()
                    await asyncio.wait_for(fut, self._request_timeout_s)
                except Exception as exc:
                    self._pending.pop(seq, None)
                    reader_task.cancel()
                    writer.close()
                    self._dial_failed(exc)
                    raise
            self._connect_failures = 0
            self._backoff_until = 0.0
            self._reader, self._writer = reader, writer
            self._reader_task = reader_task

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        # A protocol-level failure (e.g. version mismatch) is a better
        # reason to hand in-flight futures than a generic lost-connection.
        reason: Exception = ConnectionError("connection to store lost")
        try:
            while True:
                body = await wire.read_frame(reader)
                if body is None:
                    break
                seq, kind, vals = wire.decode_response(body)
                fut = self._pending.pop(seq, None)
                if fut is None or fut.done():
                    continue
                if kind == wire.RESP_ERROR:
                    fut.set_exception(wire.RemoteStoreError(vals[0]))
                else:
                    fut.set_result(vals)
        except Exception as exc:
            log.error_evaluating_kernel(exc)
            if isinstance(exc, wire.RemoteStoreError):
                reason = exc
        finally:
            self._drop_connection(reason)

    def _drop_connection(self, exc: Exception) -> None:
        """Fail all in-flight requests; the next use reconnects."""
        if self._writer is not None:
            self._writer.close()
        reader_task = self._reader_task
        if (reader_task is not None
                and reader_task is not asyncio.current_task()):
            # A reader stalled mid-read (e.g. an injected read stall)
            # would otherwise outlive the connection it served.
            reader_task.cancel()
        self._reader = self._writer = None
        self._reader_task = None
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)

    # -- request path (on the I/O loop) -------------------------------------
    async def _request_io(self, op: int, key: str, count: int,
                          a: float, b: float,
                          parent: "tracing.TraceContext | None" = None,
                          timeout_s: "float | None" = None,
                          hier=None) -> tuple:
        # rows=1: one wire command = one request (the permit count is the
        # command's argument, not its row count — keep units consistent
        # with the device store's per-batch rows).
        tracer = tracing.get_tracer()
        if not tracer.enabled:
            with self.profiler.span(wire.op_name(op), 1, annotate=False):
                return await self._request_io_unprofiled(
                    op, key, count, a, b, timeout_s=timeout_s,
                    hier=hier)
        # The trace starts HERE (the client wire layer): `parent` is the
        # caller-side ambient context, captured before hopping onto the
        # I/O loop where contextvars don't follow (cluster fan-out spans
        # arrive this way).
        span = tracer.start_span(f"client.{wire.op_name(op)}",
                                 parent=parent)
        with span, self.profiler.span(wire.op_name(op), 1,
                                      annotate=False):
            trace = span.context if self._peer_traces else None
            try:
                vals = await self._request_io_unprofiled(
                    op, key, count, a, b, trace, timeout_s=timeout_s,
                    hier=hier)
            except wire.RemoteStoreError as exc:
                if trace is not None and "unknown op" in str(exc):
                    # Old peer: it parsed the frame far enough to route
                    # an error but does not speak the trace tail. Latch
                    # stamping off and retry bare — once per connection
                    # lifetime, not per request. (The deadline tail has
                    # its own, inner latch — it is tried and shed first.)
                    self._peer_traces = False
                    span.set_attr("trace_tail", "unsupported_peer")
                    try:
                        vals = await self._request_io_unprofiled(
                            op, key, count, a, b, None,
                            timeout_s=timeout_s, hier=hier)
                    except wire.RemoteStoreError as exc2:
                        if "unknown op" in str(exc2):
                            # The BARE re-send was rejected too: the
                            # base OP is what the peer doesn't speak
                            # (e.g. OP_ACQUIRE_H against an old server)
                            # — the trace tail was never the problem,
                            # so undo the latch before surfacing (the
                            # deadline latch's posture; without this, a
                            # hier flat-fallback would silently strip
                            # tracing from the whole connection).
                            self._peer_traces = True
                        raise
                else:
                    raise
            if vals and vals[0] is False:
                span.set_status("denied")
            return vals

    async def _request_io_unprofiled(self, op: int, key: str, count: int,
                                     a: float, b: float,
                                     trace=None, *,
                                     timeout_s: "float | None" = None,
                                     hier=None) -> tuple:
        """Send one request with the at-most-once retry contract
        (docs/DESIGN.md §11): a failure in the CONNECT phase provably
        never sent this request's frame, so any op may retry it; once
        :meth:`_send_once` is entered the frame may have reached the
        server, and only :data:`_IDEMPOTENT_OPS` may retry. Timeouts
        (:class:`StoreTimeoutError`) and server-answered errors never
        retry. Retry delays are the policy's jittered backoff, stretched
        to at least the reconnect-backoff window."""
        timeout = (self._request_timeout_s if timeout_s is None
                   else timeout_s)
        policy = self._retry_policy
        attempt = 0
        latched_here = False
        attempt_latched_here = False
        while True:
            sent = False
            ddl = (timeout if (self._propagate_deadlines
                               and self._peer_deadlines) else None)
            # Attempt tail (retry-storm defense, docs/DESIGN.md §24):
            # stamped only on re-sends, so first attempts stay
            # byte-identical to pre-attempt frames.
            atl = attempt if (attempt and self._peer_attempts) else 0
            try:
                await self._connect_io()
                sent = True  # past here the frame may be on the wire
                return await self._send_once(op, key, count, a, b,
                                             trace, ddl, timeout, hier,
                                             attempt=atl)
            except wire.RemoteStoreError as exc:
                if atl and "unknown op" in str(exc):
                    # Pre-attempt peer: it routed an error without
                    # executing, so re-sending is NOT a replay. The
                    # attempt tail is the newest (innermost) and sheds
                    # first — independently of the deadline latch.
                    self._peer_attempts = False
                    attempt_latched_here = True
                    continue
                if ddl is not None and "unknown op" in str(exc):
                    # Pre-deadline peer: it routed an error without
                    # executing, so re-sending is NOT a replay. Latch
                    # stamping off for the connection and go again.
                    self._peer_deadlines = False
                    latched_here = True
                    continue
                if ((latched_here or attempt_latched_here)
                        and "unknown op" in str(exc)):
                    # The BARE re-send was rejected too: the base op is
                    # what the peer doesn't speak (e.g. a newer op) —
                    # the tails were never the problem, so undo the
                    # latches before surfacing the error.
                    if latched_here:
                        self._peer_deadlines = True
                    if attempt_latched_here:
                        self._peer_attempts = True
                raise  # the server answered: definitive, never retried
            except (StoreTimeoutError, asyncio.CancelledError):
                raise
            except Exception:
                attempt += 1
                retryable = not sent or op in _IDEMPOTENT_OPS
                if (policy is None or not retryable or self._closed
                        or attempt >= policy.max_attempts):
                    raise
                await self._retry_sleep(attempt)

    async def _send_once(self, op: int, key: str, count: int,
                         a: float, b: float, trace,
                         deadline_s: "float | None",
                         timeout: float, hier=None, *,
                         attempt: int = 0) -> tuple:
        if self._writer is None or self._io_loop is None:
            raise ConnectionError("store client is closed")
        self._seq = (self._seq + 1) & 0xFFFFFFFF
        seq = self._seq
        fut: asyncio.Future = self._io_loop.create_future()
        self._pending[seq] = fut
        try:
            try:
                wire.write_frame(
                    self._writer,
                    wire.encode_request(seq, op, key, count, a, b,
                                        trace=trace,
                                        deadline_s=deadline_s,
                                        hier=hier, attempt=attempt),
                )
                # Drain only under real buffer pressure — a per-request
                # drain await costs a task switch on a hot pipelined
                # connection where the buffer is nearly always empty.
                if (self._writer.transport.get_write_buffer_size()
                        > 256 * 1024):
                    await self._writer.drain()
            except Exception as exc:
                self._drop_connection(
                    exc if isinstance(exc, ConnectionError)
                    else ConnectionError(str(exc))
                )
                raise
            try:
                return await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                self._timeouts += 1
                raise StoreTimeoutError(
                    f"store gave no reply within {timeout}s "
                    f"(op {wire.op_name(op)})") from None
        finally:
            # Timeout / cancellation must not leak the future: against a
            # hung-but-connected server every timed-out request would
            # otherwise grow _pending forever.
            self._pending.pop(seq, None)

    async def _request(self, op: int, key: str = "", count: int = 0,
                       a: float = 0.0, b: float = 0.0,
                       timeout_s: "float | None" = None,
                       hier=None) -> tuple:
        # Capture the ambient trace context on the CALLER's side — the
        # coroutine body runs on the I/O loop thread, where the caller's
        # contextvars are invisible.
        return await self._await_on_io(self._request_io(
            op, key, count, a, b, tracing.current_context(), timeout_s,
            hier))

    async def _retry_sleep(self, attempt: int) -> None:
        """One retry's backoff: the policy's jittered delay, stretched
        to at least the reconnect-backoff window's remainder (no point
        dialing before it opens). Counts the retry."""
        self._retries += 1
        if faults._INJECTOR is not None:  # chaos seam; no-op in prod
            await faults._INJECTOR.on_event("client.retry")
        delay = self._retry_policy.delay_s(attempt, self._rng)
        remaining = (self._backoff_until
                     - asyncio.get_running_loop().time())
        if remaining > 0:
            delay = max(delay, remaining)
        await asyncio.sleep(delay)

    async def _connect_with_retry(self) -> None:
        """Connect with the retry policy: a dial failure provably sent
        nothing, so retrying it is safe for every op (the bulk lane's
        retry surface — post-send bulk failures never retry)."""
        policy = self._retry_policy
        attempt = 0
        while True:
            try:
                return await self._connect_io()
            except asyncio.CancelledError:
                raise
            except Exception:
                attempt += 1
                if (policy is None or self._closed
                        or attempt >= policy.max_attempts):
                    raise
                await self._retry_sleep(attempt)

    # -- bulk path (OP_ACQUIRE_MANY) ----------------------------------------
    async def _bulk_io(self, blob: bytes, offsets: np.ndarray,
                       klens: np.ndarray, counts_np: np.ndarray,
                       spans: list[tuple[int, int]], capacity: float,
                       fill_rate: float, with_remaining: bool,
                       kind: int = wire.BULK_KIND_BUCKET,
                       profile: bool = True,
                       parent: "tracing.TraceContext | None" = None,
                       timeout_s: "float | None" = None,
                       hier=None) -> list[tuple]:
        """Send every chunk of one bulk call pipelined on the connection,
        then await all replies. One wire round-trip (per ~MAX_FRAME of
        keys) carries thousands of decisions — this is what carries the
        local bulk path's throughput across the process boundary, where
        the reference paid one RTT per decision
        (``RedisTokenBucketRateLimiter.cs:63``).

        Tracing: one ``client.acquire_many`` span covers the whole call
        (all chunks); every chunk frame carries the span's context as
        the bulk trace tail — old servers ignore it by construction, so
        no latch is needed on this lane. ``parent`` is the caller-side
        ambient context (coalesced flushes arrive with the flush span
        ambient instead)."""
        tracer = tracing.get_tracer()
        tspan = (tracer.start_span("client.acquire_many", parent=parent,
                                   attrs={"rows": int(len(klens))})
                 if tracer.enabled else tracing._NULL_SPAN)
        timeout = (self._request_timeout_s if timeout_s is None
                   else timeout_s)
        with tspan, self.profiler.span("acquire_many", len(klens),
                                       annotate=False, enabled=profile):
            trace = tspan.context if self._peer_traces else None
            await self._connect_with_retry()
            if self._writer is None or self._io_loop is None:
                raise ConnectionError("store client is closed")
            futs: list[tuple[int, asyncio.Future]] = []
            try:
                try:
                    for i, (start, end) in enumerate(spans):
                        self._seq = (self._seq + 1) & 0xFFFFFFFF
                        seq = self._seq
                        fut: asyncio.Future = self._io_loop.create_future()
                        self._pending[seq] = fut
                        futs.append((seq, fut))
                        wire.write_frame(
                            self._writer, wire.encode_bulk_request_span(
                                seq, blob, offsets, klens, counts_np,
                                start, end, capacity, fill_rate,
                                with_remaining=with_remaining, kind=kind,
                                chained=(i > 0), trace=trace,
                                hier=hier))
                    await self._writer.drain()
                except Exception as exc:
                    self._drop_connection(
                        exc if isinstance(exc, ConnectionError)
                        else ConnectionError(str(exc)))
                    raise
                try:
                    return await asyncio.wait_for(
                        asyncio.gather(*(f for _, f in futs)), timeout)
                except asyncio.TimeoutError:
                    self._timeouts += 1
                    raise StoreTimeoutError(
                        f"store gave no bulk reply within {timeout}s "
                        f"({len(klens)} rows)") from None
            finally:
                for seq, _ in futs:
                    self._pending.pop(seq, None)

    def _bulk_prepare(self, keys: Sequence[str], counts: Sequence[int],
                      budget: "int | None" = None):
        """Whole-call key prep: ONE join + ONE encode for the common
        all-ascii case (393K ``str.encode`` calls plus two length
        genexprs per 131K-key call were the client's top profile
        entries), falling back to per-key encode only when byte length
        ≠ char length (non-ascii present). Returns ``(blob, offsets,
        klens, counts_np, spans)`` — chunks encode by slicing the blob
        (:func:`wire.encode_bulk_request_span`)."""
        n = len(keys)
        counts_np = np.asarray(counts, np.uint32)
        joined = "".join(keys)
        if joined.isascii():  # char lens ARE byte lens: one encode
            blob = joined.encode("ascii")
            klens = np.fromiter(map(len, keys), np.int64, n)
        else:
            key_blobs = [k.encode("utf-8", "surrogateescape")
                         for k in keys]
            klens = np.fromiter(map(len, key_blobs), np.int64, n)
            blob = b"".join(key_blobs)
        offsets = np.zeros(n + 1, np.int64)
        np.cumsum(klens, out=offsets[1:])
        return (blob, offsets, klens, counts_np,
                wire.bulk_chunk_spans(klens, budget))

    @staticmethod
    def _bulk_assemble(chunks: list[tuple],
                       with_remaining: bool) -> BulkAcquireResult:
        if len(chunks) == 1:
            granted, remaining = chunks[0]
        else:
            granted = np.concatenate([c[0] for c in chunks])
            remaining = (np.concatenate([c[1] for c in chunks])
                         if with_remaining else None)
        return BulkAcquireResult(granted, remaining)

    @staticmethod
    def _bulk_empty(with_remaining: bool) -> BulkAcquireResult:
        return BulkAcquireResult(
            np.zeros((0,), bool),
            np.zeros((0,), np.float32) if with_remaining else None)

    async def _bulk_call(self, keys, counts, a: float, b: float,
                         with_remaining: bool, kind: int,
                         timeout_s: "float | None" = None
                         ) -> BulkAcquireResult:
        """One bulk round trip (any table kind): prepare → chunked
        pipelined frames on the I/O loop → reassemble."""
        if len(keys) == 0:
            return self._bulk_empty(with_remaining)
        blob, offsets, klens, counts_np, spans = self._bulk_prepare(
            keys, counts)
        chunks = await self._await_on_io(self._bulk_io(
            blob, offsets, klens, counts_np, spans, a, b, with_remaining,
            kind=kind, parent=tracing.current_context(),
            timeout_s=timeout_s))
        return self._bulk_assemble(chunks, with_remaining)

    def _bulk_call_blocking(self, keys, counts, a: float, b: float,
                            with_remaining: bool, kind: int,
                            timeout_s: "float | None" = None
                            ) -> BulkAcquireResult:
        if len(keys) == 0:
            return self._bulk_empty(with_remaining)
        blob, offsets, klens, counts_np, spans = self._bulk_prepare(
            keys, counts)
        chunks = self._submit(self._bulk_io(
            blob, offsets, klens, counts_np, spans, a, b, with_remaining,
            kind=kind, parent=tracing.current_context(),
            timeout_s=timeout_s)).result(self._blocking_timeout(timeout_s))
        return self._bulk_assemble(chunks, with_remaining)

    async def acquire_many(self, keys: Sequence[str], counts: Sequence[int],
                           capacity: float, fill_rate_per_sec: float, *,
                           with_remaining: bool = True,
                           timeout_s: "float | None" = None
                           ) -> BulkAcquireResult:
        # One config-moved chase, like the scalar lanes: the server
        # answers a retired config frame-level without applying any row,
        # so the translated re-send is not a replay.
        return await self._chase_config(
            "bucket", capacity, fill_rate_per_sec,
            lambda a, b: self._bulk_call(keys, counts, a, b,
                                         with_remaining,
                                         wire.BULK_KIND_BUCKET,
                                         timeout_s))

    def acquire_many_blocking(self, keys: Sequence[str],
                              counts: Sequence[int], capacity: float,
                              fill_rate_per_sec: float, *,
                              with_remaining: bool = True,
                              timeout_s: "float | None" = None
                              ) -> BulkAcquireResult:
        return self._chase_config_blocking(
            "bucket", capacity, fill_rate_per_sec,
            lambda a, b: self._bulk_call_blocking(
                keys, counts, a, b, with_remaining,
                wire.BULK_KIND_BUCKET, timeout_s))

    async def window_acquire_many(self, keys: Sequence[str],
                                  counts: Sequence[int], limit: float,
                                  window_sec: float, *, fixed: bool = False,
                                  with_remaining: bool = True
                                  ) -> BulkAcquireResult:
        """Bulk windows over the wire: same ACQUIRE_MANY framing with the
        table-kind flag selecting the server's window tier."""
        kind = wire.BULK_KIND_FWINDOW if fixed else wire.BULK_KIND_WINDOW
        return await self._chase_config(
            liveconfig.BULK_KINDS[kind], limit, window_sec,
            lambda a, b: self._bulk_call(keys, counts, a, b,
                                         with_remaining, kind))

    def window_acquire_many_blocking(self, keys: Sequence[str],
                                     counts: Sequence[int], limit: float,
                                     window_sec: float, *,
                                     fixed: bool = False,
                                     with_remaining: bool = True
                                     ) -> BulkAcquireResult:
        kind = wire.BULK_KIND_FWINDOW if fixed else wire.BULK_KIND_WINDOW
        return self._chase_config_blocking(
            liveconfig.BULK_KINDS[kind], limit, window_sec,
            lambda a, b: self._bulk_call_blocking(
                keys, counts, a, b, with_remaining, kind))

    # -- hierarchical tenant → key admission (OP_ACQUIRE_H / HBUCKET) -------
    def _note_hier_fallback(self) -> None:
        """Old-peer latch: log the degradation ONCE per client (the
        tenant level goes unenforced against this server), count every
        fallback decision."""
        if self._peer_hier:
            self._peer_hier = False
            log.error_evaluating_kernel(RuntimeError(
                "server does not speak the tenant extension "
                "(OP_ACQUIRE_H/HBUCKET); hierarchical calls fall back "
                "to FLAT child-only admission — tenant budgets are NOT "
                "enforced against this peer"))
        self._hier_fallbacks += 1

    @staticmethod
    def _hier_unsupported(exc: Exception) -> bool:
        msg = str(exc)
        return "unknown op" in msg or "unknown bulk kind" in msg

    async def _chase_hier(self, tcap: float, trate: float, cap: float,
                          rate: float, call):
        """The hierarchical edition of :meth:`_chase_config`: BOTH
        levels' operands translate through the learned "bucket" rules
        up front, and a moved error on EITHER level learns its rule and
        re-sends — at most two chases (one per level; the gate answered
        without touching the store, so a re-send is not a replay)."""
        for attempt in range(3):
            a, b = self._fwd_config("bucket", cap, rate)
            ta, tb = self._fwd_config("bucket", tcap, trate)
            try:
                return await call(ta, tb, a, b)
            except wire.RemoteStoreError as exc:
                if (attempt >= 2
                        or self._learn_config(exc, "bucket") is None):
                    raise

    def _chase_hier_blocking(self, tcap: float, trate: float,
                             cap: float, rate: float, call):
        for attempt in range(3):
            a, b = self._fwd_config("bucket", cap, rate)
            ta, tb = self._fwd_config("bucket", tcap, trate)
            try:
                return call(ta, tb, a, b)
            except wire.RemoteStoreError as exc:
                if (attempt >= 2
                        or self._learn_config(exc, "bucket") is None):
                    raise

    async def acquire_hierarchical(self, tenant: str, key: str,
                                   count: int, tenant_capacity: float,
                                   tenant_fill_rate_per_sec: float,
                                   capacity: float,
                                   fill_rate_per_sec: float, *,
                                   priority: int = 0,
                                   timeout_s: "float | None" = None
                                   ) -> AcquireResult:
        """Two-level admission as ONE OP_ACQUIRE_H frame (grant iff
        both levels admit, decided server-side in one fused launch);
        ``priority`` rides the tenant extension so the server's
        envelope serving honors the shed order."""
        from distributedratelimiting.redis_tpu.runtime.store import (
            check_hierarchical_args,
        )

        check_hierarchical_args(count, tenant_capacity,
                                tenant_fill_rate_per_sec, capacity,
                                fill_rate_per_sec)
        if not self._peer_hier:
            self._hier_fallbacks += 1
            return await self.acquire(key, count, capacity,
                                      fill_rate_per_sec,
                                      timeout_s=timeout_s)

        async def call(ta, tb, a, b):
            granted, remaining = await self._request(
                wire.OP_ACQUIRE_H, key, count, a, b,
                timeout_s=timeout_s,
                hier=(tenant, ta, tb, priority))
            return AcquireResult(granted, remaining)

        try:
            return await self._chase_hier(
                tenant_capacity, tenant_fill_rate_per_sec, capacity,
                fill_rate_per_sec, call)
        except wire.RemoteStoreError as exc:
            if not self._hier_unsupported(exc):
                raise
            self._note_hier_fallback()
            return await self.acquire(key, count, capacity,
                                      fill_rate_per_sec,
                                      timeout_s=timeout_s)

    def acquire_hierarchical_blocking(self, tenant: str, key: str,
                                      count: int,
                                      tenant_capacity: float,
                                      tenant_fill_rate_per_sec: float,
                                      capacity: float,
                                      fill_rate_per_sec: float, *,
                                      priority: int = 0,
                                      timeout_s: "float | None" = None
                                      ) -> AcquireResult:
        from distributedratelimiting.redis_tpu.runtime.store import (
            check_hierarchical_args,
        )

        check_hierarchical_args(count, tenant_capacity,
                                tenant_fill_rate_per_sec, capacity,
                                fill_rate_per_sec)
        if not self._peer_hier:
            self._hier_fallbacks += 1
            return self.acquire_blocking(key, count, capacity,
                                         fill_rate_per_sec,
                                         timeout_s=timeout_s)

        def call(ta, tb, a, b):
            granted, remaining = self._request_blocking(
                wire.OP_ACQUIRE_H, key, count, a, b,
                timeout_s=timeout_s,
                hier=(tenant, ta, tb, priority))
            return AcquireResult(granted, remaining)

        try:
            return self._chase_hier_blocking(
                tenant_capacity, tenant_fill_rate_per_sec, capacity,
                fill_rate_per_sec, call)
        except wire.RemoteStoreError as exc:
            if not self._hier_unsupported(exc):
                raise
            self._note_hier_fallback()
            return self.acquire_blocking(key, count, capacity,
                                         fill_rate_per_sec,
                                         timeout_s=timeout_s)

    # -- estimate-reserve-settle (OP_RESERVE / OP_SETTLE) --------------------
    #: The ledger lives SERVER-side; None (not a method) so the
    #: migration import lane's ``callable(...)`` probe skips this
    #: client instead of minting a local ledger nothing would serve.
    reservation_ledger = None

    def _note_reserve_fallback(self) -> None:
        if self._peer_reserve:
            self._peer_reserve = False
            log.error_evaluating_kernel(RuntimeError(
                "server does not speak the reservation lane "
                "(OP_RESERVE/OP_SETTLE); reserve falls back to plain "
                "acquire_hierarchical at the estimate — over-estimate "
                "refunds are NOT issued against this peer"))
        self._reserve_fallbacks += 1

    async def _reserve_fallback(self, rid: str, tenant: str, key: str,
                                estimate: "float | None",
                                tenant_capacity: float,
                                tenant_fill_rate_per_sec: float,
                                capacity: float,
                                fill_rate_per_sec: float,
                                priority: int,
                                timeout_s: "float | None"):
        """Old-peer path: charge the estimate through the hierarchical
        lane (which itself degrades to flat child-only admission
        against even older peers). No hold exists anywhere — the later
        settle is a client-side no-op."""
        from distributedratelimiting.redis_tpu.runtime.reservations import (
            ReserveResult,
            fallback_charge,
        )

        charge = fallback_charge(estimate)
        res = await self.acquire_hierarchical(
            tenant, key, charge, tenant_capacity,
            tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
            priority=priority, timeout_s=timeout_s)
        return ReserveResult(res.granted,
                             float(charge) if res.granted else 0.0,
                             res.remaining, 0.0, fallback=True)

    async def reserve(self, rid: str, tenant: str, key: str,
                      estimate: "float | None",
                      tenant_capacity: float,
                      tenant_fill_rate_per_sec: float,
                      capacity: float, fill_rate_per_sec: float, *,
                      priority: int = 0,
                      ttl_s: "float | None" = None,
                      timeout_s: "float | None" = None,
                      attempt: int = 0,
                      deadline_s: "float | None" = None):
        """One OP_RESERVE frame: admission at the estimate + a TTL'd
        server-side hold (runtime/reservations.py). Both config levels
        translate through the learned live-config rules up front (the
        ``_chase_hier`` contract); post-send retries are safe — the
        server dedups by ``rid``.

        ``attempt``/``deadline_s`` ride as JSON fields (not binary
        tails — old servers ignore unknown keys, so no latch). A
        "route-to-pool" answer (budget-aware pool routing, docs/
        DESIGN.md §24) is chased ONCE, like config-moved: the re-send
        carries the redirect's pool config and the result reports
        ``routed=True``."""
        import json

        from distributedratelimiting.redis_tpu.runtime import (
            reservations,
        )
        from distributedratelimiting.redis_tpu.runtime.reservations import (
            ReserveResult,
        )

        if not self._peer_reserve:
            self._reserve_fallbacks += 1
            return await self._reserve_fallback(
                rid, tenant, key, estimate, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority, timeout_s)

        async def call(ta, tb, a, b, *, _tenant=tenant,
                       _priority=int(priority), _route=True):
            payload: dict = {"rid": rid, "tenant": _tenant, "key": key,
                             "a": a, "b": b, "ta": ta, "tb": tb,
                             "priority": _priority}
            if estimate is not None:
                payload["estimate"] = float(estimate)
            if ttl_s is not None:
                payload["ttl_s"] = float(ttl_s)
            if attempt:
                payload["attempt"] = int(attempt)
            if deadline_s is not None:
                payload["deadline_s"] = float(deadline_s)
            try:
                (text,) = await self._request(
                    wire.OP_RESERVE, json.dumps(payload),
                    timeout_s=timeout_s)
            except wire.RemoteStoreError as exc:
                route = (reservations.parse_route(str(exc))
                         if _route else None)
                if route is None:
                    raise
                # Chase the redirect once (the config-moved posture):
                # re-send against the overflow/batch pool the server
                # named — the POOL is the tenant-bucket key, so the
                # hold lands in the pool's own budget, not the
                # exhausted interactive one. A second redirect
                # surfaces as the error — no routing loops.
                self._reserves_routed += 1
                pool_name = str(route["pool"])
                routed = await call(
                    float(route["ta"]), float(route["tb"]), a, b,
                    _tenant=pool_name,
                    _priority=int(route.get("priority", _priority)),
                    _route=False)
                return routed._replace(routed=True, pool=pool_name)
            d = json.loads(text)
            return ReserveResult(bool(d.get("granted")),
                                 float(d.get("reserved", 0.0)),
                                 float(d.get("remaining", 0.0)),
                                 float(d.get("debt", 0.0)),
                                 bool(d.get("duplicate", False)))

        try:
            return await self._chase_hier(
                tenant_capacity, tenant_fill_rate_per_sec, capacity,
                fill_rate_per_sec, call)
        except wire.RemoteStoreError as exc:
            if "unknown op" not in str(exc):
                raise
            self._note_reserve_fallback()
            return await self._reserve_fallback(
                rid, tenant, key, estimate, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority, timeout_s)

    async def settle(self, rid: str, tenant: str, actual: float, *,
                     timeout_s: "float | None" = None):
        """One OP_SETTLE frame (idempotent by rid — post-send-retry-
        safe). Against a latched old peer this is a counted client-side
        no-op: the fallback reserve charged the estimate outright, and
        there is no server-side hold to reconcile."""
        import json

        from distributedratelimiting.redis_tpu.runtime.reservations import (
            SettleResult,
        )

        if not self._peer_reserve:
            self._reserve_fallbacks += 1
            return SettleResult("fallback", 0.0, 0.0, 0.0)
        try:
            (text,) = await self._request(
                wire.OP_SETTLE,
                json.dumps({"rid": rid, "tenant": tenant,
                            "actual": float(actual)}),
                timeout_s=timeout_s)
        except wire.RemoteStoreError as exc:
            if "unknown op" not in str(exc):
                raise
            self._note_reserve_fallback()
            return SettleResult("fallback", 0.0, 0.0, 0.0)
        d = json.loads(text)
        return SettleResult(str(d.get("outcome", "settled")),
                            float(d.get("delta", 0.0)),
                            float(d.get("refunded", 0.0)),
                            float(d.get("debt", 0.0)))

    # -- global quota federation (OP_FED_LEASE / RENEW / RECLAIM) ------------
    #: The ledger lives at the HOME; None (not a method) for the same
    #: reason as reservation_ledger — a ``callable(...)`` probe must
    #: skip this client, not mint a local ledger nothing serves.
    federation_ledger = None

    def _note_fed_fallback(self) -> None:
        if self._peer_fed:
            self._peer_fed = False
            log.error_evaluating_kernel(RuntimeError(
                "home does not speak the federation lane "
                "(OP_FED_LEASE/RENEW/RECLAIM); the region keeps "
                "serving from its current slice and degrades to its "
                "fair-share envelope at lease expiry — federation "
                "unavailability is treated as a partition"))
        self._fed_fallbacks += 1

    async def _fed_call(self, op: int, payload: dict,
                        timeout_s: "float | None") -> dict:
        """One federation control frame (TEXT_OPS JSON; post-send-
        retry-safe — see _IDEMPOTENT_OPS). Against a latched old home
        this returns ``{"fallback": True}``: the region treats it as a
        partition symptom (keep serving, degrade at expiry) — the
        conservative direction, never unlimited."""
        import json

        if not self._peer_fed:
            self._fed_fallbacks += 1
            return {"fallback": True}
        try:
            (text,) = await self._request(op, json.dumps(payload),
                                          timeout_s=timeout_s)
        except wire.RemoteStoreError as exc:
            if "unknown op" not in str(exc):
                raise
            self._note_fed_fallback()
            return {"fallback": True}
        return json.loads(text)

    async def fed_lease(self, payload: dict, *,
                        timeout_s: "float | None" = None) -> dict:
        """Request (or idempotently re-request) a slice lease from the
        home federation ledger (``OP_FED_LEASE``; wire.py documents
        the payload/reply fields)."""
        return await self._fed_call(wire.OP_FED_LEASE, payload,
                                    timeout_s)

    async def fed_renew(self, payload: dict, *,
                        timeout_s: "float | None" = None) -> dict:
        """Renew a lease: report the region's monotonic admitted total
        + demand, extend the TTL, adopt any slice resize
        (``OP_FED_RENEW``; absorbing — replay-safe)."""
        return await self._fed_call(wire.OP_FED_RENEW, payload,
                                    timeout_s)

    async def fed_reclaim(self, payload: dict, *,
                          timeout_s: "float | None" = None) -> dict:
        """Return a slice to the pool (``OP_FED_RECLAIM``; idempotent
        by lease id — a duplicate replays the recorded result)."""
        return await self._fed_call(wire.OP_FED_RECLAIM, payload,
                                    timeout_s)

    def _hier_tail_budget(self, tenant: str) -> int:
        """Chunk budget for HBUCKET frames: the per-frame tenant
        extension rides every chunk, so the spans must leave room for
        it under MAX_FRAME."""
        tlen = len(tenant.encode("utf-8", "surrogateescape"))
        return wire.BULK_CHUNK_BUDGET - (2 + tlen + wire.HIER_TAIL_LEN)

    async def acquire_hierarchical_many(self, tenants, keys, counts,
                                        tenant_capacity: float,
                                        tenant_fill_rate_per_sec: float,
                                        capacity: float,
                                        fill_rate_per_sec: float, *,
                                        with_remaining: bool = True,
                                        priority: int = 0,
                                        timeout_s: "float | None" = None
                                        ) -> BulkAcquireResult:
        """Bulk hierarchical admission over the wire: rows group by
        tenant (one HBUCKET frame-set per distinct tenant — the
        natural gateway shape is one tenant's flush), results scatter
        back in row order."""
        from distributedratelimiting.redis_tpu.runtime.store import (
            check_hierarchical_args,
        )

        n = len(keys)
        counts_np = np.asarray(counts, np.int64)
        check_hierarchical_args(int(counts_np.min(initial=0)),
                                tenant_capacity,
                                tenant_fill_rate_per_sec, capacity,
                                fill_rate_per_sec)
        if n == 0:
            return self._bulk_empty(with_remaining)
        if not self._peer_hier:
            self._hier_fallbacks += 1
            return await self.acquire_many(
                keys, counts, capacity, fill_rate_per_sec,
                with_remaining=with_remaining, timeout_s=timeout_s)
        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32) if with_remaining else None
        by_tenant: dict[str, list[int]] = {}
        for i, t in enumerate(tenants):
            by_tenant.setdefault(t, []).append(i)

        async def one_tenant(tenant: str, idx: list[int]):
            sub_keys = [keys[i] for i in idx]
            sub_counts = counts_np[idx]
            if not self._peer_hier:  # latched mid-call by a sibling
                self._hier_fallbacks += 1
                return await self.acquire_many(
                    sub_keys, sub_counts, capacity, fill_rate_per_sec,
                    with_remaining=with_remaining, timeout_s=timeout_s)

            async def call(ta, tb, a, b):
                blob, offsets, klens, c_np, spans = self._bulk_prepare(
                    sub_keys, sub_counts,
                    self._hier_tail_budget(tenant))
                chunks = await self._await_on_io(self._bulk_io(
                    blob, offsets, klens, c_np, spans, a, b,
                    with_remaining, kind=wire.BULK_KIND_HBUCKET,
                    parent=tracing.current_context(),
                    timeout_s=timeout_s,
                    hier=(tenant, ta, tb, priority)))
                return self._bulk_assemble(chunks, with_remaining)

            try:
                return await self._chase_hier(
                    tenant_capacity, tenant_fill_rate_per_sec,
                    capacity, fill_rate_per_sec, call)
            except wire.RemoteStoreError as exc:
                if not self._hier_unsupported(exc):
                    raise
                self._note_hier_fallback()
                return await self.acquire_many(
                    sub_keys, sub_counts, capacity, fill_rate_per_sec,
                    with_remaining=with_remaining, timeout_s=timeout_s)

        # All tenants' frame-sets in flight together — one bulk call is
        # one pipelined burst on the connection, not one RTT per tenant
        # (the flat lane's posture; frames of distinct tenants are
        # independent, so concurrency changes no decision).
        groups = list(by_tenant.items())
        results = await asyncio.gather(
            *(one_tenant(t, idx) for t, idx in groups))
        for (_t, idx), res in zip(groups, results):
            granted[idx] = res.granted
            if remaining is not None and res.remaining is not None:
                remaining[idx] = res.remaining
        return BulkAcquireResult(granted, remaining)

    def _blocking_timeout(self, timeout_s: "float | None" = None) -> float:
        """Grace timeout for a blocking ``.result()`` wait: the request
        timeout plus the retry policy's worst-case backoff, plus one
        second of slack (the inner wait_for fires first by design)."""
        t = self._request_timeout_s if timeout_s is None else timeout_s
        if self._retry_policy is not None:
            t += self._retry_policy.max_total_delay_s()
        return t + 1.0

    def _request_blocking(self, op: int, key: str = "", count: int = 0,
                          a: float = 0.0, b: float = 0.0,
                          timeout_s: "float | None" = None,
                          hier=None) -> tuple:
        return self._submit(self._request_io(
            op, key, count, a, b, tracing.current_context(),
            timeout_s, hier)).result(self._blocking_timeout(timeout_s))

    # -- client-side frame coalescing ---------------------------------------
    #: Cap on distinct (capacity, fill_rate) coalescing batchers: configs
    #: are per-call floats, so an unbounded map would leak under dynamic
    #: per-tenant rates. Overflow configs fall back to per-request frames.
    _MAX_ACQUIRE_BATCHERS = 64

    def _acquire_batcher(self, capacity: float, fill_rate_per_sec: float):
        """Per-config MicroBatcher living on the I/O loop (only ever
        touched from it): a flush becomes ONE ACQUIRE_MANY frame. Returns
        ``None`` once the config cap is hit (caller uses per-request
        framing for the overflow config)."""
        from distributedratelimiting.redis_tpu.runtime.batcher import (
            MicroBatcher,
        )

        key = (float(capacity), float(fill_rate_per_sec))
        batcher = self._acquire_batchers.get(key)
        if batcher is None:
            if (self._closed
                    or len(self._acquire_batchers)
                    >= self._MAX_ACQUIRE_BATCHERS):
                return None

            async def flush(reqs):
                keys = [k for k, _ in reqs]
                counts = [c for _, c in reqs]
                blob, offsets, klens, counts_np, spans = (
                    self._bulk_prepare(keys, counts))
                # profile=False: every request in this flush already
                # records its own 'acquire' span — an inner 'acquire_many'
                # would double-count the rows.
                chunks = await self._bulk_io(
                    blob, offsets, klens, counts_np, spans, capacity,
                    fill_rate_per_sec, True, kind=wire.BULK_KIND_BUCKET,
                    profile=False)
                res = self._bulk_assemble(chunks, True)
                return [AcquireResult(bool(res.granted[i]),
                                      float(res.remaining[i]))
                        for i in range(len(reqs))]

            batcher = MicroBatcher(
                flush, max_batch=self._coalesce_max_batch,
                max_delay_s=self._coalesce_max_delay_s,
                max_inflight=8,
            )
            self._acquire_batchers[key] = batcher
        return batcher

    async def _acquire_coalesced_io(self, key: str, count: int,
                                    capacity: float,
                                    fill_rate_per_sec: float,
                                    parent: "tracing.TraceContext | None"
                                    = None) -> AcquireResult:
        batcher = self._acquire_batcher(capacity, fill_rate_per_sec)
        if batcher is None:  # config cap hit: per-request framing
            granted, remaining = await self._request_io(
                wire.OP_ACQUIRE, key, count, capacity, fill_rate_per_sec,
                parent)
            return AcquireResult(granted, remaining)
        # Same per-command profiling contract as the per-request path —
        # the span covers submit → flush → wire round trip → fan-out (the
        # latency this caller actually observed). The trace span opened
        # here is what the batcher captures as the member context, so a
        # coalesced request's trace still names its shared flush.
        tracer = tracing.get_tracer()
        tspan = (tracer.start_span("client.acquire", parent=parent)
                 if tracer.enabled else tracing._NULL_SPAN)
        with tspan, self.profiler.span(wire.op_name(wire.OP_ACQUIRE), 1,
                                       annotate=False):
            res = await batcher.submit((key, count))
            if not res.granted:
                tspan.set_status("denied")
            return res

    # -- live-config forwarding (runtime/liveconfig.py) ----------------------
    def _fwd_config(self, kind: str, a: float, b: float
                    ) -> tuple[float, float]:
        """Translate a possibly-retired config through the learned
        forwarding rules (cycle-safe — a REVERTED mutation can leave a
        stale entry whose target maps back; the walk stops at the first
        revisit, which IS the currently-serving config). The steady
        state is one empty-dict truthiness test."""
        fwd = self._config_fwd
        if not fwd:
            return a, b
        key = (kind, float(a), float(b))
        seen = set()
        while key not in seen:
            seen.add(key)
            nxt = fwd.get(key)
            if nxt is None:
                break
            key = (kind, nxt[0], nxt[1])
        return key[1], key[2]

    def _learn_config(self, exc: Exception, kind: str
                      ) -> "tuple[float, float] | None":
        """If ``exc`` is the routable "config moved" error, record the
        rule and return the (transitively resolved) new operands to
        retry with; ``None`` for every other error. Safe to retry: the
        gate answered without touching the store, so the re-send is not
        a replay (the placement MOVED contract)."""
        parsed = liveconfig.parse_moved(str(exc))
        if parsed is None:
            return None
        pkind, old, new, _version = parsed
        if pkind != kind or old == new:
            return None
        self._config_fwd[(pkind, old[0], old[1])] = new
        # A rule old→new contradicts any cached new→old (a revert
        # retired the cached entry's world): evict it, or the resolve
        # walk would bounce between the pair instead of landing on the
        # serving config.
        if self._config_fwd.get((pkind, new[0], new[1])) == old:
            del self._config_fwd[(pkind, new[0], new[1])]
        return self._fwd_config(pkind, new[0], new[1])

    async def _chase_config(self, kind: str, a: float, b: float, call):
        """THE live-config translation contract, shared by every keyed
        lane: translate up front through the learned rules, and on the
        routable "config moved" error learn the rule and re-send ONCE
        with the new operands (the gate answered without touching the
        store — not a replay). ``call(a, b)`` awaits the actual wire
        op."""
        a, b = self._fwd_config(kind, a, b)
        try:
            return await call(a, b)
        except wire.RemoteStoreError as exc:
            fwd = self._learn_config(exc, kind)
            if fwd is None:
                raise
            return await call(fwd[0], fwd[1])

    def _chase_config_blocking(self, kind: str, a: float, b: float,
                               call):
        a, b = self._fwd_config(kind, a, b)
        try:
            return call(a, b)
        except wire.RemoteStoreError as exc:
            fwd = self._learn_config(exc, kind)
            if fwd is None:
                raise
            return call(fwd[0], fwd[1])

    async def _keyed_admission(self, op: int, kind: str, key: str,
                               count: int, a: float, b: float
                               ) -> AcquireResult:
        granted, remaining = await self._chase_config(
            kind, a, b,
            lambda a2, b2: self._request(op, key, count, a2, b2))
        return AcquireResult(granted, remaining)

    def _keyed_admission_blocking(self, op: int, kind: str, key: str,
                                  count: int, a: float, b: float
                                  ) -> AcquireResult:
        granted, remaining = self._chase_config_blocking(
            kind, a, b,
            lambda a2, b2: self._request_blocking(op, key, count,
                                                  a2, b2))
        return AcquireResult(granted, remaining)

    # -- BucketStore API ----------------------------------------------------
    # ``timeout_s`` overrides ``request_timeout_s`` for ONE call (the
    # per-call deadline the cluster's breaker probes and latency-bound
    # callers use). A per-call timeout bypasses frame coalescing — the
    # shared-flush lane cannot honor one member's tighter deadline.
    async def acquire(self, key: str, count: int, capacity: float,
                      fill_rate_per_sec: float, *,
                      timeout_s: "float | None" = None) -> AcquireResult:
        return await self._chase_config(
            "bucket", capacity, fill_rate_per_sec,
            lambda a, b: self._acquire_once(key, count, a, b, timeout_s))

    async def _acquire_once(self, key: str, count: int, capacity: float,
                            fill_rate_per_sec: float,
                            timeout_s: "float | None") -> AcquireResult:
        if self._coalesce and timeout_s is None:
            return await self._await_on_io(self._acquire_coalesced_io(
                key, count, capacity, fill_rate_per_sec,
                tracing.current_context()))
        granted, remaining = await self._request(
            wire.OP_ACQUIRE, key, count, capacity, fill_rate_per_sec,
            timeout_s=timeout_s)
        return AcquireResult(granted, remaining)

    def acquire_blocking(self, key: str, count: int, capacity: float,
                         fill_rate_per_sec: float, *,
                         timeout_s: "float | None" = None) -> AcquireResult:
        return self._chase_config_blocking(
            "bucket", capacity, fill_rate_per_sec,
            lambda a, b: self._acquire_once_blocking(key, count, a, b,
                                                     timeout_s))

    def _acquire_once_blocking(self, key: str, count: int,
                               capacity: float, fill_rate_per_sec: float,
                               timeout_s: "float | None") -> AcquireResult:
        if self._coalesce and timeout_s is None:
            return self._submit(self._acquire_coalesced_io(
                key, count, capacity, fill_rate_per_sec,
                tracing.current_context())).result(
                self._blocking_timeout())
        granted, remaining = self._request_blocking(
            wire.OP_ACQUIRE, key, count, capacity, fill_rate_per_sec,
            timeout_s=timeout_s)
        return AcquireResult(granted, remaining)

    def peek_blocking(self, key: str, capacity: float,
                      fill_rate_per_sec: float) -> float:
        (value,) = self._chase_config_blocking(
            "bucket", capacity, fill_rate_per_sec,
            lambda a, b: self._request_blocking(wire.OP_PEEK, key, 0,
                                                a, b))
        return value

    async def sync_counter(self, key: str, local_count: float,
                           decay_rate_per_sec: float, *,
                           timeout_s: "float | None" = None) -> SyncResult:
        score, ewma = await self._request(
            wire.OP_SYNC, key, 0, local_count, decay_rate_per_sec,
            timeout_s=timeout_s)
        return SyncResult(score, ewma)

    def sync_counter_blocking(self, key: str, local_count: float,
                              decay_rate_per_sec: float, *,
                              timeout_s: "float | None" = None
                              ) -> SyncResult:
        score, ewma = self._request_blocking(
            wire.OP_SYNC, key, 0, local_count, decay_rate_per_sec,
            timeout_s=timeout_s)
        return SyncResult(score, ewma)

    async def concurrency_acquire(self, key: str, count: int,
                                  limit: int) -> AcquireResult:
        granted, active = await self._request(
            wire.OP_SEMA, key, count, float(limit), 0.0)
        return AcquireResult(granted, active)

    def concurrency_acquire_blocking(self, key: str, count: int,
                                     limit: int) -> AcquireResult:
        granted, active = self._request_blocking(
            wire.OP_SEMA, key, count, float(limit), 0.0)
        return AcquireResult(granted, active)

    async def concurrency_release(self, key: str, count: int) -> None:
        await self._request(wire.OP_SEMA, key, -count, 0.0, 0.0)

    def concurrency_release_blocking(self, key: str, count: int) -> None:
        self._request_blocking(wire.OP_SEMA, key, -count, 0.0, 0.0)

    async def window_acquire(self, key: str, count: int, limit: float,
                             window_sec: float) -> AcquireResult:
        return await self._keyed_admission(wire.OP_WINDOW, "window",
                                           key, count, limit, window_sec)

    def window_acquire_blocking(self, key: str, count: int, limit: float,
                                window_sec: float) -> AcquireResult:
        return self._keyed_admission_blocking(
            wire.OP_WINDOW, "window", key, count, limit, window_sec)

    async def fixed_window_acquire(self, key: str, count: int, limit: float,
                                   window_sec: float) -> AcquireResult:
        return await self._keyed_admission(wire.OP_FWINDOW, "fwindow",
                                           key, count, limit, window_sec)

    def fixed_window_acquire_blocking(self, key: str, count: int,
                                      limit: float,
                                      window_sec: float) -> AcquireResult:
        return self._keyed_admission_blocking(
            wire.OP_FWINDOW, "fwindow", key, count, limit, window_sec)

    async def ping(self, *, timeout_s: "float | None" = None) -> None:
        await self._request(wire.OP_PING, timeout_s=timeout_s)

    def resilience_stats(self) -> dict:
        """Client-side resilience counters: retries issued, request
        timeouts (:class:`StoreTimeoutError`), consecutive dial
        failures, and whether the reconnect backoff window is CURRENTLY
        open (not merely "was ever armed")."""
        loop = self._io_loop
        backing_off = (loop is not None
                       and self._backoff_until > loop.time())
        return {
            "retries": self._retries,
            "timeouts": self._timeouts,
            "connect_failures": self._connect_failures,
            "backing_off": backing_off,
            "hier_fallbacks": self._hier_fallbacks,
            "reserve_fallbacks": self._reserve_fallbacks,
            "reserves_routed": self._reserves_routed,
        }

    async def save(self) -> None:
        """Ask the server to checkpoint its store to its configured path
        (≙ Redis ``BGSAVE``). Raises :class:`wire.RemoteStoreError` if the
        server has no snapshot path."""
        await self._request(wire.OP_SAVE)

    async def stats(self, reset: bool = False,
                    dump_flight: bool = False) -> dict:
        """Server + store metrics (requests served, kernel launches, batch
        occupancy, sweeps …) as a dict. ``reset=True`` additionally asks
        the server to start a fresh serving/stage-latency window after the
        snapshot — measurement runs use it to exclude warmup.
        ``dump_flight=True`` triggers an explicit flight-recorder dump on
        the server first (the returned ``flight_recorder.last_dump_path``
        names the file on the SERVER's disk)."""
        import json

        flags = ((wire.STATS_FLAG_RESET if reset else 0)
                 | (wire.STATS_FLAG_FLIGHT_DUMP if dump_flight else 0))
        (text,) = await self._request(wire.OP_STATS, count=flags)
        return json.loads(text)

    async def metrics(self) -> str:
        """The server's OpenMetrics text exposition (``OP_METRICS``) —
        the same bytes its HTTP ``/metrics`` endpoint serves, for
        consumers already on the wire (``ClusterBucketStore.
        cluster_metrics`` scrapes every node through this)."""
        (text,) = await self._request(wire.OP_METRICS)
        return text

    # -- placement / migration control plane (runtime/placement.py) ---------
    async def placement_fetch(self, *,
                              timeout_s: "float | None" = None) -> dict:
        """The node's adopted placement map + handoff state
        (``OP_PLACEMENT``); ``{"epoch": -1, …}`` from a node no
        coordinator has announced to yet."""
        import json

        (text,) = await self._request(wire.OP_PLACEMENT,
                                      timeout_s=timeout_s)
        return json.loads(text)

    async def placement_announce(self, payload: dict, *,
                                 timeout_s: "float | None" = None) -> int:
        """Announce a placement map (``{"map": …, "node_id": j}``) or an
        abort (``{"abort_epoch": e}``) to the node; returns the node's
        adopted epoch. Stale epochs surface as
        :class:`wire.RemoteStoreError`."""
        import json

        (epoch,) = await self._request(
            wire.OP_PLACEMENT_ANNOUNCE, json.dumps(payload),
            timeout_s=timeout_s)
        return int(epoch)

    async def migrate_pull(self, req: dict, *,
                           timeout_s: "float | None" = None) -> dict:
        """Export + park state on the old owner for a pending epoch
        (``OP_MIGRATE_PULL``; idempotent per target epoch)."""
        import json

        (text,) = await self._request(wire.OP_MIGRATE_PULL,
                                      json.dumps(req),
                                      timeout_s=timeout_s)
        return json.loads(text)

    async def migrate_push(self, req: dict, *,
                           timeout_s: "float | None" = None) -> int:
        """Apply one handoff batch on the new owner
        (``OP_MIGRATE_PUSH``; exactly-once per ``(epoch, batch)``).
        Returns rows applied (0 for a deduplicated re-delivery)."""
        import json

        (applied,) = await self._request(wire.OP_MIGRATE_PUSH,
                                         json.dumps(req),
                                         timeout_s=timeout_s)
        return int(applied)

    async def config_fetch(self, *,
                           timeout_s: "float | None" = None) -> dict:
        """The node's committed live-config state (``OP_CONFIG`` with an
        empty payload): ``{"version": v, "rules": […]}`` —
        ``{"version": 0, "rules": []}`` from a node no mutation has
        reached yet (runtime/liveconfig.py)."""
        import json

        (text,) = await self._request(wire.OP_CONFIG, "{}",
                                      timeout_s=timeout_s)
        return json.loads(text)

    async def config_announce(self, payload: dict, *,
                              timeout_s: "float | None" = None) -> int:
        """Drive one step of a live config mutation on the node:
        ``{"prepare": rule, "version": v}`` / ``{"commit": v}`` /
        ``{"abort": v}`` (two-phase; every form idempotent at its
        version — runtime/liveconfig.py). Returns the node's committed
        version; stale versions surface as
        :class:`wire.RemoteStoreError`."""
        import json

        (version,) = await self._request(
            wire.OP_CONFIG, json.dumps(payload), timeout_s=timeout_s)
        return int(version)

    async def traces(self, drain: bool = False) -> dict:
        """The server's kept traces as Chrome-trace-event JSON
        (``OP_TRACES``) — the same payload its HTTP ``/traces`` endpoint
        serves, for consumers already on the wire. ``drain=True``
        empties the server's buffer after export (size-capped at
        MAX_FRAME; the newest traces win)."""
        import json

        (text,) = await self._request(wire.OP_TRACES,
                                      count=1 if drain else 0)
        return json.loads(text)

    async def audit(self, bundles: int = 0) -> dict:
        """The server's conservation-audit snapshot (``OP_AUDIT``):
        identity residues, ε-budget utilization per source, the
        burn-rate watchdog's state and alert log. ``bundles=N`` ships
        the newest N black-box incident bundles along (heavy —
        correlated flight frames + traces ride inside), matching the
        HTTP ``GET /audit?bundles=N`` surface."""
        import json

        payload = json.dumps({"bundles": bundles}) if bundles else ""
        (text,) = await self._request(wire.OP_AUDIT, payload)
        return json.loads(text)

    # -- lifecycle ----------------------------------------------------------
    async def aclose(self) -> None:
        if self._closed:
            return
        with self._thread_gate:
            # Under the submit gate: every concurrent _submit either
            # already enqueued (ahead of the stop below) or fails fast.
            self._closed = True
        loop = self._io_loop
        if loop is None:
            return

        async def shutdown() -> None:
            self._drop_connection(ConnectionError("store client closed"))
            # Drain coalescing batchers AFTER the drop: their flushes hit
            # the closed connection and fail every parked waiter cleanly
            # (reconnects are gated off by _closed).
            # list(): a coalesced acquire queued just before shutdown can
            # still insert a batcher while we await acloses.
            for b in list(self._acquire_batchers.values()):
                await b.aclose()

        await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
            shutdown(), loop))
        loop.call_soon_threadsafe(loop.stop)
        if self._io_thread is not None:
            # to_thread: a 5s worst-case join must not stall the
            # CALLER's event loop (drl-check async-blocking).
            await asyncio.to_thread(self._io_thread.join, 5.0)
        # Close only a stopped loop (drl-check unguarded-loop-close,
        # the pump-alive use-after-free class): if the join timed out
        # the I/O thread is still running the loop — close() under it
        # would raise and hand the live thread a closed loop. Leak it
        # instead (daemon thread, dies with the process) — the same
        # guard cluster.py aclose carries.
        if self._io_thread is None or not self._io_thread.is_alive():
            loop.close()
        self._io_loop = None

    def snapshot(self) -> dict:
        raise NotImplementedError(
            "snapshot/restore runs on the server's store — durable state "
            "lives with the store, clients are stateless (SURVEY.md §5.4)"
        )

    def restore(self, snap: dict) -> None:
        raise NotImplementedError(
            "snapshot/restore runs on the server's store — durable state "
            "lives with the store, clients are stateless (SURVEY.md §5.4)"
        )
