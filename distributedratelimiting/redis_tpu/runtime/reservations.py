"""Estimate-reserve-settle — the streaming reservation lane (ROADMAP 3).

PR 9 meters admission by token cost, but a real LLM gateway does not
*know* the cost at admission time: the output length is unknown until
generation ends — exactly the gap "Token-Budget-Aware Pool Routing" and
"TokenScale" (PAPERS.md) identify between admission-time budgeting and
actual token spend. This module closes it with a three-phase protocol
over the existing hierarchical (tenant → key) budget machinery:

1. **reserve** — admit an *estimated* cost against the tenant → key
   budgets (the same grant-iff-both-levels ``acquire_hierarchical``
   decision every metered request takes), and hold a TTL'd reservation
   in a bounded server-side ledger. When the caller supplies no
   estimate, a per-``(tenant, priority)`` prior learned from settled
   actuals supplies one: interactive reserves the prior's p99 (a tail
   overrun on an interactive stream must be rare), batch and scavenger
   reserve the mean (throughput traffic amortizes its own variance).
2. **stream** — the tokens flow; the reservation is the budget hold.
3. **settle** — reconcile the *actual* cost. Over-estimates refund
   through the existing saturating negative-debit lane (``debit_many``
   with a negative amount — the PR-9 refund primitive; the capacity
   clamp on the next refill bounds any transient overshoot, so a
   refund can only under-credit, the safe direction). Under-estimates
   debit the extra through the same saturating kernel; whatever the
   tenant bucket cannot cover becomes **per-tenant debt** that the
   next ``reserve`` must pay down — through the same ``debit_many``
   primitive — before new admission.

**Idempotency** (docs/DESIGN.md §18): both halves key on the caller's
reservation id. A retried ``reserve`` of a granted id returns the
recorded decision without a second debit; a retried ``settle`` of a
settled id is a counted no-op replaying the recorded result. That makes
``OP_RESERVE``/``OP_SETTLE`` application-idempotent — post-send-retry-
safe in the at-most-once contract, the OP_MIGRATE_PUSH posture.

**TTL** — a client that dies mid-stream leaves its reservation behind;
on expiry the ledger auto-settles it *at the estimate* (delta zero: the
hold simply becomes the spend — conservative, no refund is owed to a
caller that never reported), counted and flight-recorded. Expiry is
piggybacked on every ledger touch (and the stats scrape), so it needs
no background task and stays deterministic under an injected clock.

**Why debt is per-tenant, not per-key** — the tenant budget is the
contract being enforced (the paper's hierarchical composition); child
keys are ephemeral routing identities a client can mint freely, so
per-key debt would be trivially evaded by rotating keys while the
tenant's real overdraft went untracked.

The ledger survives the hard cases the repo already handles for plain
grants: live migration exports outstanding entries (and debts) as
``"reservations"``/``"debts"`` entry sections in the MIGRATE_PULL
payload (restored on abort, adopted by the new owner's ledger on push);
OP_CONFIG rebases re-home entries lazily — settle translates each
entry's recorded configs through the committed forwarding rules, so
refunds/debits land in the table the rebase moved the balance to; drain
windows relay settles to the successor; and ``stats(reset=True)`` never
touches the ledger (the monotonic-counter contract, PR 12)."""

from __future__ import annotations

import asyncio
import heapq
import json
import math
import time
from collections import OrderedDict
from typing import Callable, NamedTuple

from distributedratelimiting.redis_tpu.utils.metrics import (
    LatencyHistogram,
)

__all__ = [
    "ReserveResult", "SettleResult", "EstimatePrior",
    "ReservationLedger", "DEFAULT_TTL_S", "fallback_charge",
    "ROUTE_PREFIX", "route_message", "parse_route",
]

#: Default reservation TTL: generous for an LLM stream (minutes-long
#: generations pass ``ttl_s`` explicitly), short enough that a crashed
#: client's hold stops distorting the budget within one operator glance.
DEFAULT_TTL_S = 30.0

#: When neither the caller nor the prior has an estimate (a brand-new
#: tenant's first request), reserve this many tokens. Deliberately
#: modest: the first settle seeds the prior, so the blind window is one
#: request per (tenant, priority).
DEFAULT_ESTIMATE = 64.0


# -- route-to-pool redirect (budget-aware pool routing, DESIGN.md §24) -------

#: Marker prefix of the OP_RESERVE "route-to-pool" redirect reply — a
#: routable RESP_ERROR whose message carries the overflow pool's config
#: as JSON. The MOVED posture: an error to peers that do not speak it
#: (they surface it and fall back), a chase-once redirect to peers that
#: do (remote.reserve re-sends ONCE against the named pool and marks
#: the result ``routed=True``).
ROUTE_PREFIX = "route-to-pool "


def route_message(pool: str, ta: float, tb: float,
                  priority: int) -> str:
    """Encode the redirect reply body: the overflow pool's name, its
    tenant-level config ``(ta, tb)`` and the priority class the routed
    request is demoted to (batch — it left the interactive pool)."""
    return ROUTE_PREFIX + json.dumps(
        {"pool": pool, "ta": float(ta), "tb": float(tb),
         "priority": int(priority)},
        ensure_ascii=True, sort_keys=True)


def parse_route(message: str) -> "dict | None":
    """Parse a redirect out of a relayed error message, or ``None``
    when the error is not a route-to-pool reply (the client treats it
    as the plain error it is). Tolerant of relay prefixes — the marker
    is searched, not anchored — but strict about the JSON body: a
    mangled redirect is a plain error, never a half-parsed route."""
    idx = message.find(ROUTE_PREFIX)
    if idx < 0:
        return None
    try:
        obj = json.loads(message[idx + len(ROUTE_PREFIX):])
    except ValueError:
        return None
    if not isinstance(obj, dict) or "pool" not in obj \
            or "ta" not in obj or "tb" not in obj:
        return None
    return obj


def fallback_charge(estimate: "float | None") -> int:
    """The charge for reserve paths with NO ledger or prior in reach
    (the old-peer flat fallback, the cluster's degraded-envelope
    fallback): the caller's estimate when given, else
    :data:`DEFAULT_ESTIMATE` — the same floor the ledger itself
    applies, so a degraded path can never admit a typical stream for a
    1-token charge (that would be over-admission exactly where the
    docstrings promise the conservative direction)."""
    if estimate and estimate > 0:
        return max(1, int(math.ceil(float(estimate))))
    return int(DEFAULT_ESTIMATE)


class ReserveResult(NamedTuple):
    granted: bool
    #: Tokens actually held (the charge — the settle's baseline).
    reserved: float
    #: Binding level's post-decision balance estimate (0.0 on deny).
    remaining: float
    #: The tenant's unsettled debt AFTER this reserve's pay-down pass.
    debt: float
    #: True when this answer replayed a recorded decision (retry dedup).
    duplicate: bool = False
    #: True when an old peer forced the flat acquire-at-estimate path.
    fallback: bool = False
    #: True when the grant came from a route-to-pool redirect chase
    #: (the request was admitted in the OVERFLOW pool, not the one the
    #: caller named — docs/DESIGN.md §24).
    routed: bool = False
    #: The pool (tenant-bucket key) a routed grant landed in — the
    #: settle must target this name, not the original tenant (the
    #: ledger hold lives under the pool's budget).
    pool: "str | None" = None


class SettleResult(NamedTuple):
    #: "settled" | "duplicate" | "unknown" | "expired" | "fallback".
    outcome: str
    #: actual − reserved (the estimate error this settle reconciled).
    delta: float
    #: Tokens credited back (over-estimate refund actually issued).
    refunded: float
    #: The tenant's unsettled debt after this settle.
    debt: float


class EstimatePrior:
    """Per-``(tenant, priority)`` cost prior, learned from settled
    actuals. Bounded two ways: at most ``max_groups`` (tenant, priority)
    rings (oldest-touched evicted first), each keeping the newest
    ``window`` samples. Interactive estimates read the ring's p99;
    batch/scavenger read the mean (module docstring). A priority with
    no samples falls back to the tenant's other priorities' merged
    samples before giving up — a tenant's batch history is a better
    prior for its first interactive request than a global constant."""

    def __init__(self, window: int = 128, max_groups: int = 1024) -> None:
        if window < 1 or max_groups < 1:
            raise ValueError("window and max_groups must be >= 1")
        self.window = window
        self.max_groups = max_groups
        self._rings: "OrderedDict[tuple[str, int], list[float]]" = \
            OrderedDict()

    def observe(self, tenant: str, priority: int, actual: float) -> None:
        if actual <= 0 or not math.isfinite(actual):
            return
        key = (tenant, int(priority))
        ring = self._rings.get(key)
        if ring is None:
            if len(self._rings) >= self.max_groups:
                self._rings.popitem(last=False)
            ring = self._rings[key] = []
        else:
            self._rings.move_to_end(key)
        ring.append(float(actual))
        if len(ring) > self.window:
            del ring[: len(ring) - self.window]

    def _samples(self, tenant: str, priority: int) -> "list[float]":
        ring = self._rings.get((tenant, int(priority)))
        if ring:
            return ring
        merged: list[float] = []
        for (t, _p), r in self._rings.items():
            if t == tenant:
                merged.extend(r)
        return merged

    def estimate(self, tenant: str, priority: int) -> "float | None":
        """The reserve amount this prior recommends, or ``None`` when
        it has never seen the tenant settle. Interactive → p99 of the
        window; everything else → mean."""
        samples = self._samples(tenant, priority)
        if not samples:
            return None
        if int(priority) == 0:  # admission.PRIORITY_INTERACTIVE
            ordered = sorted(samples)
            idx = min(len(ordered) - 1,
                      int(math.ceil(0.99 * len(ordered))) - 1)
            return ordered[max(idx, 0)]
        return sum(samples) / len(samples)

    def __len__(self) -> int:
        return len(self._rings)


class _Reservation:
    __slots__ = ("rid", "tenant", "key", "reserved", "a", "b", "ta",
                 "tb", "priority", "expires_at", "remaining",
                 "deadline_at")

    def __init__(self, rid: str, tenant: str, key: str, reserved: float,
                 a: float, b: float, ta: float, tb: float,
                 priority: int, expires_at: float,
                 remaining: float,
                 deadline_at: "float | None" = None) -> None:
        self.rid = rid
        self.tenant = tenant
        self.key = key
        self.reserved = reserved
        self.a = a
        self.b = b
        self.ta = ta
        self.tb = tb
        self.priority = priority
        self.expires_at = expires_at
        self.remaining = remaining
        #: Ledger-clock instant the CLIENT's propagated budget runs out
        #: (None when the reserve carried no deadline). Settles after it
        #: are useless work — the goodput sensor's raw signal.
        self.deadline_at = deadline_at


class ReservationLedger:
    """The server-side reservation state for ONE store (module
    docstring). Bounded everywhere: ``max_entries`` outstanding holds
    (overflow reserves are DENIED, loudly counted — availability of the
    metered path over unbounded ledger growth), ``max_settled`` retry-
    dedup records (oldest evicted), the prior's own caps. One asyncio
    lock serializes reserve/settle bodies — their dedup checks span
    store awaits, the placement ``_control_lock`` posture."""

    def __init__(self, store, *, max_entries: int = 65536,
                 default_ttl_s: float = DEFAULT_TTL_S,
                 default_estimate: float = DEFAULT_ESTIMATE,
                 max_settled: int = 8192,
                 clock: Callable[[], float] = time.monotonic,
                 flight_recorder=None, velocity=None,
                 liveconfig=None) -> None:
        if max_entries < 1 or max_settled < 1:
            raise ValueError("ledger bounds must be >= 1")
        if default_ttl_s <= 0:
            raise ValueError("default_ttl_s must be positive")
        self._store = store
        self.max_entries = max_entries
        self.default_ttl_s = float(default_ttl_s)
        self.default_estimate = float(default_estimate)
        self.max_settled = max_settled
        self._clock = clock
        self.flight_recorder = flight_recorder
        #: Optional TokenVelocity: settles feed it at the ACTUAL cost —
        #: the true spend, which is what the velocity signal promises
        #: (the reserve-time estimate is covered by the outstanding
        #: gauge instead, closing the sensing gap the module docstring
        #: names).
        self.velocity = velocity
        #: Optional liveconfig.ConfigState: settle-time config
        #: translation (lazy re-home through committed rules).
        self.liveconfig = liveconfig
        self._entries: dict[str, _Reservation] = {}
        #: (expires_at, rid) min-heap; entries validate lazily (a
        #: settled rid's heap row is simply skipped).
        self._expiry: list[tuple[float, str]] = []
        #: rid → recorded SettleResult fields (retry dedup).
        self._settled: "OrderedDict[str, SettleResult]" = OrderedDict()
        self._debts: dict[str, float] = {}
        #: tenant → outstanding reserved tokens (maintained O(1)).
        self._outstanding: dict[str, float] = {}
        self.prior = EstimatePrior()
        self._lock = asyncio.Lock()
        # Visible counters (OP_STATS "reservations" + drl_reservation_*).
        # MONOTONIC — never cleared by stats(reset=True) (the PR-12
        # counter contract; test-pinned).
        self.reserves = 0
        self.reserve_denied = 0
        self.reserve_duplicates = 0
        self.ledger_full_denials = 0
        self.debt_denials = 0
        self.settles = 0
        self.settle_duplicates = 0
        self.settle_unknown = 0
        self.ttl_expired = 0
        self.refunds = 0
        self.refunded_tokens = 0.0
        self.debts_created = 0
        self.debt_tokens_created = 0.0
        self.debt_tokens_collected = 0.0
        self.rehomed = 0
        self.aborted_imports = 0
        self.reserved_tokens_total = 0.0
        self.settled_tokens_total = 0.0
        # Movement counters closing the conservation identity the audit
        # plane checks every tick (runtime/audit.py, DESIGN.md §22):
        #   reserved + restored_in + extra_debited ==
        #   settled + refunded + exported_out + dropped + forfeited
        #   + outstanding
        # Each names one flow across the ledger boundary that the
        # pre-existing counters above do not witness; without them the
        # identity only closes cluster-wide (migration flows cancel),
        # not per node — and per node is where the auditor runs.
        #: Settle-time overage debits (actual > reserved): tokens that
        #: entered the settled total without ever being held.
        self.extra_debited_tokens = 0.0
        #: Holds shipped out via migration export (placement pull).
        self.exported_tokens_out = 0.0
        #: Holds adopted via migration import / abort restore.
        self.restored_tokens_in = 0.0
        #: Holds dropped unsettled by a migration abort (drop_rids).
        self.dropped_tokens = 0.0
        #: Unspent holds a store without a negative-debit lane could
        #: not credit back — under-admission, counted so the identity
        #: still closes.
        self.forfeited_tokens = 0.0
        # Goodput plane (docs/DESIGN.md §24): first-attempt vs retry
        # admission, and how grants relate to their clients' propagated
        # deadlines — the controller's goodput sensor reads these.
        #: Grants whose reserve carried attempt == 0 (or no counter).
        self.first_attempt_grants = 0
        #: Grants whose reserve carried attempt >= 1 — tokens handed to
        #: retry traffic, the storm's amplification signal.
        self.retry_grants = 0
        #: Reserve calls (granted or not) stamped attempt >= 1.
        self.retry_reserves = 0
        #: Settles that landed AT OR BEFORE the recorded deadline —
        #: useful work, the goodput numerator's ledger half.
        self.settled_in_deadline = 0
        #: Settles that landed AFTER the recorded deadline: the client
        #: was already gone — granted-but-useless work.
        self.settled_late = 0
        #: TTL-expired entries whose deadline had passed: grants that
        #: burned their hold with no settle inside the client's budget.
        self.deadline_expired_grants = 0
        #: Settle-error magnitudes, log-1.25 bucketed. The histogram
        #: class buckets from 1e-6, so values record at ``tokens × 1e-6``
        #: — quantiles read back ×1e6 (refund_p99_tokens et al).
        self.refund_hist = LatencyHistogram()
        self.debt_hist = LatencyHistogram()

    # -- introspection -------------------------------------------------------
    @property
    def active(self) -> bool:
        """True once the ledger has ever seen traffic (gates the
        OP_STATS section so unused servers keep their old shape)."""
        return bool(self.reserves or self.settles or self._entries
                    or self._debts)

    def outstanding_count(self) -> int:
        return len(self._entries)

    def outstanding_tokens(self) -> float:
        return sum(self._outstanding.values())

    def outstanding_by_tenant(self) -> dict[str, float]:
        return dict(self._outstanding)

    def debts(self) -> dict[str, float]:
        return dict(self._debts)

    # -- config re-homing (OP_CONFIG rebase) ---------------------------------
    def _cfg(self, a: float, b: float) -> tuple[float, float]:
        """Translate a possibly-retired bucket config through the
        committed forwarding rules to its fixpoint — the lazy half of
        the OP_CONFIG rebase: the commit already re-homed the BALANCES
        through the rebase debit (liveconfig), so a settle's refund or
        extra debit must land in the table they moved to. Counted when
        a translation actually applies."""
        lc = self.liveconfig
        if lc is None or not lc.active:
            return a, b
        seen: set[tuple[float, float]] = set()
        pair = (float(a), float(b))
        while pair not in seen:
            seen.add(pair)
            fwd = lc.forward("bucket", pair[0], pair[1])
            if fwd is None:
                break
            pair = (float(fwd[0]), float(fwd[1]))
        if pair != (float(a), float(b)):
            self.rehomed += 1
        return pair

    # -- TTL expiry (sync: an expiry applies NO store adjustment) ------------
    def expire(self, now: "float | None" = None) -> int:
        """Auto-settle every expired reservation at its estimate.
        Delta zero by construction — the hold becomes the spend, no
        store call needed — so this is synchronous and piggybacks on
        every ledger touch plus the stats scrape. Returns the number
        expired."""
        now = self._clock() if now is None else now
        n = 0
        while self._expiry and self._expiry[0][0] <= now:
            _, rid = heapq.heappop(self._expiry)
            entry = self._entries.get(rid)
            if entry is None or entry.expires_at > now:
                continue  # settled already, or TTL extended — stale row
            self._drop_entry(entry)
            result = SettleResult("expired", 0.0, 0.0,
                                  self._debts.get(entry.tenant, 0.0))
            self._record_settled(rid, result)
            self.ttl_expired += 1
            if entry.deadline_at is not None and now > entry.deadline_at:
                self.deadline_expired_grants += 1
            self.settles += 1
            self.settled_tokens_total += entry.reserved
            if self.velocity is not None and entry.reserved > 0:
                self.velocity.observe(entry.tenant, entry.reserved)
            if self.flight_recorder is not None:
                self.flight_recorder.record(
                    "reservation", event="ttl_expired", rid=rid,
                    tenant=entry.tenant, reserved=entry.reserved)
            n += 1
        return n

    def _drop_entry(self, entry: _Reservation) -> None:
        self._entries.pop(entry.rid, None)
        out = self._outstanding.get(entry.tenant, 0.0) - entry.reserved
        if out <= 1e-9:
            self._outstanding.pop(entry.tenant, None)
        else:
            self._outstanding[entry.tenant] = out

    def _add_entry(self, entry: _Reservation) -> None:
        self._entries[entry.rid] = entry
        self._outstanding[entry.tenant] = \
            self._outstanding.get(entry.tenant, 0.0) + entry.reserved
        heapq.heappush(self._expiry, (entry.expires_at, entry.rid))

    def _record_settled(self, rid: str, result: SettleResult) -> None:
        self._settled[rid] = result
        while len(self._settled) > self.max_settled:
            self._settled.popitem(last=False)

    # -- reserve -------------------------------------------------------------
    async def reserve(self, rid: str, tenant: str, key: str,
                      estimate: "float | None",
                      tenant_capacity: float,
                      tenant_fill_rate_per_sec: float,
                      capacity: float, fill_rate_per_sec: float, *,
                      priority: int = 0,
                      ttl_s: "float | None" = None,
                      attempt: int = 0,
                      deadline_s: "float | None" = None) -> ReserveResult:
        """One admission-at-estimate decision + ledger hold (module
        docstring). Outstanding tenant debt is paid down FIRST through
        the saturating ``debit_many``; debt the budget cannot cover yet
        denies the reserve (the tenant is over budget — the same answer
        its empty bucket would give, reported honestly as debt).
        ``attempt`` fingerprints retries (0 = first attempt — the
        retry-stable rid plus the wire attempt tail); ``deadline_s`` is
        the client's remaining budget, recorded so the settle can be
        judged useful-or-late (the goodput sensor's input)."""
        if not rid:
            raise ValueError("reservation id must be non-empty")
        async with self._lock:
            now = self._clock()
            self.expire(now)
            dup = self._duplicate_reserve(rid, tenant)
            if dup is not None:
                return dup
            self.reserves += 1
            if attempt:
                self.retry_reserves += 1
            debt = self._debts.get(tenant, 0.0)
            ta, tb = self._cfg(tenant_capacity, tenant_fill_rate_per_sec)
            a, b = self._cfg(capacity, fill_rate_per_sec)
            if debt >= 1.0:
                debt = await self._collect_debt(tenant, debt, ta, tb)
                if debt >= 1.0:
                    # The budget could not even cover the existing debt:
                    # new admission would deepen the overdraft.
                    self.debt_denials += 1
                    self.reserve_denied += 1
                    return ReserveResult(False, 0.0, 0.0, debt)
            est = float(estimate) if estimate and estimate > 0 else None
            if est is None:
                est = self.prior.estimate(tenant, priority)
            if est is None:
                est = self.default_estimate
            charge = max(1, int(math.ceil(est)))
            if len(self._entries) >= self.max_entries:
                # Bounded ledger: deny loudly rather than grow without
                # limit (a reserve flood that never settles is exactly
                # the shape the TTL + this cap exist for).
                self.ledger_full_denials += 1
                self.reserve_denied += 1
                if self.flight_recorder is not None:
                    self.flight_recorder.record(
                        "reservation", event="ledger_full", rid=rid,
                        tenant=tenant, entries=len(self._entries))
                return ReserveResult(False, 0.0, 0.0, debt)
            res = await self._store.acquire_hierarchical(
                tenant, key, charge, ta, tb, a, b, priority=priority)
            if not res.granted:
                self.reserve_denied += 1
                return ReserveResult(False, 0.0, res.remaining, debt)
            ttl = self.default_ttl_s if ttl_s is None else float(ttl_s)
            self._add_entry(_Reservation(
                rid, tenant, key, float(charge), a, b, ta, tb,
                int(priority), now + ttl, res.remaining,
                (now + float(deadline_s)) if deadline_s is not None
                and deadline_s > 0 else None))
            self.reserved_tokens_total += charge
            if attempt:
                self.retry_grants += 1
            else:
                self.first_attempt_grants += 1
            return ReserveResult(True, float(charge), res.remaining,
                                 debt)

    def _duplicate_reserve(self, rid: str,
                           tenant: str) -> "ReserveResult | None":
        entry = self._entries.get(rid)
        if entry is not None:
            self.reserve_duplicates += 1
            return ReserveResult(True, entry.reserved, entry.remaining,
                                 self._debts.get(tenant, 0.0),
                                 duplicate=True)
        settled = self._settled.get(rid)
        if settled is not None:
            # A reserve retry that arrives AFTER its settle (or TTL):
            # the original was granted (only grants enter the ledger) —
            # answer granted without a second debit. The recorded delta
            # reconstructs the reserved amount where known.
            self.reserve_duplicates += 1
            return ReserveResult(True, 0.0, 0.0,
                                 self._debts.get(tenant, 0.0),
                                 duplicate=True)
        return None

    async def _collect_debt(self, tenant: str, debt: float,
                            ta: float, tb: float) -> float:
        """Pay tenant debt down through the saturating debit; the
        shortfall (tokens the bucket did not hold yet) stays owed."""
        debit = getattr(self._store, "debit_many", None)
        if not callable(debit):
            return debt  # no reconciliation lane: debt persists, deny
        _remaining, shortfall = await debit([tenant], [debt], ta, tb)
        left = float(shortfall[0])
        collected = debt - left
        if collected > 0:
            self.debt_tokens_collected += collected
        if left <= 1e-9:
            self._debts.pop(tenant, None)
            return 0.0
        self._debts[tenant] = left
        return left

    # -- settle --------------------------------------------------------------
    async def settle(self, rid: str, tenant: str,
                     actual: float) -> SettleResult:
        """Reconcile one reservation's actual cost (module docstring).
        Idempotent by rid: a duplicate replays the recorded result with
        ``outcome="duplicate"`` and zero side effects; an unknown rid
        (never reserved here, TTL'd out of the dedup window, or
        reserved through an old-peer fallback) is a counted no-op —
        the conservative direction, the hold was never refunded."""
        if actual < 0 or not math.isfinite(actual):
            raise ValueError("settle actual must be finite and >= 0")
        async with self._lock:
            now = self._clock()
            self.expire(now)
            recorded = self._settled.get(rid)
            if recorded is not None:
                self.settle_duplicates += 1
                return recorded._replace(outcome="duplicate")
            entry = self._entries.get(rid)
            if entry is None:
                self.settle_unknown += 1
                return SettleResult("unknown", 0.0, 0.0,
                                    self._debts.get(tenant, 0.0))
            self._drop_entry(entry)
            if entry.deadline_at is not None:
                if now > entry.deadline_at:
                    self.settled_late += 1
                else:
                    self.settled_in_deadline += 1
            result = await self._settle_entry(entry, float(actual))
            self._record_settled(rid, result)
            return result

    async def _settle_entry(self, entry: _Reservation,
                            actual: float) -> SettleResult:
        delta = actual - entry.reserved
        refunded = 0.0
        debit = getattr(self._store, "debit_many", None)
        # Settle-time config translation: a commit between reserve and
        # settle moved the balances — follow them (module docstring).
        ta, tb = self._cfg(entry.ta, entry.tb)
        a, b = self._cfg(entry.a, entry.b)
        if delta < 0.0:
            # Over-estimate: credit the unspent hold back to BOTH
            # levels through the saturating negative-debit lane — the
            # EXACT delta, fractions included (skipping sub-token
            # residue would drift the settled-vs-balance accounting
            # without bound over many streams). The next refill's
            # capacity clamp bounds any overshoot — the refund can
            # only under-credit (the PR-9 contract).
            refund = -delta
            if callable(debit):
                await debit([entry.key], [-refund], a, b)
                await debit([entry.tenant], [-refund], ta, tb)
                refunded = refund
                self.refunds += 1
                self.refunded_tokens += refund
                self.refund_hist.record(refund * 1e-6)
            else:
                # No negative-debit lane: the hold cannot be credited
                # back. Under-admission (the safe direction), but it
                # must be WITNESSED or the conservation identity reads
                # it as a leak.
                self.forfeited_tokens += refund
        elif delta > 0.0:
            # Under-estimate: charge the overage now. Child shortfall
            # saturates silently (the key bucket can at worst sit at
            # zero); the TENANT shortfall is the real overdraft and
            # becomes debt the next reserve must cover.
            if callable(debit):
                await debit([entry.key], [delta], a, b)
                _rem, short = await debit([entry.tenant], [delta],
                                          ta, tb)
                owed = float(short[0])
            else:
                owed = delta  # no debit lane: carry the whole overage
            if owed > 1e-9:
                self._debts[entry.tenant] = \
                    self._debts.get(entry.tenant, 0.0) + owed
                self.debts_created += 1
                self.debt_tokens_created += owed
            # The overage is an INFLOW across the ledger boundary
            # (settled will exceed the hold by exactly this much) —
            # witnessed whether the debit lane existed or the tenant
            # shortfall became debt.
            self.extra_debited_tokens += delta
            self.debt_hist.record(delta * 1e-6)
        self.settles += 1
        self.settled_tokens_total += actual
        self.prior.observe(entry.tenant, entry.priority, actual)
        if self.velocity is not None and actual > 0:
            self.velocity.observe(entry.tenant, actual)
        if self.flight_recorder is not None and abs(delta) >= 1.0:
            self.flight_recorder.record(
                "reservation", event="settle", rid=entry.rid,
                tenant=entry.tenant, reserved=entry.reserved,
                actual=actual, refunded=refunded,
                debt=self._debts.get(entry.tenant, 0.0))
        return SettleResult("settled", delta, refunded,
                            self._debts.get(entry.tenant, 0.0))

    # -- migration export/import (placement entry sections) ------------------
    def export_rows(self, keep: Callable[[str], bool],
                    tag: "str | None" = None) -> tuple[list, list]:
        """Remove and return the ledger rows whose TENANT ``keep``
        selects — the MIGRATE_PULL half. Reservation rows carry the
        remaining TTL (ages, never absolute times: the two processes'
        clocks never compare — invariant 1); debt rows are
        ``[tenant, amount, tag]`` — ``tag`` names the export episode
        (the pull's target epoch) so a re-delivery dedups: reservation
        rows have their rid for that, but a debt restored on abort and
        re-exported by the same-epoch retry would otherwise DOUBLE at
        the new owner (whose copy of attempt 1's chunk already
        landed). The caller stashes what it got for a possible abort
        restore (:meth:`restore_rows`)."""
        now = self._clock()
        res_rows = []
        for entry in [e for e in self._entries.values()
                      if keep(e.tenant)]:
            self._drop_entry(entry)
            self.exported_tokens_out += entry.reserved
            res_rows.append([entry.tenant, entry.rid, entry.key,
                             entry.reserved, entry.a, entry.b,
                             entry.ta, entry.tb, entry.priority,
                             max(0.1, entry.expires_at - now)])
        debt_rows = [[t, amt, tag] for t, amt in self._debts.items()
                     if keep(t)]
        for t, _amt, _tag in debt_rows:
            del self._debts[t]
        return res_rows, debt_rows

    def drop_rids(self, rids) -> int:
        """Remove outstanding entries for ``rids`` without settling —
        the destination half of a migration ABORT (placement.py
        ``_abort``): rows this node imported for the aborted epoch go
        back out, because the source's stash restore (or the retry's
        re-export) is each rid's single surviving home. Settled
        records stay (a dedup answer is still correct); unknown rids
        are skipped. Counted, returns the number dropped."""
        n = 0
        for rid in rids:
            entry = self._entries.get(str(rid))
            if entry is not None:
                self._drop_entry(entry)
                self.dropped_tokens += entry.reserved
                n += 1
        self.aborted_imports += n
        return n

    #: Seen (tag, tenant) debt deliveries kept for dedup (bounded).
    _DEBT_SEEN_CAP = 4096

    def restore_rows(self, res_rows, debt_rows) -> int:
        """Adopt exported rows — the abort-restore AND the new owner's
        MIGRATE_PUSH import (both sides re-anchor the TTL against their
        own clock). A rid already present (a duplicate push chunk that
        slipped past the batch dedup, or an abort racing a late push)
        keeps the FIRST copy — re-adding would double the outstanding
        gauge. A TAGGED debt row applies once per (tag, tenant) —
        attempt 2 of an aborted migration re-ships the restored debt
        under attempt 1's tag, and the owner that already holds it
        skips the copy; untagged rows (legacy peers) merge additively.
        Returns rows adopted."""
        now = self._clock()
        n = 0
        for row in res_rows or ():
            # Row layout (placement.py _EMPTY_ENTRIES note): tenant
            # FIRST — it is the routing identity split_entries keys on.
            tenant, rid, key, reserved, a, b, ta, tb, prio, ttl = row
            if rid in self._entries or rid in self._settled:
                continue
            self._add_entry(_Reservation(
                str(rid), str(tenant), str(key), float(reserved),
                float(a), float(b), float(ta), float(tb), int(prio),
                now + float(ttl), 0.0))
            self.restored_tokens_in += float(reserved)
            n += 1
        seen = getattr(self, "_debt_seen", None)
        if seen is None:
            seen = self._debt_seen = OrderedDict()
        for row in debt_rows or ():
            tenant, amt = str(row[0]), float(row[1])
            tag = row[2] if len(row) > 2 else None
            if amt <= 0:
                continue
            if tag is not None:
                if (tag, tenant) in seen:
                    continue
                seen[(tag, tenant)] = True
                while len(seen) > self._DEBT_SEEN_CAP:
                    seen.popitem(last=False)
            self._debts[tenant] = self._debts.get(tenant, 0.0) + amt
            n += 1
        return n

    # -- conservation (runtime/audit.py, DESIGN.md §22) ----------------------
    def conservation(self) -> dict:
        """The ledger's flow identity, closed per node: every token
        that crossed INTO the ledger boundary (a reserve hold, an
        adopted migration row, a settle-time overage debit) must be
        findable on the way OUT (settled spend, refund, export, abort
        drop, forfeit) or still held (outstanding). ``residue`` is
        inflow − outflow — zero up to f64 noise, ANY sign of drift is
        a ledger bug (there is no ε term here; estimate error shows up
        as refunds/debts, both witnessed flows)."""
        inflow = (self.reserved_tokens_total + self.restored_tokens_in
                  + self.extra_debited_tokens)
        outflow = (self.settled_tokens_total + self.refunded_tokens
                   + self.exported_tokens_out + self.dropped_tokens
                   + self.forfeited_tokens + self.outstanding_tokens())
        return {
            "inflow": inflow,
            "outflow": outflow,
            "residue": inflow - outflow,
            "reserved": self.reserved_tokens_total,
            "restored_in": self.restored_tokens_in,
            "extra_debited": self.extra_debited_tokens,
            "settled": self.settled_tokens_total,
            "refunded": self.refunded_tokens,
            "exported_out": self.exported_tokens_out,
            "dropped": self.dropped_tokens,
            "forfeited": self.forfeited_tokens,
            "outstanding": self.outstanding_tokens(),
        }

    # -- stats ---------------------------------------------------------------
    def numeric_stats(self) -> dict:
        """Flat numeric dict for ``register_numeric_dict`` — the
        ``drl_reservation_*`` families."""
        return {
            "reserves": self.reserves,
            "reserve_denied": self.reserve_denied,
            "reserve_duplicates": self.reserve_duplicates,
            "ledger_full_denials": self.ledger_full_denials,
            "debt_denials": self.debt_denials,
            "settles": self.settles,
            "settle_duplicates": self.settle_duplicates,
            "settle_unknown": self.settle_unknown,
            "ttl_expired": self.ttl_expired,
            "refunds": self.refunds,
            "refunded_tokens": self.refunded_tokens,
            "debts_created": self.debts_created,
            "debt_tokens_created": self.debt_tokens_created,
            "debt_tokens_collected": self.debt_tokens_collected,
            "rehomed": self.rehomed,
            "aborted_imports": self.aborted_imports,
            "reserved_tokens_total": self.reserved_tokens_total,
            "settled_tokens_total": self.settled_tokens_total,
            "extra_debited_tokens": self.extra_debited_tokens,
            "exported_tokens_out": self.exported_tokens_out,
            "restored_tokens_in": self.restored_tokens_in,
            "dropped_tokens": self.dropped_tokens,
            "forfeited_tokens": self.forfeited_tokens,
            "outstanding": float(len(self._entries)),
            "outstanding_tokens": self.outstanding_tokens(),
            "debt_tokens": sum(self._debts.values()),
            "first_attempt_grants": self.first_attempt_grants,
            "retry_grants": self.retry_grants,
            "retry_reserves": self.retry_reserves,
            "settled_in_deadline": self.settled_in_deadline,
            "settled_late": self.settled_late,
            "deadline_expired_grants": self.deadline_expired_grants,
        }

    def stats(self) -> dict:
        """JSON-shaped summary for OP_STATS embedding (piggybacks one
        expiry pass so a scraped-but-idle server still expires)."""
        self.expire()
        out = self.numeric_stats()
        out["debts"] = {t: round(v, 3)
                        for t, v in sorted(self._debts.items())}
        out["outstanding_by_tenant"] = {
            t: round(v, 3)
            for t, v in sorted(self._outstanding.items())}
        # Settle-error quantiles, read back in TOKENS (recorded ×1e-6).
        for name, hist in (("refund", self.refund_hist),
                           ("debt", self.debt_hist)):
            if hist.total:
                out[f"{name}_p50_tokens"] = round(hist.p50 * 1e6, 1)
                out[f"{name}_p99_tokens"] = round(hist.p99 * 1e6, 1)
        return out
