"""Epoch-versioned keyspace placement — the cluster's ownership map.

Until round 6 the cluster's ``key → node`` split was a static
``crc32(key) % N`` (the Redis-Cluster shape with the hash function where
the slot table should be): every membership change re-homed ~half the
keyspace instantly, with no way to move the state along — each join or
leave was an availability *and* over-admission event. This module
replaces the modulus with a **directory-driven placement map**:

- The keyspace is split into ``n_slots`` fixed slots
  (``slot = crc32(key) % n_slots``, the same stable crc32 every client
  already routes by). A membership change reassigns *slots*, not the
  hash function, so only the moved slots' keys re-home.
- The map is **epoch-versioned**: every reassignment is a new epoch.
  Nodes adopt maps monotonically (a stale announce is a typed, routable
  error) and clients learn new epochs from a ``placement moved`` error +
  refetch — the MOVED-redirect posture, not a coordination service.
- **Hot-shard splitting**: a single key may carry an *override* pinning
  it to a node regardless of its slot — the unit the heavy-hitter
  sketch's top-K feeds (one hot tenant stops sharing a node with its
  whole slot).
- :meth:`PlacementMap.initial` assigns slot ``s`` to node ``s % N`` over
  ``n_slots = N × slots_per_node`` slots, which makes epoch-0 routing
  **bit-identical to the legacy ``crc32 % N``** for every N — adopting
  the map is not itself a resharding event.

Live migration ships bucket state along with ownership using the state
primitives earlier rounds built: the export/import below normalizes any
store's :meth:`snapshot` (host dict or device slot-array schema) into
flat per-key entries, and the *generic* import lane replays them through
the saturating **debit kernel** (``debit_many`` — the tier-0
reconciliation primitive) so a device store adopts migrated balances
with no snapshot surgery; stores with a host-dict schema take the exact
merge lane. Checkpoints carry the placement epoch
(:mod:`~.checkpoint`), so a rejoining node cannot serve a table whose
key memberships predate the current map (typed mismatch → init-on-miss,
the ``SnapshotCorruptError`` posture).

**The dual-ownership bound.** "When Two is Worse Than One" (PAPERS.md)
names the failure: during an ownership transfer, two nodes serving the
same key independently double-admit. Here the handoff window *partitions
the budget* instead of duplicating it: at PULL time the old owner debits
every exported bucket by a fair-share envelope
(:func:`~..models.approximate.headroom_budget`) and keeps serving the
parked keys **only from that envelope** for a bounded ``window_s``;
the new owner imports the debited remainder, and the old owner's own
store is charged for the shipped amount at the same instant
(:func:`debit_source`) so its authoritative residual IS the envelope.
Old + new together can never admit more than the original balance plus
one envelope per key per episode — the same epsilon family the tier-0
cache and the degraded fallback are bounded by — in **every**
termination order: commit (the target epoch's announce drops the parked
state; ``placement moved`` answers take over), coordinator-driven
abort (the old owner resumes authoritative serving from the envelope
residual — under-admitting by the shipped amount until refill, the
conservative direction), and window expiry (the old owner auto-aborts
back to the old epoch; even if a slow commit already announced the new
epoch to the destinations, the source's residual is bounded so the two
owners' combined spend stays inside the envelope bound).
"""

from __future__ import annotations

import json
import math
import time
import zlib
from typing import Callable, Iterable, Mapping, Sequence

import numpy as np

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
)

__all__ = [
    "PlacementMap", "NodePlacementState", "StalePlacementError",
    "PlacementError", "extract_entries", "entry_count",
    "split_entries", "chunk_entries", "debit_source",
    "DEFAULT_SLOTS_PER_NODE", "DEFAULT_ENVELOPE_FRACTION",
    "MOVED_ERROR_PREFIX", "HANDOFF_DEFERRAL_PREFIX",
]

#: Epoch-0 slots per node. The initial map's ``n_slots = N × this`` with
#: slot ``s → s % N`` reproduces ``crc32 % N`` exactly (``crc32 % kN % N
#: == crc32 % N``), and 16 slots/node keeps single-slot moves ≤ ~6% of a
#: node's keyspace — the rebalance granularity.
DEFAULT_SLOTS_PER_NODE = 16

#: Stable prefix of the routable "wrong owner" error — clients detect it
#: with a substring match (the trace/deadline "unknown op" latch posture)
#: and refetch the map instead of failing the caller.
MOVED_ERROR_PREFIX = "placement moved"

#: Stable prefix of the transient "parked mid-handoff, no envelope
#: value" error (PEEK/SYNC/SEMA on a parked key). A healthy node answers
#: it for at most one handoff window — clients must treat it as
#: retryable, never as node failure (breakers exempt it).
HANDOFF_DEFERRAL_PREFIX = "placement handoff in progress"

#: Fair-share fraction of the handoff envelope — the same default as the
#: cluster's degraded fallback (one confidence policy family).
DEFAULT_ENVELOPE_FRACTION = 0.5


class PlacementError(RuntimeError):
    """Membership/migration control-plane failure (health gate, state
    push, commit) — the migration aborted cleanly to the old epoch."""


class StalePlacementError(PlacementError):
    """The announced/requested epoch is older than what this node has
    already adopted (epochs are monotonic; re-announcing the current
    epoch is idempotent, announcing an older one is a protocol error)."""


def _slot_of(key: str, n_slots: int) -> int:
    # byte-identical to parallel.sharded_store.shard_of_key — one hash
    # family for in-mesh shards, cluster slots, and the native router.
    return zlib.crc32(key.encode("utf-8", "surrogateescape")) % n_slots


def keep_predicate(n_slots: int, overrides: Mapping,
                   slots: "frozenset[int] | set[int]",
                   keys: "frozenset[str] | set[str] | None"
                   ) -> Callable[[str], bool]:
    """THE ownership-transfer selection rule, shared by the server-side
    pull and the cluster's in-process lane: the union of the named keys
    and the slot set — a drain moves both its slots AND any override
    pinned here. Override keys route independently of their slot (the
    gate's rule), so a slot move never drags a pinned key's state
    along."""
    def keep(k: str) -> bool:
        if keys and k in keys:
            return True
        return k not in overrides and _slot_of(k, n_slots) in slots
    return keep


class PlacementMap:
    """Immutable epoch-versioned ``slot → node`` map plus per-key
    overrides. Mutation = :meth:`with_assignments` → a new map at
    ``epoch + 1`` (nodes and clients compare epochs, never diffs)."""

    __slots__ = ("epoch", "n_slots", "slot_owner", "overrides",
                 "_override_slot_cache")

    def __init__(self, epoch: int, slot_owner: "Sequence[int] | np.ndarray",
                 overrides: "Mapping[str, int] | None" = None) -> None:
        self.epoch = int(epoch)
        self.slot_owner = np.ascontiguousarray(slot_owner, np.int32)
        self.n_slots = int(len(self.slot_owner))
        if self.n_slots == 0:
            raise ValueError("placement map needs at least one slot")
        self.overrides: dict[str, int] = dict(overrides or {})
        self._override_slot_cache: "np.ndarray | None" = None

    def override_slots(self) -> np.ndarray:
        """Sorted slots the override keys hash into — the bulk lanes'
        prefilter: rows outside these slots can skip the per-key
        override probe entirely (the map is immutable, so this is
        computed once)."""
        cache = self._override_slot_cache
        if cache is None:
            cache = np.unique(np.fromiter(
                (_slot_of(k, self.n_slots) for k in self.overrides),
                np.int64, len(self.overrides)))
            self._override_slot_cache = cache
        return cache

    @classmethod
    def initial(cls, n_nodes: int,
                slots_per_node: int = DEFAULT_SLOTS_PER_NODE
                ) -> "PlacementMap":
        """Epoch-0 map whose routing is bit-identical to the legacy
        ``crc32(key) % n_nodes`` (see module docstring)."""
        if n_nodes < 1:
            raise ValueError("placement needs at least one node")
        n_slots = n_nodes * slots_per_node
        return cls(0, np.arange(n_slots, dtype=np.int32) % n_nodes)

    # -- routing -------------------------------------------------------------
    def slot_of(self, key: str) -> int:
        return _slot_of(key, self.n_slots)

    def node_of(self, key: str) -> int:
        ov = self.overrides.get(key)
        if ov is not None:
            return ov
        return int(self.slot_owner[_slot_of(key, self.n_slots)])

    def route(self, keys: Sequence[str]) -> np.ndarray:
        """Vectorized :meth:`node_of` over a batch — one native crc32
        pass (``route_keys``; KeyBlob-aware) plus a table take; override
        fix-up only runs when overrides exist (they are few by design)."""
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            route_keys,
        )

        slots = route_keys(keys, self.n_slots)
        owners = self.slot_owner[slots].astype(np.int64)
        if self.overrides:
            # Prefilter by slot: only rows that hash into an override
            # key's slot pay the per-key probe — one vectorized isin
            # keeps the zero-copy bulk lane zero-copy for every other
            # row no matter how long the override table lives.
            cand = np.isin(slots, self.override_slots())
            if cand.any():
                ov = self.overrides
                for i in np.nonzero(cand)[0]:
                    j = ov.get(keys[int(i)])
                    if j is not None:
                        owners[i] = j
        return owners

    # -- introspection -------------------------------------------------------
    def owned_slots(self, node: int) -> np.ndarray:
        return np.nonzero(self.slot_owner == node)[0].astype(np.int32)

    def slot_counts(self, n_nodes: int) -> np.ndarray:
        return np.bincount(self.slot_owner, minlength=n_nodes)

    def nodes_in_use(self) -> set[int]:
        used = set(np.unique(self.slot_owner).tolist())
        used.update(self.overrides.values())
        return {int(j) for j in used}

    # -- evolution -----------------------------------------------------------
    def with_assignments(self, moves: "Mapping[int, int] | None" = None,
                         set_overrides: "Mapping[str, int] | None" = None,
                         drop_overrides: "Iterable[str] | None" = None
                         ) -> "PlacementMap":
        """The next epoch: reassign ``moves`` (slot → new owner), add
        ``set_overrides`` (key → node pins), drop ``drop_overrides``."""
        owner = self.slot_owner.copy()
        for slot, node in (moves or {}).items():
            if not 0 <= slot < self.n_slots:
                raise ValueError(f"slot {slot} out of range")
            owner[slot] = node
        ov = dict(self.overrides)
        for k in drop_overrides or ():
            ov.pop(k, None)
        ov.update(set_overrides or {})
        return PlacementMap(self.epoch + 1, owner, ov)

    def rebalance_moves(self, active: Sequence[int]) -> dict[int, int]:
        """Deterministic plan evening slot counts over ``active`` nodes:
        slots leave over-target nodes (and every inactive node) in
        ascending slot order and land on the most-underfilled active
        node. Empty plan = already balanced."""
        active = sorted(set(int(j) for j in active))
        if not active:
            raise ValueError("rebalance needs at least one active node")
        base, extra = divmod(self.n_slots, len(active))
        target = {j: base + (1 if i < extra else 0)
                  for i, j in enumerate(active)}
        have: dict[int, int] = {j: 0 for j in active}
        for s in self.slot_owner.tolist():
            if s in have:
                have[s] += 1
        moves: dict[int, int] = {}
        deficit = {j: target[j] - have[j] for j in active}
        receivers = [j for j in active if deficit[j] > 0]
        if not receivers:
            return {}
        ri = 0
        for slot in range(self.n_slots):
            owner = int(self.slot_owner[slot])
            give = owner not in target or have[owner] > target[owner]
            if not give:
                continue
            while ri < len(receivers) and deficit[receivers[ri]] <= 0:
                ri += 1
            if ri >= len(receivers):
                break
            dst = receivers[ri]
            moves[slot] = dst
            deficit[dst] -= 1
            if owner in have:
                have[owner] -= 1
        return moves

    # -- codec ---------------------------------------------------------------
    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "n_slots": self.n_slots,
                "slot_owner": self.slot_owner.tolist(),
                "overrides": dict(self.overrides)}

    @classmethod
    def from_dict(cls, data: Mapping) -> "PlacementMap":
        m = cls(data["epoch"], data["slot_owner"],
                data.get("overrides") or {})
        if m.n_slots != data.get("n_slots", m.n_slots):
            raise ValueError("placement map n_slots disagrees with its "
                             "slot_owner table")
        return m

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, text: str) -> "PlacementMap":
        return cls.from_dict(json.loads(text))

    def __eq__(self, other) -> bool:
        return (isinstance(other, PlacementMap)
                and self.epoch == other.epoch
                and np.array_equal(self.slot_owner, other.slot_owner)
                and self.overrides == other.overrides)

    def __repr__(self) -> str:
        return (f"PlacementMap(epoch={self.epoch}, n_slots={self.n_slots},"
                f" nodes={sorted(self.nodes_in_use())},"
                f" overrides={len(self.overrides)})")


# -- normalized state entries (the handoff payload) --------------------------
#
# One schema-free form for "a key's limiter state", JSON-safe so it rides
# RESP_TEXT migration frames:
#
#   {"buckets":  [[key, capacity, rate_per_sec, tokens, age_ticks], …],
#    "windows":  [[key, limit, wt_ticks, interp, prev, curr, idx_behind], …],
#    "counters": [[key, value, period, age_ticks], …],
#    "semas":    [[key, active], …]}
#
# Timestamps travel as AGES relative to the exporting snapshot's clock
# (``now_ticks − ts``) — the two processes' clock epochs never compare
# (invariant 1); the importer re-anchors against its own now.

#: ``reservations`` rows are the estimate-reserve-settle ledger's
#: outstanding holds (``[tenant, rid, key, reserved, a, b, ta, tb,
#: priority, ttl_remaining_s]`` — row[0] is the TENANT because that is
#: the routing identity hierarchical traffic and its settles follow);
#: ``debts`` rows are ``[tenant, amount, export_tag]``. Both ride the same
#: chunk/dedup/push machinery as bucket state; an old importer simply
#: ignores the unknown sections (the reserved tokens stay debited and
#: unrefunded — under-admission, the safe direction).
_EMPTY_ENTRIES = {"buckets": [], "windows": [], "counters": [],
                  "semas": [], "reservations": [], "debts": []}


def entry_count(entries: Mapping) -> int:
    return sum(len(entries.get(k, ())) for k in _EMPTY_ENTRIES)


def extract_entries(snap: Mapping, keep: Callable[[str], bool]) -> dict:
    """Filter a store snapshot down to the keys ``keep`` selects, in the
    normalized entry form. Understands both snapshot schemas in-tree:
    the host-dict form (:class:`~.store.InProcessBucketStore`) and the
    device slot-array form (:class:`~.store.DeviceBucketStore` — per-
    table key directory + SoA arrays)."""
    if "buckets" not in snap and "tables" not in snap:
        # Unknown schema must fail LOUDLY: an empty export would commit
        # a migration that silently dropped the keyspace's state (the
        # coordinator's abort path exists exactly for this).
        raise ValueError(
            "unrecognized snapshot schema (neither host-dict 'buckets' "
            "nor device 'tables'); this store cannot export handoff "
            "entries")
    now = int(snap["now_ticks"])
    out = {k: [] for k in _EMPTY_ENTRIES}
    if "buckets" in snap:  # host-dict schema
        for (key, cap, rate), (tokens, ts) in snap["buckets"].items():
            if keep(key):
                out["buckets"].append(
                    [key, float(cap), float(rate), float(tokens),
                     now - int(ts)])
        for (key, limit, wt, interp), (prev, curr, idx) in \
                snap.get("windows", {}).items():
            if keep(key):
                out["windows"].append(
                    [key, float(limit), int(wt), int(bool(interp)),
                     float(prev), float(curr), now // int(wt) - int(idx)])
        for key, (v, p, ts) in snap.get("counters", {}).items():
            if keep(key):
                out["counters"].append(
                    [key, float(v), float(p), now - int(ts)])
        for key, active in snap.get("semas", {}).items():
            if keep(key) and active:
                out["semas"].append([key, int(active)])
        return out
    # device slot-array schema
    for (cap, rate), t in snap.get("tables", {}).items():
        tokens, last_ts = np.asarray(t["tokens"]), np.asarray(t["last_ts"])
        exists = np.asarray(t["exists"])
        for key, slot in t["directory"].items():
            if exists[slot] and keep(key):
                out["buckets"].append(
                    [key, float(cap), float(rate), float(tokens[slot]),
                     now - int(last_ts[slot])])
    for (limit, wt, fixed), t in snap.get("wtables", {}).items():
        prev, curr = np.asarray(t["prev_count"]), np.asarray(t["curr_count"])
        idx, exists = np.asarray(t["window_idx"]), np.asarray(t["exists"])
        for key, slot in t["directory"].items():
            if exists[slot] and keep(key):
                out["windows"].append(
                    [key, float(limit), int(wt), int(not fixed),
                     float(prev[slot]), float(curr[slot]),
                     now // int(wt) - int(idx[slot])])
    c = snap.get("counters")
    if isinstance(c, dict) and "value" in c:
        value, period = np.asarray(c["value"]), np.asarray(c["period"])
        last_ts, exists = np.asarray(c["last_ts"]), np.asarray(c["exists"])
        for key, slot in snap.get("counter_dir", {}).items():
            if exists[slot] and keep(key):
                out["counters"].append(
                    [key, float(value[slot]), float(period[slot]),
                     now - int(last_ts[slot])])
    s = snap.get("semas")
    if isinstance(s, dict) and "active" in s:
        active, exists = np.asarray(s["active"]), np.asarray(s["exists"])
        for key, slot in snap.get("sema_dir", {}).items():
            if exists[slot] and keep(key) and int(active[slot]):
                out["semas"].append([key, int(active[slot])])
    return out


def split_entries(entries: Mapping, owner_of: Callable[[str], int]
                  ) -> dict[int, dict]:
    """Partition one export by destination node (a drain fans one pull
    out to several new owners)."""
    out: dict[int, dict] = {}
    for section in _EMPTY_ENTRIES:
        for row in entries.get(section, ()):
            dst = owner_of(row[0])
            out.setdefault(dst, {k: [] for k in _EMPTY_ENTRIES})[
                section].append(row)
    return out


#: Per-chunk serialized-size budget: well under wire.MAX_FRAME (1 MiB)
#: after JSON framing + the push envelope. Rows are bounded by BOTH this
#: and ``max_rows`` — long keys (up to 64 KiB on the keyed lane) must
#: not produce a chunk no frame can carry.
_CHUNK_BYTE_BUDGET = 700_000
#: JSON overhead per row beyond the key text (brackets, numbers, commas).
_ROW_OVERHEAD = 96


def chunk_entries(entries: Mapping, max_rows: int = 4096) -> list[dict]:
    """Split an export into batches bounded by row count AND serialized
    size, so every MIGRATE_PUSH frame fits MAX_FRAME regardless of key
    length. Each chunk carries its own batch id slot-in (the receiver's
    exactly-once dedup unit)."""
    chunks: list[dict] = []
    cur = {k: [] for k in _EMPTY_ENTRIES}
    n = 0
    size = 0
    for section in _EMPTY_ENTRIES:
        for row in entries.get(section, ()):
            # Size EVERY string field as it will actually serialize:
            # ensure_ascii JSON expands every non-ASCII / surrogate-
            # escaped char to a 6-byte \uXXXX escape, so a 60 KiB
            # hostile key can be ~6x its character count on the wire —
            # and reservation rows carry rid + child key at positions
            # 1-2 beyond the tenant at row[0], so sizing row[0] alone
            # would let a chunk of long-keyed reservations blow past
            # MAX_FRAME.
            row_size = sum(len(json.dumps(v)) for v in row
                           if isinstance(v, str)) + _ROW_OVERHEAD
            if n and (n >= max_rows
                      or size + row_size > _CHUNK_BYTE_BUDGET):
                chunks.append(cur)
                cur = {k: [] for k in _EMPTY_ENTRIES}
                n = 0
                size = 0
            cur[section].append(row)
            n += 1
            size += row_size
    if n or not chunks:
        chunks.append(cur)
    return chunks


def merge_entries(a: Mapping, b: Mapping) -> dict:
    """Concatenate two entry batches section-wise (the client half of a
    paged pull: pages reassemble into the one export they were chunked
    from)."""
    out = {k: list(a.get(k, ())) for k in _EMPTY_ENTRIES}
    for k in _EMPTY_ENTRIES:
        out[k].extend(b.get(k, ()))
    return out


async def saturating_drain(op: Callable, n: int) -> None:
    """Full-then-partial drain through a store's public acquire surface:
    ask ``op`` for the whole amount; a denial retries once for the
    bucket's reported remaining balance. The bucket lands at (or near)
    empty, never negative — the fallback debit idiom shared by
    :func:`debit_source`, :func:`import_entries`, and the cluster's
    rejoin reconciliation."""
    if n <= 0:
        return
    res = await op(n)
    if not res.granted and res.remaining >= 1:
        await op(int(res.remaining))


async def _debit_buckets(store, by_config: Mapping) -> None:
    """Charge ``{(cap, rate): ([keys], [amounts])}`` bucket debits
    through the store's fastest lane: the saturating ``debit_many``
    kernel when the store has one, else a best-effort
    :func:`saturating_drain` through the public acquire surface — the
    one debit path shared by :func:`debit_source` (the old owner's
    pull-time charge) and :func:`import_entries` (the new owner's
    replay)."""
    for (cap, rate), (ks, amounts) in by_config.items():
        debit = getattr(store, "debit_many", None)
        if callable(debit):
            await debit(ks, amounts, cap, rate)
        else:  # best effort through the public surface
            for k, amt in zip(ks, amounts):
                await saturating_drain(
                    lambda m, k=k: store.acquire(k, m, cap, rate),
                    int(amt))


def debit_export(entries: dict, fraction: float) -> dict:
    """The dual-ownership budget split (module docstring): reduce every
    exported bucket's tokens by the fair-share envelope the old owner
    keeps serving from, and pre-charge every window's current count by
    its envelope — old + new together stay within the original balance
    plus one envelope."""
    out = dict(entries)
    out["buckets"] = [
        [k, cap, rate,
         max(0.0, tok - headroom_budget(cap, fraction=fraction,
                                        min_budget=1.0)), age]
        for k, cap, rate, tok, age in entries.get("buckets", ())]
    out["windows"] = [
        [k, limit, wt, interp, prev,
         min(float(limit),
             curr + headroom_budget(limit, fraction=fraction,
                                    min_budget=1.0)), behind]
        for k, limit, wt, interp, prev, curr, behind
        in entries.get("windows", ())]
    return out


async def debit_source(store, entries: Mapping, fraction: float,
                       keep_envelope: bool = True) -> None:
    """The other half of the dual-ownership partition: charge the OLD
    owner's own state for what the export shipped, at pull time.

    Without this, a handoff window that expires AFTER the destinations
    already adopted the target epoch (a slow commit under chaos delays)
    would auto-abort the source back to its full, undebited balance
    while the new owner serves the shipped remainder — the unbounded
    two-owner spend "When Two is Worse Than One" forbids. With it the
    bound holds in every termination order; a coordinator-driven abort
    merely under-admits by the shipped amount until refill (the
    conservative direction — see docs/DESIGN.md §12).

    ``keep_envelope=True`` (the wire lane) leaves each bucket the
    fair-share envelope :func:`debit_export` withheld from the shipped
    copy — the source's store residual matches the in-memory envelope
    it serves parked keys from. ``keep_envelope=False`` (the in-process
    lane, which ships balances exactly and has no parked envelope)
    drains the bucket entirely. Windows are charged to their limit in
    both lanes: the source has no authoritative window authority to
    keep — parked window keys serve from the envelope, and after an
    abort the charge expires with the window itself.

    Saturating by construction (``debit_many`` floors at zero; a raced
    admission between the snapshot and the debit is already reflected
    in the balance being debited), so any interleaving stays inside the
    bound."""
    by_config: dict[tuple, tuple[list, list]] = {}
    for key, cap, rate, tokens, _age in entries.get("buckets", ()):
        shipped = float(tokens)
        if keep_envelope:
            shipped -= headroom_budget(float(cap), fraction=fraction,
                                       min_budget=1.0)
        if shipped <= 0.0:
            continue
        ks, amounts = by_config.setdefault((float(cap), float(rate)),
                                           ([], []))
        ks.append(key)
        amounts.append(shipped)
    await _debit_buckets(store, by_config)
    for key, limit, wt, interp, _prev, curr, _behind in \
            entries.get("windows", ()):
        charge = int(math.floor(float(limit) - float(curr)))
        if charge <= 0:
            continue
        from distributedratelimiting.redis_tpu.ops import bucket_math
        window_sec = wt / bucket_math.TICKS_PER_SECOND
        if interp:
            await store.window_acquire(key, charge, limit, window_sec)
        else:
            await store.fixed_window_acquire(key, charge, limit,
                                             window_sec)


def envelope_step(entry: "tuple[float, float] | None", now: float,
                  count: int, cap: float, rate: float,
                  fraction: float, priority: int = 0
                  ) -> "tuple[bool, float]":
    """One fair-share-envelope admission step — THE shared formula the
    epsilon over-admission bound depends on: a ``headroom_budget(cap,
    fraction)`` bucket refilled at ``fraction × rate``, clamped to the
    budget. ``entry`` is the stored ``(tokens, last_ts)`` or None for a
    fresh key (born at full budget). Returns ``(granted, new_tokens)``;
    callers persist ``(new_tokens, now)`` and own their eviction and
    ledger policy. Shared by the handoff :class:`_FairShareEnvelope`
    (old-owner side) and the cluster's ``_DegradedKeyspace`` (client
    edge) so the two halves of the bound can never drift apart.

    ``priority`` routes the grant rule through the ONE shed gate
    (:func:`~.runtime.admission.shed_allows`): scavenger is shed
    outright from any envelope, batch cannot spend the reserved half,
    interactive (the default — every plain wire frame) keeps the
    classic ``tokens >= count`` rule bit-for-bit."""
    from distributedratelimiting.redis_tpu.runtime.admission import (
        shed_allows,
    )

    budget = headroom_budget(cap, fraction=fraction, min_budget=1.0)
    if entry is None:
        tokens = budget
    else:
        tokens, ts = entry
        tokens = min(budget, tokens + (now - ts) * rate * fraction)
    granted = shed_allows(priority, tokens, count, budget)
    if granted and count > 0:
        tokens -= count
    return bool(granted), float(tokens)


class _FairShareEnvelope:
    """Bounded local admission for parked keys during a handoff window —
    the same confidence policy as the cluster's degraded fallback
    (``headroom_budget(a, fraction)`` tokens refilled at ``fraction ×
    rate``), hosted server-side by the OLD owner. Its budget is exactly
    what :func:`debit_export` already subtracted from the shipped state,
    so envelope grants spend a balance the new owner never received."""

    _MAX_KEYS = 1 << 14

    def __init__(self, fraction: float,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._fraction = fraction
        self._clock = clock
        self._buckets: dict[tuple, tuple[float, float]] = {}
        self.decisions = 0

    def acquire(self, key: str, count: int, a: float, b: float,
                kind: str, priority: int = 0) -> tuple[bool, float]:
        cap, rate = ((a, b) if kind == "bucket"
                     else (a, a / b if b > 0 else 0.0))
        now = self._clock()
        k = (key, kind, float(a), float(b))
        entry = self._buckets.get(k)
        if entry is None and len(self._buckets) >= self._MAX_KEYS:
            self._buckets.pop(next(iter(self._buckets)))
        granted, tokens = envelope_step(entry, now, count, cap, rate,
                                        self._fraction, priority)
        self._buckets[k] = (tokens, now)
        self.decisions += 1
        return granted, max(tokens, 0.0)


class _Handoff:
    """One in-flight outbound migration on the old owner: the parked
    slot/key set, the cached (already-debited) export, and the envelope
    that serves the parked keys until commit, abort, or expiry."""

    __slots__ = ("target_epoch", "slots", "keys", "export", "chunks",
                 "window_s", "started_s", "envelope", "ledger",
                 "res_stash")

    def __init__(self, target_epoch: int, slots: frozenset,
                 keys: "frozenset | None", export: dict, window_s: float,
                 started_s: float, fraction: float,
                 clock: Callable[[], float]) -> None:
        self.target_epoch = target_epoch
        self.slots = slots
        self.keys = keys
        self.export = export
        # Paged once here (the export is immutable from now on): every
        # page request serves a slice, never a re-chunk of the whole.
        self.chunks = chunk_entries(export)
        self.window_s = window_s
        self.started_s = started_s
        self.envelope = _FairShareEnvelope(fraction, clock)
        # Reservation-ledger stash: the rows pull removed from the
        # source ledger, kept so an abort can restore them (the new
        # owner's copy only exists once a push delivered the chunk).
        self.ledger = None
        self.res_stash: "tuple[list, list] | None" = None

    def expired(self, now: float) -> bool:
        return now - self.started_s > self.window_s


class NodePlacementState:
    """A serving node's placement half: the adopted map + this node's
    id, parked outbound handoffs, and the exactly-once import ledger.
    Engaged only once a map has been announced — a node that never hears
    an announce serves exactly as before (placement-unaware)."""

    #: Import ledger depth: applied-batch sets kept for this many most
    #: recent epochs (re-deliveries are same-epoch by construction).
    _LEDGER_EPOCHS = 8

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 envelope_fraction: float = DEFAULT_ENVELOPE_FRACTION
                 ) -> None:
        import asyncio

        self._clock = clock
        self._fraction = envelope_fraction
        self.pmap: PlacementMap | None = None
        self.node_id: int | None = None
        self._handoffs: dict[int, _Handoff] = {}      # target epoch →
        self._parked_slots: dict[int, _Handoff] = {}  # slot →
        self._parked_keys: dict[str, _Handoff] = {}   # override key →
        self._applied: dict[int, set[int]] = {}       # epoch → batch ids
        # Target epochs whose handoff this node aborted LOCALLY (window
        # expiry — coordinator presumed dead). A post-send wire retry of
        # the original pull landing after the abort must NOT re-export:
        # the first pull already debited the source, and a second
        # export+debit double-charges it past the one-envelope bound.
        # A coordinator abort announce clears the tombstone — the
        # deliberate retry-same-epoch path stays open (and is the one
        # place a second envelope is knowingly charged).
        self._aborted_epochs: set[int] = set()
        # Reservation rows imported by this node's pushes, by target
        # epoch: (ledger, rids). The dual of the _applied reset in
        # _abort — an aborted epoch's imported rows must LEAVE this
        # ledger, or the rid lives in two gated-owner ledgers once the
        # source restores its stash and a later retry commits: a
        # settle retry then refunds on both sides (drl-verify's
        # settle-dedup counterexample). Pruned with _applied.
        self._imported_res: "dict[int, tuple] | dict" = {}
        # Serializes pull/push bodies: their idempotency checks span an
        # await (export off-thread, import through the store), and a
        # post-send retry racing the original in-flight op must wait and
        # hit the cache/ledger, not run a second export + source debit.
        self._control_lock = asyncio.Lock()
        # Visible counters (OP_STATS "placement" section + OpenMetrics).
        self.moved_errors = 0
        self.envelope_decisions = 0
        self.handoff_deferrals = 0
        self.announces = 0
        self.stale_announces = 0
        self.pulls = 0
        self.pushes_applied = 0
        self.pushes_duplicate = 0
        self.rows_imported = 0
        self.aborts = 0
        self.expired_aborts = 0
        self.res_stash_forfeited = 0

    @property
    def active(self) -> bool:
        return self.pmap is not None and self.node_id is not None

    @property
    def epoch(self) -> int:
        return -1 if self.pmap is None else self.pmap.epoch

    # -- control plane -------------------------------------------------------
    def snapshot_payload(self) -> dict:
        """The OP_PLACEMENT reply: the adopted map (or ``epoch: -1`` for
        a placement-unaware node) plus this node's id and live handoff
        state."""
        out: dict = {"epoch": self.epoch, "node_id": self.node_id,
                     "parked_slots": sorted(self._parked_slots),
                     "parked_keys": sorted(self._parked_keys)}
        if self.pmap is not None:
            out["map"] = self.pmap.to_dict()
        return out

    def announce(self, payload: Mapping) -> int:
        """Adopt an announced map (monotonic by epoch; idempotent at the
        current epoch; a STALE epoch raises). ``abort_epoch`` payloads
        instead cancel that target epoch's parked handoff — the
        coordinator's clean-abort path. Returns the adopted epoch."""
        self.announces += 1
        abort = payload.get("abort_epoch")
        if abort is not None:
            self._abort(int(abort))
            # The COORDINATOR aborted: it knows the migration failed
            # and may retry the same target epoch — re-arm pull for it
            # (unlike a local expiry abort, where a late wire retry of
            # the original pull must keep hitting the tombstone).
            self._aborted_epochs.discard(int(abort))
            return self.epoch
        pmap = PlacementMap.from_dict(payload["map"])
        node_id = payload.get("node_id")
        if self.pmap is not None:
            if pmap.epoch < self.pmap.epoch:
                self.stale_announces += 1
                raise StalePlacementError(
                    f"stale placement epoch {pmap.epoch} "
                    f"(this node adopted {self.pmap.epoch})")
            if pmap.epoch == self.pmap.epoch and pmap != self.pmap:
                # Two coordinators raced to the same target epoch with
                # different maps: adopting the second would split-brain
                # slot ownership across the fleet with no error
                # anywhere. Re-announcing the SAME map is idempotent;
                # a conflicting twin loses loudly and must rebase onto
                # the adopted epoch.
                self.stale_announces += 1
                raise StalePlacementError(
                    f"conflicting placement map at epoch {pmap.epoch}: "
                    "another coordinator already committed this epoch "
                    "with a different assignment — rebase and retry")
        self.pmap = pmap
        if node_id is not None:
            self.node_id = int(node_id)
        # Commit: any handoff whose target epoch is now current (or
        # behind it) has transferred ownership — drop the parked state;
        # the map itself answers "moved" from here on.
        for e in [e for e in self._handoffs
                  if e <= pmap.epoch]:
            self._unpark(self._handoffs.pop(e))
        # Committed epochs' imported rows are legitimately owned now —
        # drop the abort provenance so a later abort of a NEWER epoch
        # cannot evict them.
        for e in [e for e in self._imported_res if e <= pmap.epoch]:
            del self._imported_res[e]
        # Tombstones at or below the adopted epoch are unreachable
        # (pull refuses non-future epochs outright) — drop them.
        self._aborted_epochs = {e for e in self._aborted_epochs
                                if e > pmap.epoch}
        self._prune_ledger()
        return pmap.epoch

    def _abort(self, target_epoch: int, *,
               restore_reservations: bool = True) -> None:
        # A retried migration REUSES the aborted target epoch (the
        # adopted epoch never moved), so the push ledger for it must
        # reset with the abort: deduping attempt 2's batches against
        # attempt 1's would silently drop re-pushed state (init-on-miss
        # at full capacity — over-admission); re-applying is merely
        # conservative (the import's debit replay floors at zero).
        self._applied.pop(target_epoch, None)
        # The destination half: reservation rows imported under the
        # aborted epoch leave this ledger again. The source's stash
        # restore (coordinator abort) or the retry's re-export is the
        # single surviving home for each rid — without this, a settle
        # retried across the abort+retry window refunds at BOTH the
        # restored source and the stale destination copy.
        imported = self._imported_res.pop(target_epoch, None)
        if imported is not None:
            led, rids = imported
            dropper = getattr(led, "drop_rids", None)
            if callable(dropper):
                dropper(rids)
        h = self._handoffs.pop(target_epoch, None)
        if h is not None:
            self._unpark(h)
            if h.ledger is not None and h.res_stash is not None:
                if restore_reservations:
                    # A COORDINATOR abort: it only runs pre-commit, so
                    # no destination ever adopted the target epoch and
                    # the exported reservations safely come home
                    # (restore_rows skips any rid the ledger re-learned
                    # meanwhile, so a racing late push cannot
                    # double-count).
                    h.ledger.restore_rows(*h.res_stash)
                else:
                    # An EXPIRY abort: the coordinator is presumed dead
                    # and the commit MAY already have reached the
                    # destinations (dst-first commit order). Restoring
                    # the RESERVATION rows here would put the SAME rid
                    # live in two gated-owner ledgers — a retried
                    # settle then refunds on BOTH sides (drl-verify's
                    # settle-dedup counterexample). Forfeit those:
                    # settles answer the counted "unknown" no-op (the
                    # hold is never refunded — the conservative
                    # direction), and the destination copy either
                    # serves settles after its commit or TTL-expires
                    # at the estimate. DEBT rows are the opposite
                    # polarity and DO come home: dropping them would
                    # FORGIVE the tenant's overdraft (over-admission),
                    # while dual-homing debt at worst double-collects
                    # (over-denial, bounded by the per-(tag, tenant)
                    # dedup when the retry re-exports it).
                    self.res_stash_forfeited += len(h.res_stash[0])
                    if h.res_stash[1]:
                        h.ledger.restore_rows([], h.res_stash[1])
                h.res_stash = None
            self.aborts += 1
            # The export for this epoch (and its source debit) is gone:
            # refuse late re-pulls until the coordinator acknowledges
            # the abort (announce with abort_epoch clears this).
            self._aborted_epochs.add(target_epoch)

    def _unpark(self, h: _Handoff) -> None:
        for s in h.slots:
            if self._parked_slots.get(s) is h:
                del self._parked_slots[s]
        for k in h.keys or ():
            if self._parked_keys.get(k) is h:
                del self._parked_keys[k]

    async def pull(self, req: Mapping, store) -> dict:
        """MIGRATE_PULL on the old owner: export the requested slots'
        (or keys') state with the envelope debit applied, park them, and
        start the handoff window. Idempotent per target epoch — a
        re-delivered pull returns the cached export (the at-most-once
        client may safely retry it).

        Large exports page: the reply carries one :func:`chunk_entries`
        chunk (so it always fits MAX_FRAME) plus the total ``pages``
        count; the client fetches pages 1..N-1 with ``page`` in the
        request, served from the cached handoff export."""
        import asyncio

        if not self.active:
            raise PlacementError(
                "no placement announced: pull requires an adopted map")
        target_epoch = int(req["target_epoch"])
        if target_epoch <= self.pmap.epoch:
            raise StalePlacementError(
                f"stale migration target epoch {target_epoch} "
                f"(this node adopted {self.pmap.epoch})")
        page = int(req.get("page", 0))
        async with self._control_lock:
            cached = self._handoffs.get(target_epoch)
            if cached is not None:
                return self._pull_page(cached, page, cached=True)
            if target_epoch in self._aborted_epochs:
                # This node already exported (and debited) for this
                # epoch and then aborted it on window expiry; the
                # cached export is gone. A silent re-export here would
                # double-debit the source — this is a late wire retry
                # of the original pull, not a coordinated new attempt.
                # The coordinator's clean-abort announce re-arms it.
                raise PlacementError(
                    f"migration to epoch {target_epoch} was aborted on "
                    "this node (handoff window expired); announce the "
                    "abort and retry the migration")
            slots = frozenset(int(s) for s in req.get("slots", ()))
            keys = (frozenset(req["keys"]) if req.get("keys") else None)
            window_s = float(req.get("window_s", 2.0))
            keep = keep_predicate(self.pmap.n_slots, self.pmap.overrides,
                                  slots, keys)
            # snapshot() pulls device state to host — blocking; off-loop
            # so one pull never stalls the serving path's event loop.
            entries = await asyncio.to_thread(_export_from_store, store,
                                              keep)
            # Outstanding reservations (and debts) whose TENANT moves
            # ride the same export: their settles will land on the new
            # owner (the tenant's MOVED target), so the ledger entries
            # must be there to reconcile against. Removed from the
            # source ledger here; an abort restores them (stash below).
            led = getattr(store, "_reservations", None)
            res_stash = None
            if led is not None:
                # Tag = the export episode: a same-epoch retry after an
                # abort re-ships the restored debts under the SAME tag,
                # and an owner already holding attempt 1's copy skips
                # them (ReservationLedger.restore_rows).
                res_rows, debt_rows = led.export_rows(
                    keep, tag=f"epoch:{target_epoch}")
                if res_rows or debt_rows:
                    entries = dict(entries)
                    entries["reservations"] = res_rows
                    entries["debts"] = debt_rows
                    res_stash = (res_rows, debt_rows)
            export = debit_export(entries, self._fraction)
            h = _Handoff(target_epoch, slots, keys, export, window_s,
                         self._clock(), self._fraction, self._clock)
            h.ledger = led
            h.res_stash = res_stash
            self._handoffs[target_epoch] = h
            for s in slots:
                self._parked_slots[s] = h
            for k in keys or ():
                self._parked_keys[k] = h
            # Charge this store for the shipped amount NOW (parked keys
            # serve from the envelope meanwhile): the authoritative
            # residual equals the envelope, so even a handoff that
            # expires after a slow commit announced the new epoch cannot
            # resume a full undebited balance alongside the new owner
            # (see debit_source).
            await debit_source(store, entries, self._fraction,
                               keep_envelope=True)
            self.pulls += 1
            return self._pull_page(h, page, cached=False)

    def _pull_page(self, h: _Handoff, page: int, cached: bool) -> dict:
        if not 0 <= page < len(h.chunks):
            raise PlacementError(
                f"pull page {page} out of range (export has "
                f"{len(h.chunks)} pages)")
        return {"target_epoch": h.target_epoch, "node_id": self.node_id,
                "entries": h.chunks[page], "pages": len(h.chunks),
                "cached": cached}

    async def push(self, req: Mapping, store) -> int:
        """MIGRATE_PUSH on the new owner: import one handoff batch
        exactly once — a re-delivered ``(target_epoch, batch)`` is a
        counted no-op, never a double-apply (the lock covers the
        in-flight duplicate too: the dedup check and the import span an
        await)."""
        target_epoch = int(req["target_epoch"])
        batch = int(req.get("batch", 0))
        async with self._control_lock:
            applied = self._applied.setdefault(target_epoch, set())
            if batch in applied:
                self.pushes_duplicate += 1
                return 0
            entries = req.get("entries") or {}
            n = await import_entries(store, entries)
            applied.add(batch)
            # Provenance for the abort path: remember which reservation
            # rids this epoch's pushes put into our ledger, so an abort
            # can take them back out (see _abort / _imported_res).
            rids = [row[1] for row in (entries.get("reservations")
                                       or ())]
            if rids:
                maker = getattr(store, "reservation_ledger", None)
                if callable(maker):
                    led, seen = self._imported_res.setdefault(
                        target_epoch, (maker(), set()))
                    seen.update(str(r) for r in rids)
            self.pushes_applied += 1
            self.rows_imported += n
            self._prune_ledger()
            return n

    def _prune_ledger(self) -> None:
        while len(self._applied) > self._LEDGER_EPOCHS:
            del self._applied[min(self._applied)]
        while len(self._imported_res) > self._LEDGER_EPOCHS:
            # Evicting abort provenance must not strand the rows it
            # tracks: a later abort of the evicted epoch would find no
            # record and leave them dual-homed (the double-refund this
            # machinery closes). Drop them NOW instead — the
            # conservative direction: if that epoch somehow still
            # commits, its settles answer the counted "unknown" (no
            # refund), never a second one.
            led, rids = self._imported_res.pop(
                min(self._imported_res))
            dropper = getattr(led, "drop_rids", None)
            if callable(dropper):
                dropper(rids)

    # -- serving gate --------------------------------------------------------
    def gate(self, key: str):
        """The serving-path ownership check. Returns ``None`` (serve
        authoritatively), ``("envelope", handoff)`` (parked mid-handoff:
        admission ops serve the fair-share envelope), or ``("moved",
        owner)`` (answer the routable moved error). Expired handoffs
        auto-abort here — coordinator loss must not strand a keyspace."""
        if not self.active:
            return None
        h = self._parked_keys.get(key)
        if h is None and key not in self.pmap.overrides:
            # Override keys route (and migrate) independently of their
            # slot — a parked slot does not park its split-out keys.
            h = self._parked_slots.get(self.pmap.slot_of(key))
        if h is not None:
            if h.expired(self._clock()):
                # The commit never came: abort back to the old epoch.
                # Safe in BOTH races — if the target epoch was never
                # announced no client routes to the new owner, and if a
                # slow commit DID announce it, this store was already
                # debited down to the envelope at pull time
                # (debit_source), so resuming authoritative serving
                # stays inside the dual-ownership bound. Reservation
                # rows are NOT restored on this path (unlike a
                # coordinator abort): they were moved whole, not
                # debited, so restoring them under a slow commit would
                # double-home the rid — see _abort.
                self._abort(h.target_epoch, restore_reservations=False)
                self.expired_aborts += 1
            else:
                return ("envelope", h)
        owner = self.pmap.node_of(key)
        if owner != self.node_id:
            self.moved_errors += 1
            return ("moved", owner)
        return None

    def bulk_gate(self, keys: Sequence[str]):
        """Ownership masks for one bulk frame. Returns ``None`` when
        every row serves authoritatively (the overwhelming steady-state
        — one vectorized crc32 pass plus a table compare), else
        ``(serve_mask, envelope_rows, moved_mask)`` where
        ``envelope_rows`` is ``[(row_index, handoff), …]``. Expired
        handoffs auto-abort first, exactly like the scalar gate."""
        if not self.active:
            return None
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            route_keys,
        )

        now = self._clock()
        for e in [e for e, h in self._handoffs.items()
                  if h.expired(now)]:
            self._abort(e, restore_reservations=False)
            self.expired_aborts += 1
        pmap = self.pmap
        slots = route_keys(keys, pmap.n_slots)
        owners = pmap.slot_owner[slots]
        parked = (np.isin(slots, np.fromiter(self._parked_slots,
                                             np.int64,
                                             len(self._parked_slots)))
                  if self._parked_slots else
                  np.zeros(len(slots), bool))
        if pmap.overrides or self._parked_keys:
            # Slot prefilter (route()'s discipline): only rows hashing
            # into an override or parked key's slot pay the per-key
            # probe — a long-lived hot-split table must not put every
            # bulk frame's every row back on a Python string loop.
            ov, pk = pmap.overrides, self._parked_keys
            special = pmap.override_slots()
            if pk:
                special = np.union1d(special, np.fromiter(
                    (_slot_of(k, pmap.n_slots) for k in pk),
                    np.int64, len(pk)))
            cand = np.isin(slots, special)
            for i in np.nonzero(cand)[0]:
                k = keys[int(i)]
                j = ov.get(k)
                if j is not None:
                    owners[i] = j
                    parked[i] = False  # overrides route independently
                if k in pk:
                    parked[i] = True
        serve_mask = (owners == self.node_id) & ~parked
        if serve_mask.all():
            return None
        moved_mask = (owners != self.node_id) & ~parked
        envelope_rows = []
        if parked.any():
            for i in np.nonzero(parked)[0]:
                k = keys[int(i)]
                h = self._parked_keys.get(k)
                if h is None:
                    h = self._parked_slots.get(int(slots[i]))
                if h is not None:
                    envelope_rows.append((int(i), h))
                else:  # raced an abort: the map still owns it here
                    serve_mask[i] = owners[i] == self.node_id
                    moved_mask[i] = not serve_mask[i]
        self.moved_errors += int(moved_mask.sum())
        return serve_mask, envelope_rows, moved_mask

    def moved_message(self, key: str, owner: int) -> str:
        return (f"{MOVED_ERROR_PREFIX}: key routes to node {owner} at "
                f"epoch {self.pmap.epoch}")

    def envelope_acquire(self, h: _Handoff, key: str, count: int,
                         a: float, b: float, kind: str,
                         priority: int = 0) -> tuple[bool, float]:
        self.envelope_decisions += 1
        return h.envelope.acquire(key, count, a, b, kind, priority)

    def stats(self) -> dict:
        out = {
            "epoch": self.epoch,
            "node_id": self.node_id,
            "parked_slots": len(self._parked_slots),
            "parked_keys": len(self._parked_keys),
            "moved_errors": self.moved_errors,
            "envelope_decisions": self.envelope_decisions,
            "handoff_deferrals": self.handoff_deferrals,
            "pulls": self.pulls,
            "pushes_applied": self.pushes_applied,
            "pushes_duplicate": self.pushes_duplicate,
            "rows_imported": self.rows_imported,
            "aborts": self.aborts,
            "expired_aborts": self.expired_aborts,
            "res_stash_forfeited": self.res_stash_forfeited,
        }
        if self.pmap is not None and self.node_id is not None:
            out["owned_slots"] = int(
                (self.pmap.slot_owner == self.node_id).sum())
        return out


# -- store import/export lanes ----------------------------------------------

def _export_from_store(store, keep: Callable[[str], bool]) -> dict:
    """Prefer a store's own ``export_entries`` override; fall back to
    filtering its snapshot through the schema-aware extractor."""
    exporter = getattr(store, "export_entries", None)
    if callable(exporter):
        return exporter(keep)
    return extract_entries(store.snapshot(), keep)


async def import_entries(store, entries: Mapping) -> int:
    """Apply normalized entries to any store. A store-provided
    ``import_entries`` override (the exact host-dict merge) wins;
    otherwise the **generic replay lane** adopts the state through the
    store's public ops:

    - buckets via the saturating **debit kernel** (``debit_many`` — the
      round-5/tier-0 state primitive): a fresh key initializes at full
      capacity and ``capacity − tokens`` is debited away, landing the
      migrated balance exactly, batched per ``(capacity, rate)`` config;
    - windows by replaying the current window's count (prior windows'
      interpolated share is dropped — conservative only toward admission,
      inside the handoff epsilon);
    - counters via ``sync_counter`` (a fresh counter adopts the pushed
      value); semaphores via ``concurrency_acquire``.

    Returns the number of rows applied."""
    n = 0
    # Reservation-ledger sections route to the store's attached ledger
    # BEFORE the store-specific importer branch — both import lanes
    # (exact host-dict merge and generic replay) must adopt them, and
    # the ledger is shared with the serving path by construction
    # (BucketStore.reservation_ledger), so the next settle sees them.
    res_rows = entries.get("reservations") or ()
    debt_rows = entries.get("debts") or ()
    if res_rows or debt_rows:
        maker = getattr(store, "reservation_ledger", None)
        if callable(maker):
            n += maker().restore_rows(res_rows, debt_rows)
    importer = getattr(store, "import_entries", None)
    if callable(importer):
        return n + await importer(entries)
    by_config: dict[tuple, tuple[list, list]] = {}
    for key, cap, rate, tokens, _age in entries.get("buckets", ()):
        ks, amounts = by_config.setdefault((float(cap), float(rate)),
                                           ([], []))
        ks.append(key)
        amounts.append(max(0.0, float(cap) - float(tokens)))
        n += 1
    await _debit_buckets(store, by_config)
    for key, limit, wt, interp, _prev, curr, behind in \
            entries.get("windows", ()):
        if behind == 0 and curr > 0:
            from distributedratelimiting.redis_tpu.ops import bucket_math
            window_sec = wt / bucket_math.TICKS_PER_SECOND
            count = int(math.ceil(curr))
            if interp:
                await store.window_acquire(key, count, limit, window_sec)
            else:
                await store.fixed_window_acquire(key, count, limit,
                                                 window_sec)
        n += 1
    for key, value, _period, _age in entries.get("counters", ()):
        await store.sync_counter(key, float(value), 0.0)
        n += 1
    for key, active in entries.get("semas", ()):
        await store.concurrency_acquire(key, int(active), int(active))
        n += 1
    return n
