"""The bucket store — where rate-limit state lives and decisions execute.

``BucketStore`` is the framework's storage seam, playing the role the
``IDatabase`` + ``ConnectionMultiplexerFactory`` pair played in the
reference (``…Options.cs:75`` — the injection point SURVEY.md §4 calls out
as "the seam designed for exactly this — preserve an equivalent seam"):

- :class:`DeviceBucketStore` — the TPU store. Per-key bucket state lives in
  HBM as SoA arrays; ``acquire`` calls are micro-batched into one kernel
  launch (≙ one Lua ``EVALSHA``, but for thousands of keys at once); the
  store's clock stamps every launch (store-as-time-authority, invariant 1).
- :class:`InProcessBucketStore` — a pure-Python store with identical
  semantics: the test fake (≙ a fake ``ConnectionMultiplexer``) and the
  single-node CPU baseline for BASELINE config 1.

Organization of the device store: one *table* per bucket configuration
``(capacity, fill_rate)`` — matching the reference, where one limiter (or
one partitioned limiter's whole key space) shares a single config
(``RedisTokenBucketRateLimiterOptions``), so tables are homogeneous and the
kernels take config as two scalar operands. Tables grow by doubling and
reclaim slots with TTL sweeps (invariant 5). Decaying global counters (the
approximate algorithm's shared tier) live in one store-wide table with a
*per-row* decay-rate operand, since each approximate limiter may have its
own rate.
"""

from __future__ import annotations

import abc
import asyncio
import threading
from typing import Callable, Iterable, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributedratelimiting.redis_tpu.ops import bucket_math as bm
from distributedratelimiting.redis_tpu.ops import kernels as K
from distributedratelimiting.redis_tpu.utils import log
from distributedratelimiting.redis_tpu.runtime.batcher import MicroBatcher
from distributedratelimiting.redis_tpu.runtime.clock import Clock, MonotonicClock
from distributedratelimiting.redis_tpu.runtime.directory import make_directory
from distributedratelimiting.redis_tpu.utils.metrics import StoreMetrics
from distributedratelimiting.redis_tpu.utils.tracing import Profiler, ProfilingSession

__all__ = [
    "AcquireResult",
    "BulkAcquireResult",
    "SyncResult",
    "BucketStore",
    "DeviceBucketStore",
    "InProcessBucketStore",
    "check_hierarchical_args",
]


def check_hierarchical_args(count: int, tenant_capacity: float,
                            tenant_fill_rate_per_sec: float,
                            capacity: float,
                            fill_rate_per_sec: float) -> None:
    """Shared validation for every hierarchical lane (in-process,
    device, remote client, server dispatch — one rule, zero drift):
    costs must be non-negative (a negative 'cost' would MINT tokens
    through the refund algebra), and the tenant and key configs must
    differ — identical configs would alias parent and child into one
    table (the fused kernel donates each state buffer once), and a
    tenant budget equal to the per-key config is a flat limiter
    spelled twice, not a hierarchy."""
    if count < 0:
        raise ValueError("hierarchical acquire cost must be >= 0")
    if (float(tenant_capacity), float(tenant_fill_rate_per_sec)) == \
            (float(capacity), float(fill_rate_per_sec)):
        raise ValueError(
            "hierarchical acquire requires distinct tenant and key "
            "configs (identical (capacity, fill_rate) would alias the "
            "two levels into one table)")

# Host tick value at which the store rebases its epoch (≪ int32 max), and
# how much history the new epoch keeps. Margin 2^29 (~6 days): timestamps
# within the last ~6 days survive the shift exactly; older ones clamp to the
# new epoch, which can only under-refill (safe) and only matters for buckets
# whose time-to-full exceeds ~6 days.
_REBASE_THRESHOLD_TICKS = 2**30
_REBASE_MARGIN_TICKS = 2**29


def _shift_ts(ts, shift: int):
    """Re-align stored tick timestamps to a new clock epoch: widen to
    int64, shift, and saturate back into int32 range (shared by every
    snapshot-restore path — single-chip and sharded)."""
    shifted = np.asarray(ts).astype(np.int64) + shift
    return np.clip(shifted, -(2**31) + 1, 2**31 - 1).astype(np.int32)


class AcquireResult(NamedTuple):
    granted: bool
    remaining: float  # post-decision token estimate (≙ Lua reply new_v)


class BulkAcquireResult:
    """Vectorized decision results: numpy arrays, not per-request objects.

    The bulk serving path exists because building one Python object (and
    resolving one future) per decision caps a process near ~50K decisions/s
    regardless of device speed; callers that hold many keys' requests get
    the verdicts as two arrays and index only what they need."""

    __slots__ = ("granted", "remaining")

    def __init__(self, granted: np.ndarray,
                 remaining: np.ndarray | None) -> None:
        self.granted = granted        # bool[n]
        # f32[n]; None when the caller opted out (``with_remaining=False``,
        # the verdict-only fast path — fetches 1 bit/decision).
        self.remaining = remaining

    def __len__(self) -> int:
        return len(self.granted)

    def __getitem__(self, i: int) -> AcquireResult:
        r = 0.0 if self.remaining is None else float(self.remaining[i])
        return AcquireResult(bool(self.granted[i]), r)

    def __iter__(self):
        for i in range(len(self.granted)):
            yield self[i]

    @property
    def granted_count(self) -> int:
        return int(np.count_nonzero(self.granted))


class SyncResult(NamedTuple):
    global_score: float
    period_ewma_ticks: float


class _AcquireReq(NamedTuple):
    key: str
    count: int


class BucketStore(abc.ABC):
    """Abstract store: token buckets + decaying counters + sliding windows.

    All rate arguments are per-second; conversion to per-tick happens at the
    store boundary so callers never see ticks except in ``SyncResult``.
    """

    clock: Clock

    @abc.abstractmethod
    async def connect(self) -> None:
        """Idempotent lazy init (≙ ``ConnectAsync``,
        ``RedisTokenBucketRateLimiter.cs:111-151``)."""

    # -- exact token bucket ------------------------------------------------
    @abc.abstractmethod
    async def acquire(self, key: str, count: int, capacity: float,
                      fill_rate_per_sec: float) -> AcquireResult: ...

    @abc.abstractmethod
    def acquire_blocking(self, key: str, count: int, capacity: float,
                         fill_rate_per_sec: float) -> AcquireResult:
        """Synchronous single-request path (the reference's sync ``Acquire``
        silently always failed — a surprise SURVEY.md §2 tells us not to
        replicate; here it is a real, blocking decision)."""

    @abc.abstractmethod
    def peek_blocking(self, key: str, capacity: float,
                      fill_rate_per_sec: float) -> float:
        """Read-only availability estimate (``GetAvailablePermits``)."""

    def acquire_submitter(self, capacity: float, fill_rate_per_sec: float):
        """Per-request hot-path factory: returns an async ``(key, count) →
        AcquireResult`` bound to one bucket config, with per-call routing
        (config→table lookup, connect check) hoisted out of the loop.
        Limiters cache one per config — at ~20µs/decision budgets the
        hoisted work is a measurable share (benchmarks/RESULTS.md r04
        per-request ceiling note). Default: a thin binding over
        :meth:`acquire`; :class:`DeviceBucketStore` overrides with a
        direct micro-batcher binding."""
        async def submit(key: str, count: int) -> AcquireResult:
            return await self.acquire(key, count, capacity,
                                      fill_rate_per_sec)

        return submit

    # -- bulk token bucket (one call, many keys) ---------------------------
    async def acquire_many(self, keys: Sequence[str], counts: Sequence[int],
                           capacity: float, fill_rate_per_sec: float, *,
                           with_remaining: bool = True) -> "BulkAcquireResult":
        """Vectorized acquire: decide ``len(keys)`` requests in one call —
        one await resolves them all (no per-request future). Duplicate keys
        serialize in request order; on batched device stores the in-batch
        serialization is *conservative* (an earlier same-key request's
        demand reserves ahead of later ones even if it is denied — the same
        property as the micro-batched serving path; over-admission is
        impossible, and the decisions are exact whenever in-call duplicates
        are all granted or keys are distinct). ``with_remaining=False``
        lets a verdict-only caller skip the per-request remaining estimates
        (the device store then fetches 1 bit per decision). Default
        implementation: a pipelined gather over the per-key path;
        :class:`DeviceBucketStore` overrides with scanned whole-array
        kernel launches."""
        results = await asyncio.gather(
            *(self.acquire(k, int(c), capacity, fill_rate_per_sec)
              for k, c in zip(keys, counts)))
        return BulkAcquireResult(
            np.fromiter((r.granted for r in results), bool, len(results)),
            np.fromiter((r.remaining for r in results), np.float32,
                        len(results)) if with_remaining else None)

    def acquire_many_blocking(self, keys: Sequence[str],
                              counts: Sequence[int], capacity: float,
                              fill_rate_per_sec: float, *,
                              with_remaining: bool = True) -> "BulkAcquireResult":
        results = [self.acquire_blocking(k, int(c), capacity,
                                         fill_rate_per_sec)
                   for k, c in zip(keys, counts)]
        return BulkAcquireResult(
            np.fromiter((r.granted for r in results), bool, len(results)),
            np.fromiter((r.remaining for r in results), np.float32,
                        len(results)) if with_remaining else None)

    # -- bulk windows (one call, many keys) --------------------------------
    async def window_acquire_many(self, keys: Sequence[str],
                                  counts: Sequence[int], limit: float,
                                  window_sec: float, *, fixed: bool = False,
                                  with_remaining: bool = True
                                  ) -> "BulkAcquireResult":
        """Vectorized window acquire (sliding by default, ``fixed=True``
        for fixed windows) — the window analogue of :meth:`acquire_many`,
        with the same in-call duplicate conservatism and probe semantics.
        Default: pipelined gather over the per-key path; device stores
        override with scanned whole-array launches."""
        op = (self.fixed_window_acquire if fixed else self.window_acquire)
        results = await asyncio.gather(
            *(op(k, int(c), limit, window_sec)
              for k, c in zip(keys, counts)))
        return BulkAcquireResult(
            np.fromiter((r.granted for r in results), bool, len(results)),
            np.fromiter((r.remaining for r in results), np.float32,
                        len(results)) if with_remaining else None)

    def window_acquire_many_blocking(self, keys: Sequence[str],
                                     counts: Sequence[int], limit: float,
                                     window_sec: float, *,
                                     fixed: bool = False,
                                     with_remaining: bool = True
                                     ) -> "BulkAcquireResult":
        op = (self.fixed_window_acquire_blocking if fixed
              else self.window_acquire_blocking)
        results = [op(k, int(c), limit, window_sec)
                   for k, c in zip(keys, counts)]
        return BulkAcquireResult(
            np.fromiter((r.granted for r in results), bool, len(results)),
            np.fromiter((r.remaining for r in results), np.float32,
                        len(results)) if with_remaining else None)

    # -- hierarchical tenant → key admission (runtime/admission.py) --------
    async def acquire_hierarchical(self, tenant: str, key: str, count: int,
                                   tenant_capacity: float,
                                   tenant_fill_rate_per_sec: float,
                                   capacity: float,
                                   fill_rate_per_sec: float, *,
                                   priority: int = 0) -> AcquireResult:
        """Two-level weighted-cost admission: grant iff BOTH the child
        key's ``(capacity, fill_rate)`` bucket and the parent tenant's
        ``(tenant_capacity, tenant_fill_rate)`` bucket admit ``count``
        tokens, with both-or-neither state change (parent refund on
        child deny — DESIGN.md §15). ``remaining`` is the binding
        constraint's post-decision view: ``min(child, parent)``.
        ``priority`` (admission.PRIORITY_*) never changes a
        healthy-path decision; wire stores stamp it on the frame so
        envelope serving (drain windows, parked handoffs) can honor
        the shed order.

        Default: sequential parent-then-child compose with a
        saturating refund of the parent on child deny (via
        ``debit_many`` with a negative amount, where the store has
        one; stores without a reconciliation lane skip the refund —
        under-admission only, never over). Exact single-step
        implementations: :class:`InProcessBucketStore` (serial core)
        and :class:`DeviceBucketStore` (the fused
        ``acquire_hierarchical_packed`` kernel);
        ``RemoteBucketStore`` ships the whole decision as one
        ``OP_ACQUIRE_H`` frame."""
        check_hierarchical_args(count, tenant_capacity,
                                tenant_fill_rate_per_sec, capacity,
                                fill_rate_per_sec)
        parent = await self.acquire(tenant, count, tenant_capacity,
                                    tenant_fill_rate_per_sec)
        if not parent.granted:
            return AcquireResult(False, parent.remaining)
        child = await self.acquire(key, count, capacity,
                                   fill_rate_per_sec)
        if child.granted:
            return AcquireResult(True, min(child.remaining,
                                           parent.remaining))
        if count > 0 and type(self).debit_many is not BucketStore.debit_many:
            # Refund the parent debit through the saturating debit lane
            # (a negative amount credits back; the next refill's
            # capacity clamp bounds any transient overshoot, so the
            # refund can only under-credit — the safe direction).
            await self.debit_many([tenant], [-float(count)],
                                  tenant_capacity,
                                  tenant_fill_rate_per_sec)
        return AcquireResult(False, child.remaining)

    def acquire_hierarchical_blocking(self, tenant: str, key: str,
                                      count: int,
                                      tenant_capacity: float,
                                      tenant_fill_rate_per_sec: float,
                                      capacity: float,
                                      fill_rate_per_sec: float, *,
                                      priority: int = 0) -> AcquireResult:
        """Blocking compose (overridden with exact single-step
        implementations by the serial/device/remote stores). The base
        compose has no blocking refund lane: a child deny leaves the
        parent debited — under-admission only, documented."""
        check_hierarchical_args(count, tenant_capacity,
                                tenant_fill_rate_per_sec, capacity,
                                fill_rate_per_sec)
        parent = self.acquire_blocking(tenant, count, tenant_capacity,
                                       tenant_fill_rate_per_sec)
        if not parent.granted:
            return AcquireResult(False, parent.remaining)
        child = self.acquire_blocking(key, count, capacity,
                                      fill_rate_per_sec)
        if child.granted:
            return AcquireResult(True, min(child.remaining,
                                           parent.remaining))
        return AcquireResult(False, child.remaining)

    async def acquire_hierarchical_many(self, tenants: Sequence[str],
                                        keys: Sequence[str],
                                        counts: Sequence[int],
                                        tenant_capacity: float,
                                        tenant_fill_rate_per_sec: float,
                                        capacity: float,
                                        fill_rate_per_sec: float, *,
                                        with_remaining: bool = True,
                                        priority: int = 0
                                        ) -> "BulkAcquireResult":
        """Vectorized hierarchical admission — row ``i`` decides
        ``counts[i]`` tokens for ``(tenants[i], keys[i])``. Same-key /
        same-tenant rows serialize in request order; on batched device
        stores the serialization is conservative on BOTH axes (the
        fused kernel's documented posture). Default: sequential loop
        over :meth:`acquire_hierarchical`."""
        n = len(keys)
        granted = np.empty(n, bool)
        remaining = np.empty(n, np.float32) if with_remaining else None
        for i in range(n):
            r = await self.acquire_hierarchical(
                tenants[i], keys[i], int(counts[i]), tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority=priority)
            granted[i] = r.granted
            if remaining is not None:
                remaining[i] = r.remaining
        return BulkAcquireResult(granted, remaining)

    def acquire_hierarchical_many_blocking(self, tenants: Sequence[str],
                                           keys: Sequence[str],
                                           counts: Sequence[int],
                                           tenant_capacity: float,
                                           tenant_fill_rate_per_sec: float,
                                           capacity: float,
                                           fill_rate_per_sec: float, *,
                                           with_remaining: bool = True,
                                           priority: int = 0
                                           ) -> "BulkAcquireResult":
        n = len(keys)
        granted = np.empty(n, bool)
        remaining = np.empty(n, np.float32) if with_remaining else None
        for i in range(n):
            r = self.acquire_hierarchical_blocking(
                tenants[i], keys[i], int(counts[i]), tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority=priority)
            granted[i] = r.granted
            if remaining is not None:
                remaining[i] = r.remaining
        return BulkAcquireResult(granted, remaining)

    # -- estimate-reserve-settle (runtime/reservations.py) -----------------
    def reservation_ledger(self, **kwargs):
        """Get-or-create this store's :class:`~.reservations.
        ReservationLedger` — ONE ledger per store, shared by every
        consumer (the server's OP_RESERVE/OP_SETTLE dispatch, the
        migration import lane, in-process cluster nodes), so a
        reservation imported by a MIGRATE_PUSH is visible to the next
        settle. ``kwargs`` configure the ledger on FIRST creation only
        (the server wires flight recorder / velocity / liveconfig in
        before serving); later callers get the existing instance."""
        led = getattr(self, "_reservations", None)
        if led is None:
            from distributedratelimiting.redis_tpu.runtime.reservations import (
                ReservationLedger,
            )

            led = self._reservations = ReservationLedger(self, **kwargs)
        return led

    # -- global quota federation (runtime/federation.py) -------------------
    def federation_ledger(self, **kwargs):
        """Get-or-create this store's :class:`~.federation.
        FederationLedger` — ONE ledger per store (the
        ``reservation_ledger`` pattern), shared by the server's
        OP_FED_* dispatch and the checkpoint attachment lane
        (runtime/checkpoint.py snapshots/restores its lease state
        beside the bucket tables, so a home crash/restart resumes
        every lease). ``kwargs`` configure the ledger on FIRST
        creation only."""
        led = getattr(self, "_federation", None)
        if led is None:
            from distributedratelimiting.redis_tpu.runtime.federation import (
                FederationLedger,
            )

            led = self._federation = FederationLedger(self, **kwargs)
        return led

    async def reserve(self, rid: str, tenant: str, key: str,
                      estimate: "float | None",
                      tenant_capacity: float,
                      tenant_fill_rate_per_sec: float,
                      capacity: float, fill_rate_per_sec: float, *,
                      priority: int = 0,
                      ttl_s: "float | None" = None,
                      attempt: int = 0,
                      deadline_s: "float | None" = None):
        """Admit an ESTIMATED cost against the tenant → key budgets and
        hold a TTL'd reservation (:mod:`~.reservations` — the streaming
        lane for costs unknown until generation ends). Default: the
        store-attached ledger; ``RemoteBucketStore`` overrides with one
        ``OP_RESERVE`` frame so the ledger lives server-side.
        ``attempt``/``deadline_s`` feed the goodput plane — retry
        fingerprinting and settle-vs-deadline accounting
        (docs/DESIGN.md §24)."""
        return await self.reservation_ledger().reserve(
            rid, tenant, key, estimate, tenant_capacity,
            tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
            priority=priority, ttl_s=ttl_s, attempt=attempt,
            deadline_s=deadline_s)

    async def settle(self, rid: str, tenant: str, actual: float):
        """Reconcile a reservation's actual cost: refund over-estimates
        through the saturating negative-debit lane, carry
        under-estimates as per-tenant debt (idempotent by rid — see
        :meth:`~.reservations.ReservationLedger.settle`)."""
        return await self.reservation_ledger().settle(rid, tenant,
                                                      actual)

    # -- decaying global counter (approximate algorithm's shared tier) -----
    @abc.abstractmethod
    async def sync_counter(self, key: str, local_count: float,
                           decay_rate_per_sec: float) -> SyncResult: ...

    @abc.abstractmethod
    def sync_counter_blocking(self, key: str, local_count: float,
                              decay_rate_per_sec: float) -> SyncResult: ...

    async def sync_counters_many(self, keys: Sequence[str],
                                 local_counts: Sequence[float],
                                 decay_rate_per_sec: float
                                 ) -> tuple[np.ndarray, np.ndarray]:
        """Bulk decaying-counter sync — the entry point for EXTERNAL
        replica fleets (a host process running many approximate-limiter
        replicas, or an edge tier reconciling a whole key table) to drain
        their accumulated local counts in one call instead of one
        :meth:`sync_counter` round trip per key. Returns ``(global_scores
        f64[n], period_ewmas f64[n])`` row-for-row with ``keys``.
        Default: a sequential loop (same-key rows keep request order);
        :class:`DeviceBucketStore` overrides with ONE ``sync_batch``
        launch for the whole fleet."""
        scores = np.empty(len(keys), np.float64)
        periods = np.empty(len(keys), np.float64)
        for i, (k, c) in enumerate(zip(keys, local_counts)):
            res = await self.sync_counter(k, float(c), decay_rate_per_sec)
            scores[i] = res.global_score
            periods[i] = res.period_ewma_ticks
        return scores, periods

    async def debit_many(self, keys: Sequence[str],
                         amounts: Sequence[float], capacity: float,
                         fill_rate_per_sec: float
                         ) -> tuple[np.ndarray, np.ndarray]:
        """Saturating bulk debit — the reconciliation half of the native
        front-end's tier-0 admission cache: drain permits the edge
        already granted locally out of the authoritative bucket table
        (refill, then subtract clamped at zero). Returns ``(remaining
        f64[n], shortfall f64[n])``: the post-debit balance per key and
        the part of each drained amount that found no tokens (the
        observed over-admission). Callers pre-aggregate per key. Not
        every store hosts tier-0 replicas; the front-end feature-detects
        this method and disables tier-0 when it is absent."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support tier-0 replica "
            "reconciliation (debit_many)")

    # -- sliding window ----------------------------------------------------
    @abc.abstractmethod
    async def window_acquire(self, key: str, count: int, limit: float,
                             window_sec: float) -> AcquireResult: ...

    @abc.abstractmethod
    def window_acquire_blocking(self, key: str, count: int, limit: float,
                                window_sec: float) -> AcquireResult: ...

    # -- fixed window (current-window count only, no interpolation) --------
    @abc.abstractmethod
    async def fixed_window_acquire(self, key: str, count: int, limit: float,
                                   window_sec: float) -> AcquireResult: ...

    @abc.abstractmethod
    def fixed_window_acquire_blocking(self, key: str, count: int,
                                      limit: float,
                                      window_sec: float) -> AcquireResult: ...

    # -- concurrency semaphore (held permits, returned on lease dispose) ---
    @abc.abstractmethod
    async def concurrency_acquire(self, key: str, count: int,
                                  limit: int) -> AcquireResult:
        """Atomically add ``count`` held permits iff ``active + count <=
        limit``. ``remaining`` in the result is the post-op active count."""

    @abc.abstractmethod
    def concurrency_acquire_blocking(self, key: str, count: int,
                                     limit: int) -> AcquireResult: ...

    @abc.abstractmethod
    async def concurrency_release(self, key: str, count: int) -> None:
        """Return ``count`` held permits (clamped at zero held)."""

    @abc.abstractmethod
    def concurrency_release_blocking(self, key: str, count: int) -> None: ...

    async def concurrency_acquire_many(self, keys: Sequence[str],
                                       deltas: Sequence[int],
                                       limit: "int | Sequence[int]"
                                       ) -> "BulkAcquireResult":
        """Vectorized semaphore ops: decide ``len(keys)`` signed deltas in
        one call — +n acquires (all-or-nothing against the row's limit),
        -n releases (always succeed, clamped at zero held), 0 probes.
        ``limit`` is a scalar or one per row (the native front-end sends
        a whole micro-batch as ONE call with per-row limits so same-key
        acquires and releases keep arrival order). Same-key rows
        serialize in request order; duplicate-acquire admission may be
        *conservative* on batched stores — an earlier same-key acquire's
        demand reserves ahead of later rows even if it is denied — and
        exact on serial stores, the same latitude :meth:`acquire_many`
        documents for buckets. Result rows: ``granted`` (releases always
        True), ``remaining`` = post-op active count from the row's own
        serialized view (0.0 for releases, matching the scalar wire
        reply). Default: in-order loop over the per-key path;
        :class:`DeviceBucketStore` overrides with packed kernel
        dispatches."""
        n = len(keys)
        limits = self._sema_limits(limit, n)
        granted = np.empty(n, bool)
        remaining = np.empty(n, np.float32)
        for i, (k, d) in enumerate(zip(keys, deltas)):
            d = int(d)
            if d >= 0:
                r = await self.concurrency_acquire(k, d, int(limits[i]))
                granted[i] = r.granted
                remaining[i] = r.remaining
            else:
                await self.concurrency_release(k, -d)
                granted[i] = True
                remaining[i] = 0.0
        return BulkAcquireResult(granted, remaining)

    @staticmethod
    def _sema_limits(limit, n: int) -> np.ndarray:
        """Broadcast a scalar-or-per-row ``limit`` to ``i64[n]``."""
        arr = np.asarray(limit, np.int64)
        if arr.ndim == 0:
            return np.full(n, int(arr), np.int64)
        if arr.shape != (n,):
            raise ValueError(
                f"limit must be a scalar or one per row: got shape "
                f"{arr.shape} for {n} rows")
        return arr

    # -- lifecycle / ops ---------------------------------------------------
    @abc.abstractmethod
    async def aclose(self) -> None: ...

    @abc.abstractmethod
    def snapshot(self) -> dict:
        """Host-side checkpoint of all live state (SURVEY.md §5.4: planned
        restarts snapshot ``(keys, tokens, ts)``; crash recovery simply
        accepts init-on-miss)."""

    @abc.abstractmethod
    def restore(self, snap: dict) -> None: ...

    def export_entries(self, keep) -> dict:
        """Normalized per-key state for the keys ``keep`` selects — the
        live-migration handoff unit (:mod:`~.placement`). Default:
        filter this store's :meth:`snapshot` through the schema-aware
        extractor (host-dict and device slot-array schemas both
        understood). The matching import runs through
        :func:`placement.import_entries`, whose generic lane replays
        buckets via the saturating ``debit_many`` kernel."""
        from distributedratelimiting.redis_tpu.runtime import placement

        return placement.extract_entries(self.snapshot(), keep)


def start_periodic_sweeper(sweep_all: Callable[[], None],
                           period_s: float) -> "asyncio.Task":
    """Shared active-expiry loop (DeviceBucketStore + MeshBucketStore):
    runs ``sweep_all`` off-loop every ``period_s``; a transient device
    error must not silently end active expiry for the store's lifetime —
    log and retry next period (degraded-mode posture, invariant 9)."""

    async def loop() -> None:
        while True:
            await asyncio.sleep(period_s)
            try:
                # Device passes block; keep the event loop responsive.
                await asyncio.to_thread(sweep_all)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                log.error_evaluating_kernel(exc)

    return asyncio.get_running_loop().create_task(loop())


def _rate_per_tick(rate_per_sec: float) -> float:
    return rate_per_sec / bm.TICKS_PER_SECOND


def _grant_zero_probes(granted: np.ndarray, counts_np: np.ndarray) -> None:
    """The zero-permit-probe contract in one place (shared by the
    host-directory mixin and the fingerprint store): probes always grant
    — the kernel's conservative in-batch prefix could deny one riding
    beside denied same-key demand."""
    if (counts_np == 0).any():
        granted[counts_np == 0] = True


def _pad_size(n: int, floor: int = 64) -> int:
    """Pad batches to a power of two ≥ ``floor`` so the jit cache stays
    small (one compilation per size bucket, not per batch length)."""
    size = floor
    while size < n:
        size *= 2
    return size


def _duplicate_prefix_host(slots: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Exact per-request prefix of earlier same-slot demand, computed on the
    host in int64 (vectorized stable-sort + segmented cumsum, ~30µs at
    B=4096). Shipping it with the batch lets the device kernel skip its
    in-kernel sort — the decision is then pure gather/refill/compare/scatter."""
    order = np.argsort(slots, kind="stable")
    s_sorted = slots[order]
    c_sorted = counts[order].astype(np.int64)
    csum = np.cumsum(c_sorted)
    seg_start = np.r_[True, s_sorted[1:] != s_sorted[:-1]]
    base = np.maximum.accumulate(np.where(seg_start, csum - c_sorted, 0))
    prefix = np.empty_like(csum)
    prefix[order] = csum - c_sorted - base
    return prefix


def _build_packed(reqs: Sequence[_AcquireReq], slots: Sequence[int], b: int,
                  now: int) -> np.ndarray:
    """ONE padded i32[4, b] operand per launch — row 0 slots (-1 = padding),
    row 1 counts, row 2 the batch timestamp, row 3 the host-computed
    same-slot demand prefix. Per-transfer latency dominates on
    tunneled/remote device links, so the flush hot path ships exactly one
    host→device array and reads back exactly one result array."""
    packed = np.full((4, b), -1, np.int32)
    packed[1] = 0
    packed[3] = 0
    n = len(reqs)
    packed[0, :n] = slots
    packed[1, :n] = [r.count for r in reqs]
    packed[2] = now
    if n != len(set(slots)):
        packed[3, :n] = np.minimum(
            _duplicate_prefix_host(packed[0, :n], packed[1, :n]), 2**31 - 1
        )
    return packed


def _resolve_with_reclaim(directory, keys: list[str], sweep, grow, *,
                          min_free: int = 0) -> np.ndarray:
    """Batch key→slot resolution with the shared reclaim discipline: on
    free-list exhaustion mid-batch, sweep expired slots (pinning the ones
    already resolved for this batch), grow if still dry, re-resolve —
    already-allocated keys are idempotent lookups, and each dry iteration
    doubles capacity, so the loop terminates.

    ``min_free`` adds sweep-first *hysteresis*: when a sweep reclaims only
    a trickle (≤ ``min_free`` slots), the table grows anyway — otherwise a
    near-full table of live keys re-runs a full sweep on nearly every
    allocation (each freeing a slot or two), a throughput cliff worse than
    one doubling."""
    slots = directory.resolve_batch(keys)
    while (slots < 0).any():
        pinned = {int(s) for s in slots[slots >= 0]}
        sweep(pinned)
        if directory.free_count <= min_free:
            grow()
        slots = directory.resolve_batch(keys)
    return slots


class _PackedLaunchMixin:
    """Shared flush machinery for tables whose ``_launch`` returns the
    packed ``f32[2, B]`` result (row 0 grants, row 1 remaining): readback
    convention plus cross-submit same-key coalescing. Duplicate keys in
    one flush collapse to one launch row per ``(key, count)`` group via
    the table's ``_launch_grouped`` (the Zipf hot-key win — a hot key no
    longer eats the batch), verdicts fanned back out in arrival order.
    Decision semantics are bit-identical to the per-row conservative
    serialization (``bucket_math.duplicate_prefix``); keys whose in-flush
    counts are mixed fall back to per-row entries with exact cumulative
    prefixes."""

    #: Dirty-slot accounting for incremental checkpoints
    #: (runtime/checkpoint.py v4; docs/OPERATIONS.md §10): ``None`` —
    #: the default, zero hot-path cost — until the owning store's
    #: ``enable_dirty_tracking()`` arms it with a set. Every slot a
    #: launch resolves lands here (slightly over-inclusive: a denied
    #: request still refreshes its slot's refill timestamp, so resolved
    #: ≈ written), giving OP_STATS the dirty/total ratio that predicts
    #: the next delta's size. The delta itself is computed by structural
    #: diff, not this set — forgetting a site here can never corrupt a
    #: checkpoint, only misreport the gauge.
    dirty_slots: "set[int] | None" = None

    async def _flush(self, reqs: Sequence[_AcquireReq]) -> list[AcquireResult]:
        groups = (self._coalesce(reqs)
                  if self.store.coalesce_duplicates else None)
        loop = asyncio.get_running_loop()
        # Block for device results on an executor thread so the event loop
        # keeps accumulating the next flush; readbacks of distinct flushes
        # overlap (see MicroBatcher). One packed array = one transfer.
        if groups is None:
            out = self._launch(reqs)
            out_np = await loop.run_in_executor(None, lambda: np.asarray(out))
            return [
                AcquireResult(bool(out_np[0, i] > 0.5), float(out_np[1, i]))
                for i in range(len(reqs))
            ]
        out = self._dispatch_grouped(groups)
        out_np = await loop.run_in_executor(None, lambda: np.asarray(out))
        results: list[AcquireResult | None] = [None] * len(reqs)
        for g, (_, count, _, members, _) in enumerate(groups):
            n_granted = int(out_np[0, g])
            # Reconstruct each member's exact per-row remaining view from
            # the group result: avail = post-consumption remaining +
            # consumed (clamping matches the per-row kernel's, since a
            # negative avail yields 0 either way).
            avail = float(out_np[1, g]) + n_granted * count
            for j, idx in enumerate(members):
                granted = j < n_granted
                results[idx] = AcquireResult(
                    granted,
                    max(avail - j * count - (count if granted else 0), 0.0))
        return results  # type: ignore[return-value]

    @staticmethod
    def _coalesce(reqs: Sequence[_AcquireReq]):
        """Group requests for the grouped kernels; ``None`` when there are
        no duplicates (the classic single-row-per-request path is used)."""
        by_key: dict[str, list[int]] = {}
        for i, r in enumerate(reqs):
            by_key.setdefault(r.key, []).append(i)
        if len(by_key) == len(reqs):
            return None
        # (key, count, n, member_indices, prefix)
        groups: list[tuple[str, int, int, list[int], int]] = []
        for key, members in by_key.items():
            counts = {reqs[i].count for i in members}
            if len(counts) == 1:
                groups.append((key, counts.pop(), len(members), members, 0))
            else:
                # Mixed counts for one key: per-request rows with exact
                # cumulative prefixes (identical to the per-row kernel).
                pref = 0
                for i in members:
                    # Saturate like _build_packed does — a huge cumulative
                    # prefix must under-admit, not overflow the i32 operand.
                    groups.append((key, reqs[i].count, 1, [i],
                                   min(pref, 2**31 - 1)))
                    pref += reqs[i].count
        return groups

    def _dispatch_grouped(self, groups):
        """Pack groups into the shared i32[5, B] operand and hand it to
        the table's grouped kernel (``_launch_grouped``)."""
        with self.store.profiler.span("acquire_batch_grouped",
                                      len(groups)), self.store._lock:
            slots = self.resolve_slots([g[0] for g in groups])
            b = self.store.max_batch
            now = self.store.now_ticks_checked()
            packed = np.full((5, b), -1, np.int32)
            packed[1] = 0
            packed[3] = 0
            packed[4] = 0
            n = len(groups)
            packed[0, :n] = slots
            packed[1, :n] = [g[1] for g in groups]
            packed[2] = now
            packed[3, :n] = [g[4] for g in groups]
            packed[4, :n] = [g[2] for g in groups]
            out = self._launch_grouped(jnp.asarray(packed))
            n_reqs = sum(g[2] for g in groups)
            self.store.metrics.record_launch(b, n)
            self.store.metrics.rows_coalesced += n_reqs - n
            return out

    def _warm_grouped(self) -> None:
        """Compile the grouped kernel at table construction (all-padding
        operand, state values untouched). Lazily compiling it on the first
        duplicate-containing flush would land a ~1s TPU compile inside the
        store lock at an unpredictable point mid-serving."""
        packed = np.full((5, self.store.max_batch), -1, np.int32)
        packed[1:] = 0
        jax.block_until_ready(self._launch_grouped(jnp.asarray(packed)))

    # -- growth de-cliffing -------------------------------------------------
    def _maybe_pregrow(self) -> None:
        """When the table crosses 75% occupancy, pre-compile the serving
        kernels for the doubled size on a background thread — OUTSIDE the
        store lock — so the eventual ``_grow`` swap finds them in the jit
        cache instead of stalling the serving path for the recompile
        (~1 s/size on TPU; see DESIGN.md "Table growth")."""
        target = self.n_slots * 2
        if (self._pregrow_target < target
                and self.dir.free_count * 4 < self.n_slots):
            self._pregrow_target = target
            t = threading.Thread(
                target=self._pregrow_worker, args=(target,),
                name="table-pregrow", daemon=True,
            )
            # Tracked so aclose() can join: a daemon thread mid-XLA-compile
            # at interpreter teardown aborts the process ("FATAL: exception
            # not rethrown" out of the runtime's thread machinery).
            self.store._bg_threads.add(t)
            t.start()

    def _pregrow_worker(self, n_slots: int) -> None:
        try:
            with self.store.profiler.span("pregrow_warm", n_slots):
                self._warm_for_size(n_slots)
            self.store.metrics.pregrows += 1
        except Exception as exc:  # a failed warm only costs the old cliff
            log.error_evaluating_kernel(exc)

    def acquire_blocking(self, key: str, count: int) -> AcquireResult:
        out_np = np.asarray(self._launch([_AcquireReq(key, count)]))
        return AcquireResult(bool(out_np[0, 0] > 0.5), float(out_np[1, 0]))


    # -- shared bulk machinery (acquire_many over any packed table) --------
    #: Max scanned batches per bulk dispatch: 32 × 4096 ≈ 768KB of compact
    #: operands — under the tunneled link's ~1MB sustained-transfer cliff
    #: (benchmarks/RESULTS.md) while amortizing dispatch overhead. K is
    #: chosen per call from {1, 2, 4, …, 32}, so the jit cache holds at
    #: most 6 bulk variants per table.
    _BULK_MAX_K = 32
    #: Profiler span name for the scan-path dispatch (per table family).
    _BULK_SPAN = "acquire_many"

    def _launch_many(self, keys: Sequence[str], counts_np: np.ndarray,
                     with_remaining: bool = True) -> list[tuple]:
        """Dispatch a whole key array as scanned kernel launches; returns
        per-dispatch device handles (no readback — callers overlap it).
        The chunking/padding discipline is shared; the table family's
        ``_launch_scan_chunk`` runs its own scanned kernel per chunk.
        u8 counts ride the fused 5-bytes/decision layout (slots + counts
        in ONE operand — transfer count matters as much as bytes on
        per-transfer-floor-bound links); rare oversized counts fall back
        to the split layout with an explicit mask."""
        n = len(keys)
        b = self.store.max_batch
        outs: list[tuple] = []
        compact = n > 0 and int(counts_np.max(initial=0)) <= 0xFF
        with self.store.profiler.span(self._BULK_SPAN, n), self.store._lock:
            # keys may be a wire.KeyBlob: the native directory resolves
            # straight from the frame's byte blob (zero Python strings).
            slots = self.resolve_slots(keys)
            now = self.store.now_ticks_checked()
            pos = 0
            while pos < n:
                rows = -(-(n - pos) // b)  # ceil
                k = 1
                while k < rows and k < self._BULK_MAX_K:
                    k *= 2
                take = min(k * b, n - pos)
                s = np.full((k * b,), -1, np.int32)
                s[:take] = slots[pos:pos + take]
                c = np.zeros((k * b,), np.uint8 if compact else np.int32)
                c[:take] = np.minimum(counts_np[pos:pos + take], 2**31 - 1)
                nows = np.full((k,), now, np.int32)
                out = self._launch_scan_chunk(
                    s.reshape(k, b), c.reshape(k, b), nows, compact,
                    with_remaining)
                outs.append((out, take))
                self.store.metrics.record_launch(k * b, take)
                pos += take
        return outs

    def _launch_scan_chunk(self, s: np.ndarray, c: np.ndarray,
                           nows: np.ndarray, compact: bool,
                           with_remaining: bool):
        """One chunk's scanned dispatch — returns a device handle whose
        layout ``_gather_bulk`` understands (u8 bit-packed grants or
        ``f32[K, 2, B]``)."""
        raise NotImplementedError

    @staticmethod
    def _gather_bulk(outs: list[tuple], n: int,
                     with_remaining: bool = True) -> BulkAcquireResult:
        granted = np.empty((n,), bool)
        remaining = np.empty((n,), np.float32) if with_remaining else None
        pos = 0
        # ONE device→host fetch per dispatch, and ONE device_get across
        # dispatches so those fetches overlap instead of serializing a
        # link RTT each (fetches are RTT-bound on tunneled links — this
        # is the bulk path's whole latency story).
        arrs = jax.device_get([h for h, _ in outs])
        for out_np, (_, take) in zip(arrs, outs):
            if out_np.dtype == np.uint8:       # bit-packed grants
                bits = np.unpackbits(out_np.reshape(-1), bitorder="little")
                granted[pos:pos + take] = bits[:take].astype(bool)
            else:                              # f32[K, 2, B]
                granted[pos:pos + take] = (
                    out_np[:, 0, :].reshape(-1)[:take] > 0.5)
                if remaining is not None:
                    remaining[pos:pos + take] = (
                        out_np[:, 1, :].reshape(-1)[:take])
            pos += take
        return BulkAcquireResult(granted, remaining)

    @staticmethod
    def _grant_probes(res: BulkAcquireResult,
                      counts_np: np.ndarray) -> BulkAcquireResult:
        """Zero-permit probes are granted unconditionally on every
        single-request path (the kernel's ``new_v >= 0`` is always true);
        the bulk path's conservative in-batch prefix could deny a probe
        riding beside denied same-key demand — override here so direct
        store callers see one contract (not just limiters that patch up)."""
        _grant_zero_probes(res.granted, counts_np)
        return res

    @staticmethod
    def _bulk_groups(slots: np.ndarray, counts_np: np.ndarray):
        """Slot-grouped view of a bulk call for duplicate coalescing, or
        ``None`` when it wouldn't pay. Fully vectorized (stable argsort +
        segment boundaries — request order is preserved within each slot's
        segment, which is what makes group decisions bit-identical to the
        per-row conservative serialization). Declines when <25% of rows
        would be saved, or when any key's counts are mixed (the scan
        path's exact prefixes handle that rare shape)."""
        n = len(slots)
        order = np.argsort(slots, kind="stable")
        s_sorted = slots[order]
        seg_start = np.r_[True, s_sorted[1:] != s_sorted[:-1]]
        n_groups = int(seg_start.sum())
        if n_groups * 4 > n * 3:
            return None
        starts = np.nonzero(seg_start)[0]
        lengths = np.diff(np.r_[starts, n])
        c_sorted = counts_np[order]
        first_c = c_sorted[starts]
        if not np.array_equal(c_sorted, np.repeat(first_c, lengths)):
            return None
        seg_id = np.cumsum(seg_start) - 1
        rank = np.arange(n) - starts[seg_id]
        return order, seg_id, rank, starts, lengths, first_c

    def _launch_many_grouped(self, keys: Sequence[str],
                             counts_np: np.ndarray, with_remaining: bool):
        """Coalesced bulk dispatch: one launch row per ``(key, count)``
        group via the grouped flush kernel — under Zipf hot keys the
        transferred bytes (the bulk path's real cost) shrink by the
        duplicate fraction. Returns a readback closure, or ``None`` when
        grouping doesn't pay (caller falls back to the scan path)."""
        n = len(keys)
        if n == 0:
            return None
        with self.store.profiler.span("acquire_many_grouped", n), \
                self.store._lock:
            slots = self.resolve_slots(keys)  # KeyBlob-aware (see above)
            g = self._bulk_groups(slots, counts_np)
            if g is None:
                return None
            order, seg_id, rank, starts, lengths, first_c = g
            gslots = slots[order][starts]
            gcounts = np.minimum(first_c, 2**31 - 1).astype(np.int32)
            b = self.store.max_batch
            now = self.store.now_ticks_checked()
            outs: list[tuple] = []
            for pos in range(0, len(gslots), b):
                m = min(b, len(gslots) - pos)
                packed = np.full((5, b), -1, np.int32)
                packed[1] = 0
                packed[3] = 0  # one group per slot per call ⇒ prefix 0
                packed[4] = 0
                packed[0, :m] = gslots[pos:pos + m]
                packed[1, :m] = gcounts[pos:pos + m]
                packed[2] = now
                packed[4, :m] = np.minimum(lengths[pos:pos + m], 2**31 - 1)
                out = self._launch_grouped(jnp.asarray(packed))
                outs.append((out, m))
                self.store.metrics.record_launch(b, m)
            self.store.metrics.rows_coalesced += n - len(gslots)

        def gather() -> BulkAcquireResult:
            n_g = np.empty(len(gslots), np.float32)
            rem_g = np.empty(len(gslots), np.float32)
            pos = 0
            for out, m in outs:
                out_np = np.asarray(out)  # one fetch per dispatch
                n_g[pos:pos + m] = out_np[0, :m]
                rem_g[pos:pos + m] = out_np[1, :m]
                pos += m
            granted_sorted = rank < n_g[seg_id]
            granted = np.empty(n, bool)
            granted[order] = granted_sorted
            remaining = None
            if with_remaining:
                c = first_c[seg_id].astype(np.float32)
                # Each member's per-row remaining view, reconstructed from
                # the group result exactly as the flush path does
                # (_PackedLaunchMixin._flush).
                avail = rem_g[seg_id] + n_g[seg_id] * c
                rem_sorted = np.maximum(
                    avail - rank * c - np.where(granted_sorted, c, 0.0), 0.0)
                remaining = np.empty(n, np.float32)
                remaining[order] = rem_sorted.astype(np.float32)
            return BulkAcquireResult(granted, remaining)

        return gather

    def _bulk_plan(self, keys: Sequence[str], counts_np: np.ndarray,
                   with_remaining: bool):
        """Choose + dispatch the bulk strategy; returns the readback
        closure (callers run it inline or on an executor)."""
        if self.store.coalesce_duplicates:
            gather = self._launch_many_grouped(keys, counts_np,
                                               with_remaining)
            if gather is not None:
                return gather
        outs = self._launch_many(keys, counts_np, with_remaining)
        return lambda: self._gather_bulk(outs, len(keys), with_remaining)

    def acquire_many_blocking(self, keys: Sequence[str],
                              counts: Sequence[int], *,
                              with_remaining: bool = True) -> BulkAcquireResult:
        counts_np = np.asarray(counts, np.int64)
        gather = self._bulk_plan(keys, counts_np, with_remaining)
        return self._grant_probes(gather(), counts_np)

    async def acquire_many(self, keys: Sequence[str],
                           counts: Sequence[int], *,
                           with_remaining: bool = True) -> BulkAcquireResult:
        counts_np = np.asarray(counts, np.int64)
        gather = self._bulk_plan(keys, counts_np, with_remaining)
        loop = asyncio.get_running_loop()
        # ONE await resolves the whole call; the readback runs off-loop so
        # the event loop keeps serving (and other bulk calls' dispatches
        # overlap this one's transfer).
        res = await loop.run_in_executor(None, gather)
        return self._grant_probes(res, counts_np)


def _arm_dirty(table) -> None:
    """Arm one table's dirty accounting (idempotent). Classic tables
    track the exact host-resolved slot set (``dirty_slots``);
    fingerprint tables — whose slot placement happens in-kernel, never
    on host — count dispatched rows instead (``dirty_rows``, a
    documented upper bound: duplicates re-count)."""
    if hasattr(table, "dirty_slots"):
        if table.dirty_slots is None:
            table.dirty_slots = set()
    elif hasattr(table, "dirty_rows") and table.dirty_rows is None:
        table.dirty_rows = 0


def _dirty_clear(table) -> None:
    if getattr(table, "dirty_slots", None) is not None:
        table.dirty_slots.clear()
    elif getattr(table, "dirty_rows", None) is not None:
        table.dirty_rows = 0


def _dirty_count(table) -> int:
    if getattr(table, "dirty_slots", None) is not None:
        return len(table.dirty_slots)
    return int(getattr(table, "dirty_rows", None) or 0)


class _DeviceTable(_PackedLaunchMixin):
    """One homogeneous-config bucket table: device arrays + host directory."""

    def __init__(self, store: "DeviceBucketStore", capacity: float,
                 fill_rate_per_sec: float, n_slots: int) -> None:
        self.store = store
        self.capacity = float(capacity)
        self.fill_rate_per_sec = float(fill_rate_per_sec)
        self.rate_per_tick = _rate_per_tick(fill_rate_per_sec)
        self.state = K.init_bucket_state(n_slots)
        self.n_slots = n_slots
        # Host key→slot routing: C++ batch-resolve when buildable, Python
        # fallback otherwise (runtime/directory.py — identical semantics).
        self.dir = make_directory(n_slots)
        # Device-resident config constants: uploaded once, never per flush.
        self.cap_dev = jnp.float32(self.capacity)
        self.rate_dev = jnp.float32(self.rate_per_tick)
        self.batcher: MicroBatcher[_AcquireReq, AcquireResult] = MicroBatcher(
            self._flush,
            max_batch=store.max_batch,
            max_delay_s=store.max_delay_s,
            max_inflight=store.max_inflight,
            flush_latency=store.metrics.flush_latency,
            queue_latency=store.metrics.queue_latency,
            flush_observer=store._flush_observer,
        )
        self._pregrow_target = 0
        if store.coalesce_duplicates:
            self._warm_grouped()

    # -- slot management ---------------------------------------------------
    def resolve_slots(self, keys: list[str]) -> np.ndarray:
        """Batch key→slot resolution (the host hot path — one native call)."""
        slots = _resolve_with_reclaim(self.dir, keys, self._sweep, self._grow,
                                      min_free=self.n_slots // 16)
        if self.dirty_slots is not None:
            self.dirty_slots.update(slots.tolist())
        self._maybe_pregrow()
        return slots

    def _warm_for_size(self, n_slots: int) -> None:
        """One dummy pass of every serving+sweep kernel at ``n_slots`` —
        populates the jit cache for the post-grow shapes. Includes the
        K=``_BULK_MAX_K`` scan variants (the large-``acquire_many`` shape)
        so the first post-grow bulk call doesn't hit the ~1s recompile
        cliff the pregrow machinery exists to remove; smaller tail-K
        chunks may still compile lazily (cheaper, and off the common
        path). The dummy state is freed eagerly at the end — the warm
        runs concurrently with the live table, so holding it would keep
        transient device memory at ~3× through the 75%-occupancy window."""
        b = self.store.max_batch
        state = K.init_bucket_state(n_slots)
        packed = np.full((4, b), -1, np.int32)
        packed[1:] = 0
        state, out = K.acquire_batch_packed(
            state, jnp.asarray(packed), self.cap_dev, self.rate_dev)
        state, _ = K.sweep_expired(state, jnp.int32(0), self.cap_dev,
                                   self.rate_dev)
        if self.store.coalesce_duplicates:
            packed5 = np.full((5, b), -1, np.int32)
            packed5[1:] = 0
            state, out = K.acquire_batch_packed_grouped(
                state, jnp.asarray(packed5), self.cap_dev, self.rate_dev)
        k = self._BULK_MAX_K
        s = np.full((k, b), -1, np.int32)
        nows = np.zeros((k,), np.int32)
        c8 = np.zeros((k, b), np.uint8)
        state, out = K.acquire_scan_compact_packed(
            state, jnp.asarray(s), jnp.asarray(c8), jnp.asarray(nows),
            self.cap_dev, self.rate_dev)
        if b % 8 == 0:
            state, out = K.acquire_scan_compact_bits(
                state, jnp.asarray(s), jnp.asarray(c8), jnp.asarray(nows),
                self.cap_dev, self.rate_dev)
        jax.block_until_ready(out)
        for arr in state:
            arr.delete()

    def _sweep(self, pinned: set[int] | None = None) -> None:
        """Reclaim slots whose buckets have sat full-refilled past TTL
        (invariant 5). One vectorized pass; freed ids return to the pool.

        On TPU the pass runs as the fused Pallas streaming kernel, whose
        per-tile expired counts let a no-op sweep finish after a ~100-byte
        readback instead of fetching the full bool mask (N bytes — 10 MB at
        10M slots, expensive on tunneled links). Falls back to the XLA
        kernel elsewhere or on any Pallas failure.

        ``pinned`` slots (already resolved for the in-flight batch) are
        exempt — a sweep triggered mid-batch must not free-and-reallocate a
        slot an earlier request in the same batch is about to touch, which
        would cross-contaminate two keys' buckets."""
        with self.store.profiler.span("sweep", self.n_slots):
            self._sweep_locked(pinned)

    def _sweep_locked(self, pinned: set[int] | None = None) -> None:
        now = self.store.clock.now_ticks()
        freed_np = None
        if self.store.use_pallas_sweep:
            try:
                from distributedratelimiting.redis_tpu.ops.pallas_kernels import (
                    sweep_expired_pallas,
                )

                new_exists, mask, counts = sweep_expired_pallas(
                    self.state.tokens, self.state.last_ts,
                    self.state.exists.astype(jnp.int8), jnp.int32(now),
                    jnp.float32(self.capacity), jnp.float32(self.rate_per_tick),
                )
                if int(np.asarray(counts).sum()) == 0:
                    self.store.metrics.sweeps += 1
                    return
                # Read the mask back BEFORE committing the cleared exists —
                # if this readback fails, self.state is untouched and the
                # XLA fallback still sees the expired slots.
                freed_np = np.asarray(mask).astype(bool)
                self.state = K.BucketState(
                    self.state.tokens, self.state.last_ts,
                    new_exists.astype(bool),
                )
            except Exception as exc:  # experimental platform — fall back
                # Disable after the first failure: a broken Pallas path
                # would otherwise re-trace and re-fail inside the store
                # lock on every sweep. The counter makes the silent
                # fallback observable (the TPU bench asserts it stays 0).
                self.store.use_pallas_sweep = False
                self.store.metrics.pallas_sweep_failures += 1
                log.error_evaluating_kernel(exc)
                freed_np = None
        if freed_np is None:
            self.state, freed = K.sweep_expired(
                self.state, jnp.int32(now), jnp.float32(self.capacity),
                jnp.float32(self.rate_per_tick),
            )
            freed_np = np.asarray(freed)
        if freed_np.any():
            dead = np.nonzero(freed_np)[0].astype(np.int32)
            if pinned:
                dead = dead[~np.isin(dead, np.fromiter(pinned, np.int32,
                                                       len(pinned)))]
            self.store.metrics.slots_evicted += self.dir.remove_slots(dead)
        self.store.metrics.sweeps += 1

    def _grow(self) -> None:
        """Double the table. Amortized; recompiles kernels for the new N."""
        old_n = self.n_slots
        new_n = old_n * 2
        self.state = K.BucketState(
            tokens=jnp.concatenate([self.state.tokens, jnp.zeros((old_n,), jnp.float32)]),
            last_ts=jnp.concatenate([self.state.last_ts, jnp.zeros((old_n,), jnp.int32)]),
            exists=jnp.concatenate([self.state.exists, jnp.zeros((old_n,), bool)]),
        )
        self.dir.add_slots(old_n, new_n)
        self.n_slots = new_n

    # -- decision paths ----------------------------------------------------
    def _launch_grouped(self, packed):
        self.state, out = K.acquire_batch_packed_grouped(
            self.state, packed, self.cap_dev, self.rate_dev,
        )
        return out

    def _launch(self, reqs: Sequence[_AcquireReq]):
        """Build padded arrays and dispatch one acquire kernel launch.

        The whole read-modify-write of the donated ``self.state`` runs under
        the store lock: the blocking path may be called from arbitrary
        threads while the event loop flushes batches, and two concurrent
        donating kernel calls on the same buffers would race (one side
        would operate on a deleted/donated array)."""
        with self.store.profiler.span("acquire_batch", len(reqs)), \
                self.store._lock:
            slots = self.resolve_slots([r.key for r in reqs])
            # Fixed pad width ⇒ exactly ONE compiled kernel per table (the
            # extra rows are masked padding and cost ~nothing next to launch
            # overhead; a varying pad width would recompile per size — ~1 s
            # per size on TPU, fatal for serving-path p99).
            b = self.store.max_batch
            now = self.store.now_ticks_checked()
            packed = _build_packed(reqs, slots, b, now)
            self.state, out = K.acquire_batch_packed(
                self.state, jnp.asarray(packed), self.cap_dev, self.rate_dev,
            )
            self.store.metrics.record_launch(b, len(reqs))
            return out

    # -- bulk decision path (chunk loop shared via _PackedLaunchMixin) -----
    def _launch_scan_chunk(self, s: np.ndarray, c: np.ndarray,
                           nows: np.ndarray, compact: bool,
                           with_remaining: bool):
        k, b = s.shape
        if compact:
            fused = jnp.asarray(K.pack_compact5(s, c))
            if not with_remaining and b % 8 == 0:
                self.state, out = K.acquire_scan_fused_bits(
                    self.state, fused, jnp.asarray(nows),
                    self.cap_dev, self.rate_dev,
                )
            else:
                self.state, out = K.acquire_scan_fused_packed(
                    self.state, fused, jnp.asarray(nows),
                    self.cap_dev, self.rate_dev,
                )
            return out
        self.state, granted, remaining = K.acquire_scan(
            self.state, jnp.asarray(s), jnp.asarray(c),
            jnp.asarray(s >= 0), jnp.asarray(nows),
            self.cap_dev, self.rate_dev,
        )
        # One lazy device op so the fetch stays single.
        return jnp.stack([granted.astype(jnp.float32), remaining], axis=1)

    def _debit_launch(self, keys: Sequence[str], amounts: Sequence[float]):
        """One saturating-debit launch (tier-0 reconciliation): refill,
        subtract the drained local grants clamped at zero, return the
        packed ``f32[2, B]`` result (post-debit balance, shortfall).
        Same single-transfer/locking discipline as ``_launch``."""
        n = len(keys)
        with self.store.profiler.span("debit_batch", n), self.store._lock:
            slots = self.resolve_slots(list(keys))
            b = _pad_size(n, floor=64)
            now = self.store.now_ticks_checked()
            packed = np.full((3, b), -1, np.int32)
            packed[1] = 0
            packed[0, :n] = slots
            # Float amounts travel bitcast in the int32 row (exact) —
            # the counter-sync operand convention.
            packed[1, :n] = np.asarray(amounts, np.float32).view(np.int32)
            packed[2] = now
            self.state, out = K.debit_batch_packed(
                self.state, jnp.asarray(packed), self.cap_dev, self.rate_dev,
            )
            self.store.metrics.record_launch(b, n)
            return out

    def peek_blocking(self, key: str) -> float:
        with self.store._lock:
            slot = self.dir.lookup(key)
            if slot is None:
                return float(np.floor(self.capacity))
            b = _pad_size(1)
            packed = _build_packed([_AcquireReq(key, 0)], [slot], b,
                                   self.store.now_ticks_checked())
            est = K.peek_batch_packed(
                self.state, jnp.asarray(packed), self.cap_dev, self.rate_dev,
            )
        return float(np.asarray(est)[0])

    def rebase(self, offset: int) -> None:
        self.state = K.rebase_bucket_epoch(self.state, jnp.int32(offset))

    # -- checkpoint form (swapped wholesale by _FpTable) -------------------
    def to_snap(self) -> dict:
        return {
            "directory": self.dir.to_dict(),
            "tokens": np.asarray(self.state.tokens),
            "last_ts": np.asarray(self.state.last_ts),
            "exists": np.asarray(self.state.exists),
        }

    def load_snap(self, data: dict, shift: int) -> None:
        if "directory" not in data:
            raise ValueError(
                "checkpoint's bucket tables use the device-resident "
                "fingerprint directory — restore into a "
                "FingerprintBucketStore")
        # Adopt the snapshot's size: tables grow independently by
        # doubling at runtime, so a post-growth checkpoint has no
        # reason to match a fresh store's default size — a restore
        # that raised here would crash-loop exactly the planned
        # restart it exists for.
        self.n_slots = len(data["tokens"])
        self.state = K.BucketState(
            tokens=jnp.asarray(data["tokens"]),
            last_ts=jnp.asarray(_shift_ts(data["last_ts"], shift)),
            exists=jnp.asarray(data["exists"]),
        )
        self.dir.load(data["directory"], self.n_slots)


class _DeviceWindowTable(_PackedLaunchMixin):
    """One homogeneous-config window table (sliding by default;
    ``fixed=True`` disables the trailing-window interpolation — the
    fixed-window limiter's semantics — over the same state/sweeps)."""

    def __init__(self, store: "DeviceBucketStore", limit: float,
                 window_ticks: int, n_slots: int, *,
                 fixed: bool = False) -> None:
        self.store = store
        self.limit = float(limit)
        self.fixed = fixed
        self.window_ticks = int(window_ticks)
        self.state = K.init_window_state(n_slots)
        self.n_slots = n_slots
        self.dir = make_directory(n_slots)
        self.limit_dev = jnp.float32(self.limit)
        self.window_dev = jnp.int32(self.window_ticks)
        self.batcher: MicroBatcher[_AcquireReq, AcquireResult] = MicroBatcher(
            self._flush,
            max_batch=store.max_batch,
            max_delay_s=store.max_delay_s,
            max_inflight=store.max_inflight,
            flush_latency=store.metrics.flush_latency,
            queue_latency=store.metrics.queue_latency,
            flush_observer=store._flush_observer,
        )
        self._pregrow_target = 0
        if store.coalesce_duplicates:
            self._warm_grouped()

    def resolve_slots(self, keys: list[str]) -> np.ndarray:
        slots = _resolve_with_reclaim(self.dir, keys, self._sweep, self._grow,
                                      min_free=self.n_slots // 16)
        if self.dirty_slots is not None:
            self.dirty_slots.update(slots.tolist())
        self._maybe_pregrow()
        return slots

    def _warm_for_size(self, n_slots: int) -> None:
        b = self.store.max_batch
        state = K.init_window_state(n_slots)
        packed = np.full((4, b), -1, np.int32)
        packed[1:] = 0
        state, out = K.window_acquire_batch_packed(
            state, jnp.asarray(packed), self.limit_dev, self.window_dev,
            interpolate=not self.fixed)
        state, _ = K.sweep_windows(state, jnp.int32(0), self.window_dev)
        if self.store.coalesce_duplicates:
            packed5 = np.full((5, b), -1, np.int32)
            packed5[1:] = 0
            state, out = K.window_acquire_batch_packed_grouped(
                state, jnp.asarray(packed5), self.limit_dev, self.window_dev,
                interpolate=not self.fixed)
        jax.block_until_ready(out)

    def _sweep(self, pinned: set[int] | None = None) -> None:
        with self.store.profiler.span("sweep_windows", self.n_slots):
            self._sweep_locked(pinned)

    def _sweep_locked(self, pinned: set[int] | None = None) -> None:
        now = self.store.clock.now_ticks()
        self.state, freed = K.sweep_windows(
            self.state, jnp.int32(now), jnp.int32(self.window_ticks)
        )
        freed_np = np.asarray(freed)
        if freed_np.any():
            dead = np.nonzero(freed_np)[0].astype(np.int32)
            if pinned:
                dead = dead[~np.isin(dead, np.fromiter(pinned, np.int32,
                                                       len(pinned)))]
            self.store.metrics.slots_evicted += self.dir.remove_slots(dead)
        self.store.metrics.sweeps += 1

    def rebase(self, offset_ticks: int) -> None:
        self.state = K.rebase_window_epoch(
            self.state, jnp.int32(offset_ticks // self.window_ticks)
        )

    # -- checkpoint form (swapped wholesale by _FpWindowTable) -------------
    def to_snap(self) -> dict:
        return {
            "directory": self.dir.to_dict(),
            "prev_count": np.asarray(self.state.prev_count),
            "curr_count": np.asarray(self.state.curr_count),
            "window_idx": np.asarray(self.state.window_idx),
            "exists": np.asarray(self.state.exists),
        }

    def load_snap(self, data: dict, shift: int) -> None:
        if "directory" not in data:
            raise ValueError(
                "checkpoint's window tables use the device-resident "
                "fingerprint directory — restore into a "
                "FingerprintBucketStore")
        self.n_slots = len(data["prev_count"])
        self.state = K.WindowState(
            prev_count=jnp.asarray(data["prev_count"]),
            curr_count=jnp.asarray(data["curr_count"]),
            window_idx=jnp.asarray(
                _shift_ts(data["window_idx"], shift // self.window_ticks)),
            exists=jnp.asarray(data["exists"]),
        )
        self.dir.load(data["directory"], self.n_slots)

    def _grow(self) -> None:
        old_n = self.n_slots
        self.state = K.WindowState(
            prev_count=jnp.concatenate([self.state.prev_count, jnp.zeros((old_n,), jnp.float32)]),
            curr_count=jnp.concatenate([self.state.curr_count, jnp.zeros((old_n,), jnp.float32)]),
            window_idx=jnp.concatenate([self.state.window_idx, jnp.zeros((old_n,), jnp.int32)]),
            exists=jnp.concatenate([self.state.exists, jnp.zeros((old_n,), bool)]),
        )
        self.dir.add_slots(old_n, old_n * 2)
        self.n_slots = old_n * 2

    def _launch_grouped(self, packed):
        self.state, out = K.window_acquire_batch_packed_grouped(
            self.state, packed, self.limit_dev, self.window_dev,
            interpolate=not self.fixed,
        )
        return out

    def _launch(self, reqs: Sequence[_AcquireReq]):
        # Same dispatch discipline as _DeviceTable.
        with self.store.profiler.span("window_acquire_batch", len(reqs)), \
                self.store._lock:
            slots = self.resolve_slots([r.key for r in reqs])
            b = self.store.max_batch  # fixed pad ⇒ one compiled kernel
            packed = _build_packed(reqs, slots, b,
                                   self.store.now_ticks_checked())
            self.state, out = K.window_acquire_batch_packed(
                self.state, jnp.asarray(packed), self.limit_dev,
                self.window_dev, interpolate=not self.fixed,
            )
            self.store.metrics.record_launch(b, len(reqs))
            return out

    # -- bulk path (chunk loop shared via _PackedLaunchMixin) --------------
    _BULK_SPAN = "window_acquire_many"

    def _launch_scan_chunk(self, s: np.ndarray, c: np.ndarray,
                           nows: np.ndarray, compact: bool,
                           with_remaining: bool):
        k, b = s.shape
        if compact:
            fused = jnp.asarray(K.pack_compact5(s, c))
            if not with_remaining and b % 8 == 0:
                self.state, out = K.window_acquire_scan_fused_bits(
                    self.state, fused, jnp.asarray(nows),
                    self.limit_dev, self.window_dev,
                    interpolate=not self.fixed,
                )
            else:
                self.state, out = K.window_acquire_scan_fused_packed(
                    self.state, fused, jnp.asarray(nows),
                    self.limit_dev, self.window_dev,
                    interpolate=not self.fixed,
                )
            return out
        self.state, granted, remaining = K.window_acquire_scan(
            self.state, jnp.asarray(s), jnp.asarray(c),
            jnp.asarray(s >= 0), jnp.asarray(nows),
            self.limit_dev, self.window_dev, interpolate=not self.fixed,
        )
        return jnp.stack([granted.astype(jnp.float32), remaining], axis=1)


class DeviceBucketStore(BucketStore):
    """TPU-resident store: HBM tables + micro-batched kernel launches."""

    def __init__(
        self,
        *,
        n_slots: int = 2**17,
        counter_slots: int = 2**14,
        clock: Clock | None = None,
        max_batch: int = 4096,
        max_delay_s: float = 200e-6,
        max_inflight: int = 8,
        use_pallas_sweep: bool | None = None,
        coalesce_duplicates: bool = True,
        profiling_session: Callable[[], ProfilingSession | None] | None = None,
        rebase_threshold_ticks: int = _REBASE_THRESHOLD_TICKS,
    ) -> None:
        self.clock = clock or MonotonicClock()
        # ≙ Func<ProfilingSession> registered with the connection on connect
        # (TryRegisterProfiler, RedisTokenBucketRateLimiter.cs:166-174);
        # here the "commands" profiled are kernel dispatches.
        self.profiler = Profiler(profiling_session)
        if use_pallas_sweep is None:
            use_pallas_sweep = jax.devices()[0].platform == "tpu"
        self.use_pallas_sweep = use_pallas_sweep
        # Flush-level same-key coalescing (False = ablation/debug: every
        # request is its own launch row, in-kernel prefix serialization).
        self.coalesce_duplicates = coalesce_duplicates
        self.n_slots_default = n_slots
        self.counter_slots = counter_slots
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.max_inflight = max_inflight
        self.metrics = StoreMetrics()
        self._tables: dict[tuple[float, float], _DeviceTable] = {}
        self._wtables: dict[tuple[float, int], _DeviceWindowTable] = {}
        self._counters = K.init_counter_state(counter_slots)
        self._counter_dir = make_directory(counter_slots)
        self._semas = K.init_sema_state(counter_slots)
        self._sema_dir = make_directory(counter_slots)
        self._decay_rate_dev: dict[float, jax.Array] = {}
        self._lock = threading.RLock()  # directory/slot allocation guard
        # A composing store (MeshBucketStore) sets this effectively
        # infinite and coordinates one rebase across every table sharing
        # the clock — independent rebases would strand sibling stores'
        # timestamps in the old epoch.
        self._rebase_threshold = rebase_threshold_ticks
        self._connected = False
        self._connect_gate = asyncio.Lock()
        self._sweeper_task: asyncio.Task | None = None
        # Live background pregrow-warm threads (see _maybe_pregrow);
        # joined on aclose so process exit never tears XLA down under a
        # mid-compile thread.
        self._bg_threads: set[threading.Thread] = set()
        # Dirty-slot accounting (incremental checkpoints; see
        # enable_dirty_tracking) — off by default, zero serving cost.
        self._dirty_tracking = False

    # -- connection lifecycle (lazy, idempotent) ---------------------------
    async def connect(self) -> None:
        if self._connected:
            return
        async with self._connect_gate:  # ≙ SemaphoreSlim(1,1) double-check
            if self._connected:
                return
            # Touch the device so real connection errors surface here, not
            # on the first hot-path acquire (mirrors lazy ConnectAsync).
            jax.block_until_ready(jnp.zeros((8,)))
            self._connected = True

    def _flush_observer(self, n: int, wall_s: float,
                        error: str | None,
                        trace_id: str | None = None) -> None:
        """Per-flush flight-recorder feed (MicroBatcher ``flush_observer``).
        One attribute check per flush when no recorder is attached; a
        flush FAILURE is the store's degraded-mode entry, so it also
        fires a rate-limited auto-dump — the outage window's lead-in
        frames land on disk while they still exist. ``trace_id`` (the
        flush's elected trace, when any member was sampled) stamps the
        frame so a flight dump cross-references its exported trace."""
        rec = self.metrics.flight_recorder
        if rec is None:
            return
        rec.record("flush", n=n, wall_ms=round(wall_s * 1e3, 3),
                   error=error, trace_id=trace_id)
        if error is not None:
            rec.auto_dump("flush_error", {"error": error,
                                          "trace_id": trace_id})

    def now_ticks_checked(self) -> int:
        """Read the store clock; rebase every table's epoch before int32
        tick time can overflow (~24 days of uptime)."""
        now = self.clock.now_ticks()
        if now >= self._rebase_threshold:
            with self._lock:
                now = self.clock.now_ticks()
                if now >= self._rebase_threshold:
                    offset = now - _REBASE_MARGIN_TICKS
                    self.force_rebase(offset)
                    self.clock.rebase(offset)  # type: ignore[attr-defined]
                    now = self.clock.now_ticks()
        return now

    def force_rebase(self, offset: int) -> None:
        """Shift every table's stored timestamps by ``-offset`` WITHOUT
        touching the clock — the coordinated-rebase hook for composing
        stores (the caller rebases the shared clock exactly once after
        every participating store has shifted)."""
        with self._lock:
            for t in self._tables.values():
                t.rebase(offset)
            for wt in self._wtables.values():
                wt.rebase(offset)
            self._counters = K.rebase_counter_epoch(
                self._counters, jnp.int32(offset)
            )
            self._semas = K.rebase_sema_epoch(
                self._semas, jnp.int32(offset)
            )

    # -- table routing -----------------------------------------------------
    # Subclasses swap the constructed table classes (the fingerprint store
    # substitutes its device-directory tables) without copying the keying
    # or locking below.
    _TABLE_CLS: type = None  # type: ignore[assignment]  # set after class
    _WTABLE_CLS: type = None  # type: ignore[assignment]

    def _table(self, capacity: float, fill_rate_per_sec: float) -> "_DeviceTable":
        key = (float(capacity), float(fill_rate_per_sec))
        with self._lock:
            table = self._tables.get(key)
            if table is None:
                table = self._TABLE_CLS(self, capacity, fill_rate_per_sec,
                                        self.n_slots_default)
                if self._dirty_tracking:
                    _arm_dirty(table)
                self._tables[key] = table
            return table

    def _wtable(self, limit: float, window_sec: float,
                fixed: bool = False) -> "_DeviceWindowTable":
        wt = int(window_sec * bm.TICKS_PER_SECOND)
        key = (float(limit), wt, fixed)
        with self._lock:
            table = self._wtables.get(key)
            if table is None:
                table = self._WTABLE_CLS(self, limit, wt,
                                           self.n_slots_default, fixed=fixed)
                if self._dirty_tracking:
                    _arm_dirty(table)
                self._wtables[key] = table
            return table

    # -- exact bucket ------------------------------------------------------
    async def acquire(self, key: str, count: int, capacity: float,
                      fill_rate_per_sec: float) -> AcquireResult:
        await self.connect()
        table = self._table(capacity, fill_rate_per_sec)
        return await table.batcher.submit(_AcquireReq(key, count))

    def acquire_submitter(self, capacity: float, fill_rate_per_sec: float):
        """Hot-path binding: resolve the table ONCE; each call is then one
        ``MicroBatcher.submit`` — no connect check, no config→table lock,
        no arg re-validation per request."""
        submit = self._table(capacity, fill_rate_per_sec).batcher.submit

        async def fast(key: str, count: int) -> AcquireResult:
            return await submit(_AcquireReq(key, count))

        return fast

    def acquire_blocking(self, key: str, count: int, capacity: float,
                         fill_rate_per_sec: float) -> AcquireResult:
        return self._table(capacity, fill_rate_per_sec).acquire_blocking(key, count)

    async def acquire_many(self, keys: Sequence[str], counts: Sequence[int],
                           capacity: float, fill_rate_per_sec: float, *,
                           with_remaining: bool = True) -> BulkAcquireResult:
        """Bulk path: the whole array rides scanned kernel launches — no
        per-request futures, one await per call (the batching the
        reference's README promised but never built, ``README.md:7``)."""
        await self.connect()
        table = self._table(capacity, fill_rate_per_sec)
        return await table.acquire_many(keys, counts,
                                        with_remaining=with_remaining)

    def acquire_many_blocking(self, keys: Sequence[str],
                              counts: Sequence[int], capacity: float,
                              fill_rate_per_sec: float, *,
                              with_remaining: bool = True) -> BulkAcquireResult:
        return self._table(capacity, fill_rate_per_sec).acquire_many_blocking(
            keys, counts, with_remaining=with_remaining)

    def peek_blocking(self, key: str, capacity: float,
                      fill_rate_per_sec: float) -> float:
        return self._table(capacity, fill_rate_per_sec).peek_blocking(key)

    async def window_acquire_many(self, keys: Sequence[str],
                                  counts: Sequence[int], limit: float,
                                  window_sec: float, *, fixed: bool = False,
                                  with_remaining: bool = True
                                  ) -> BulkAcquireResult:
        await self.connect()
        table = self._wtable(limit, window_sec, fixed)
        return await table.acquire_many(keys, counts,
                                        with_remaining=with_remaining)

    def window_acquire_many_blocking(self, keys: Sequence[str],
                                     counts: Sequence[int], limit: float,
                                     window_sec: float, *,
                                     fixed: bool = False,
                                     with_remaining: bool = True
                                     ) -> BulkAcquireResult:
        return self._wtable(limit, window_sec, fixed).acquire_many_blocking(
            keys, counts, with_remaining=with_remaining)

    # -- decaying counter --------------------------------------------------
    def _counter_slot(self, key: str) -> int:
        with self._lock:
            return int(_resolve_with_reclaim(
                self._counter_dir, [key],
                lambda pinned: self._sweep_counters(),
                self._grow_counters,
            )[0])

    def _sweep_counters(self) -> None:
        with self.profiler.span("sweep_counters",
                                self._counters.value.shape[0]):
            self._sweep_counters_locked()

    def _sweep_counters_locked(self) -> None:
        self._counters, freed = K.sweep_counters(
            self._counters, jnp.int32(self.clock.now_ticks())
        )
        freed_np = np.asarray(freed)
        if freed_np.any():
            dead = np.nonzero(freed_np)[0].astype(np.int32)
            self.metrics.slots_evicted += self._counter_dir.remove_slots(dead)
        self.metrics.sweeps += 1

    def _grow_counters(self) -> None:
        old_n = self._counters.value.shape[0]
        self._counters = K.CounterState(
            value=jnp.concatenate([self._counters.value, jnp.zeros((old_n,), jnp.float32)]),
            period=jnp.concatenate([self._counters.period, jnp.zeros((old_n,), jnp.float32)]),
            last_ts=jnp.concatenate([self._counters.last_ts, jnp.zeros((old_n,), jnp.int32)]),
            exists=jnp.concatenate([self._counters.exists, jnp.zeros((old_n,), bool)]),
        )
        self._counter_dir.add_slots(old_n, old_n * 2)

    def _sync_dispatch(self, key: str, local_count: float,
                       decay_rate_per_sec: float):
        return self._sync_dispatch_many([key], [local_count],
                                        decay_rate_per_sec)

    def _sync_dispatch_many(self, keys: Sequence[str],
                            local_counts: Sequence[float],
                            decay_rate_per_sec: float):
        """ONE ``sync_batch`` launch for a whole fleet of counters — the
        device half of :meth:`sync_counters_many` (and, with one row, of
        the classic per-limiter :meth:`sync_counter`)."""
        n = len(keys)
        with self._lock:
            slots = _resolve_with_reclaim(
                self._counter_dir, list(keys),
                lambda pinned: self._sweep_counters(),
                self._grow_counters,
            )
        with self.profiler.span("sync_counter", n), self._lock:
            b = _pad_size(n, floor=8)
            packed = np.full((3, b), -1, np.int32)
            packed[1] = 0
            packed[0, :n] = slots
            # Float local counts travel bitcast in the int32 row (exact).
            packed[1, :n] = np.asarray(local_counts,
                                       np.float32).view(np.int32)
            packed[2] = self.now_ticks_checked()
            rate = self._decay_rate_dev.get(decay_rate_per_sec)
            if rate is None:
                rate = jnp.float32(_rate_per_tick(decay_rate_per_sec))
                self._decay_rate_dev[decay_rate_per_sec] = rate
            self._counters, out = K.sync_batch_packed(
                self._counters, jnp.asarray(packed), rate,
            )
            return out

    async def sync_counter(self, key: str, local_count: float,
                           decay_rate_per_sec: float) -> SyncResult:
        """One decaying-counter sync (≙ the approximate limiter's periodic
        ``ScriptEvaluateAsync(_syncScript)``,
        ``RedisApproximateTokenBucketRateLimiter.cs:439``)."""
        await self.connect()
        out = self._sync_dispatch(key, local_count, decay_rate_per_sec)
        loop = asyncio.get_running_loop()
        out_np = await loop.run_in_executor(None, lambda: np.asarray(out))
        return SyncResult(float(out_np[0, 0]), float(out_np[1, 0]))

    def sync_counter_blocking(self, key: str, local_count: float,
                              decay_rate_per_sec: float) -> SyncResult:
        """Synchronous sync path for loop-less callers (the approximate
        limiter's inline refresh when only the sync API is used)."""
        out_np = np.asarray(self._sync_dispatch(key, local_count,
                                                decay_rate_per_sec))
        return SyncResult(float(out_np[0, 0]), float(out_np[1, 0]))

    async def sync_counters_many(self, keys: Sequence[str],
                                 local_counts: Sequence[float],
                                 decay_rate_per_sec: float
                                 ) -> tuple[np.ndarray, np.ndarray]:
        """Bulk replica sync: the whole fleet's local counts land in ONE
        ``sync_batch`` launch against the counter table (duplicate keys'
        adds accumulate — pre-aggregate per key for exact EWMAs, see
        :func:`~.ops.kernels.sync_batch`)."""
        await self.connect()
        n = len(keys)
        out = self._sync_dispatch_many(keys, local_counts,
                                       decay_rate_per_sec)
        loop = asyncio.get_running_loop()
        out_np = await loop.run_in_executor(None, lambda: np.asarray(out))
        return (out_np[0, :n].astype(np.float64),
                out_np[1, :n].astype(np.float64))

    async def debit_many(self, keys: Sequence[str],
                         amounts: Sequence[float], capacity: float,
                         fill_rate_per_sec: float
                         ) -> tuple[np.ndarray, np.ndarray]:
        """Tier-0 reconciliation against the authoritative bucket table:
        one saturating-debit launch per (capacity, rate) table (see
        :func:`~.ops.kernels.debit_batch_packed`)."""
        await self.connect()
        n = len(keys)
        table = self._table(capacity, fill_rate_per_sec)
        out = table._debit_launch(keys, amounts)
        loop = asyncio.get_running_loop()
        out_np = await loop.run_in_executor(None, lambda: np.asarray(out))
        return (out_np[0, :n].astype(np.float64),
                out_np[1, :n].astype(np.float64))

    # -- hierarchical tenant → key admission (fused kernel) ----------------
    def _hier_dispatch(self, tenants: Sequence[str], keys: Sequence[str],
                       counts_np: np.ndarray, tcap: float, trate: float,
                       cap: float, rate: float) -> list[tuple]:
        """Dispatch hierarchical rows as fused two-table launches: the
        child key table and the parent tenant table decide together in
        ONE kernel (grant iff both levels admit — the decision itself
        is the reconciliation, no refund traffic exists). Returns
        per-chunk device handles (no readback; callers overlap it)."""
        check_hierarchical_args(int(counts_np.min(initial=0)), tcap,
                                trate, cap, rate)
        n = len(keys)
        ctable = self._table(cap, rate)
        ptable = self._table(tcap, trate)
        outs: list[tuple] = []
        with self.profiler.span("acquire_hierarchical", n), self._lock:
            cslots = ctable.resolve_slots(list(keys))
            pslots = ptable.resolve_slots(list(tenants))
            now = self.now_ticks_checked()
            b = self.max_batch
            pos = 0
            while pos < n:
                take = min(b, n - pos)
                packed = np.full((4, b), -1, np.int32)
                packed[1] = 0
                packed[0, :take] = cslots[pos:pos + take]
                packed[1, :take] = np.minimum(counts_np[pos:pos + take],
                                              2**31 - 1)
                packed[2] = now
                packed[3, :take] = pslots[pos:pos + take]
                ctable.state, ptable.state, out = \
                    K.acquire_hierarchical_packed(
                        ctable.state, ptable.state, jnp.asarray(packed),
                        ctable.cap_dev, ctable.rate_dev,
                        ptable.cap_dev, ptable.rate_dev)
                outs.append((out, take))
                self.metrics.record_launch(b, take)
                pos += take
        return outs

    @staticmethod
    def _hier_gather(outs: list[tuple], n: int,
                     with_remaining: bool) -> BulkAcquireResult:
        granted = np.empty(n, bool)
        remaining = np.empty(n, np.float32) if with_remaining else None
        pos = 0
        arrs = jax.device_get([h for h, _ in outs])
        for out_np, (_, take) in zip(arrs, outs):
            granted[pos:pos + take] = out_np[0, :take] > 0.5
            if remaining is not None:
                remaining[pos:pos + take] = out_np[1, :take]
            pos += take
        return BulkAcquireResult(granted, remaining)

    def _hier_fused_supported(self) -> bool:
        """The fused lane needs host-resolved slots; fingerprint tables
        place in-kernel, so the fp store keeps the base compose (exact
        per call, parent refund through its ``debit_many``)."""
        return getattr(self._TABLE_CLS, "resolve_slots", None) is not None

    async def acquire_hierarchical(self, tenant, key, count,
                                   tenant_capacity,
                                   tenant_fill_rate_per_sec, capacity,
                                   fill_rate_per_sec, *, priority=0):
        await self.connect()
        if not self._hier_fused_supported():
            return await super().acquire_hierarchical(
                tenant, key, count, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority=priority)
        res = await self.acquire_hierarchical_many(
            [tenant], [key], [int(count)], tenant_capacity,
            tenant_fill_rate_per_sec, capacity, fill_rate_per_sec)
        return res[0]

    def acquire_hierarchical_blocking(self, tenant, key, count,
                                      tenant_capacity,
                                      tenant_fill_rate_per_sec, capacity,
                                      fill_rate_per_sec, *, priority=0):
        if not self._hier_fused_supported():
            return super().acquire_hierarchical_blocking(
                tenant, key, count, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority=priority)
        return self.acquire_hierarchical_many_blocking(
            [tenant], [key], [int(count)], tenant_capacity,
            tenant_fill_rate_per_sec, capacity, fill_rate_per_sec)[0]

    async def acquire_hierarchical_many(self, tenants, keys, counts,
                                        tenant_capacity,
                                        tenant_fill_rate_per_sec,
                                        capacity, fill_rate_per_sec, *,
                                        with_remaining: bool = True,
                                        priority: int = 0):
        await self.connect()
        if not self._hier_fused_supported():
            return await BucketStore.acquire_hierarchical_many(
                self, tenants, keys, counts, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                with_remaining=with_remaining, priority=priority)
        counts_np = np.asarray(counts, np.int64)
        outs = self._hier_dispatch(tenants, keys, counts_np,
                                   tenant_capacity,
                                   tenant_fill_rate_per_sec,
                                   capacity, fill_rate_per_sec)
        loop = asyncio.get_running_loop()
        res = await loop.run_in_executor(
            None, lambda: self._hier_gather(outs, len(keys),
                                            with_remaining))
        _grant_zero_probes(res.granted, counts_np)
        return res

    def acquire_hierarchical_many_blocking(self, tenants, keys, counts,
                                           tenant_capacity,
                                           tenant_fill_rate_per_sec,
                                           capacity, fill_rate_per_sec,
                                           *, with_remaining: bool = True,
                                           priority: int = 0):
        if not self._hier_fused_supported():
            return BucketStore.acquire_hierarchical_many_blocking(
                self, tenants, keys, counts, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                with_remaining=with_remaining, priority=priority)
        counts_np = np.asarray(counts, np.int64)
        outs = self._hier_dispatch(tenants, keys, counts_np,
                                   tenant_capacity,
                                   tenant_fill_rate_per_sec,
                                   capacity, fill_rate_per_sec)
        res = self._hier_gather(outs, len(keys), with_remaining)
        _grant_zero_probes(res.granted, counts_np)
        return res

    # -- concurrency semaphore ---------------------------------------------
    def _sema_slot(self, key: str) -> int:
        with self._lock:
            return int(_resolve_with_reclaim(
                self._sema_dir, [key],
                lambda pinned: self._sweep_semas(),
                self._grow_semas,
            )[0])

    def _sweep_semas(self) -> None:
        with self.profiler.span("sweep_semas", self._semas.active.shape[0]):
            self._semas, freed = K.sweep_semas(
                self._semas, jnp.int32(self.clock.now_ticks())
            )
            freed_np = np.asarray(freed)
            if freed_np.any():
                dead = np.nonzero(freed_np)[0].astype(np.int32)
                self.metrics.slots_evicted += self._sema_dir.remove_slots(dead)
            self.metrics.sweeps += 1

    def _grow_semas(self) -> None:
        old_n = self._semas.active.shape[0]
        self._semas = K.SemaState(
            active=jnp.concatenate([self._semas.active, jnp.zeros((old_n,), jnp.int32)]),
            last_ts=jnp.concatenate([self._semas.last_ts, jnp.zeros((old_n,), jnp.int32)]),
            exists=jnp.concatenate([self._semas.exists, jnp.zeros((old_n,), bool)]),
        )
        self._sema_dir.add_slots(old_n, old_n * 2)

    def _sema_dispatch(self, key: str, delta: int, limit: int):
        if delta <= 0:
            # Read-only probe — and release of an unknown key (a spurious
            # or buggy double-release): neither may allocate a directory
            # slot; a nothing-to-release no-op beats a dead slot lingering
            # for the full TTL.
            with self._lock:
                slot = self._sema_dir.lookup(key)
            if slot is None:
                return None  # unknown key ⇒ zero held
        else:
            slot = self._sema_slot(key)
        with self.profiler.span("sema"), self._lock:
            b = _pad_size(1, floor=8)
            packed = np.full((4, b), -1, np.int32)
            packed[1] = 0
            packed[2] = 0
            packed[0, 0] = slot
            packed[1, 0] = delta
            packed[2, 0] = limit
            packed[3] = self.now_ticks_checked()
            self._semas, out = K.sema_batch_packed(
                self._semas, jnp.asarray(packed)
            )
            return out

    async def concurrency_acquire(self, key: str, count: int,
                                  limit: int) -> AcquireResult:
        await self.connect()
        out = self._sema_dispatch(key, count, limit)
        if out is None:  # probe of an unknown key: zero permits held
            return AcquireResult(True, 0.0)
        loop = asyncio.get_running_loop()
        out_np = await loop.run_in_executor(None, lambda: np.asarray(out))
        return AcquireResult(bool(out_np[0, 0] > 0.5), float(out_np[1, 0]))

    def concurrency_acquire_blocking(self, key: str, count: int,
                                     limit: int) -> AcquireResult:
        out = self._sema_dispatch(key, count, limit)
        if out is None:
            return AcquireResult(True, 0.0)
        out_np = np.asarray(out)
        return AcquireResult(bool(out_np[0, 0] > 0.5), float(out_np[1, 0]))

    async def concurrency_release(self, key: str, count: int) -> None:
        out = self._sema_dispatch(key, -count, 0)
        if out is None:  # unknown key: nothing to release
            return
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, lambda: np.asarray(out))

    def concurrency_release_blocking(self, key: str, count: int) -> None:
        out = self._sema_dispatch(key, -count, 0)
        if out is not None:
            np.asarray(out)

    async def concurrency_acquire_many(self, keys, deltas, limit):
        """Packed-kernel bulk semaphore ops: one ``sema_batch_packed``
        dispatch per 4096-row chunk (chunks run in request order on the
        donated state, so cross-chunk duplicates stay serialized).
        Acquire rows resolve-with-allocate; probe/release rows look up
        only — an unknown key answers (True, 0.0) host-side, never
        allocating (same contract as the scalar path).

        Same-key rows that MIX a release with anything else bypass the
        packed dispatch and run as sequential single-op dispatches: the
        kernel clamps a slot's net batch delta at zero, which would let
        an over-release swallow a granted acquire's permit (per-op
        semantics must survive over-release, not amplify it)."""
        await self.connect()
        n = len(keys)
        deltas_np = np.asarray(deltas, np.int64)
        limits_np = self._sema_limits(limit, n)
        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32)
        slots = np.full(n, -1, np.int64)
        acq_idx = np.nonzero(deltas_np > 0)[0]
        other_idx = np.nonzero(deltas_np <= 0)[0]
        # Mixed-sign duplicate hazard: keys with a release AND ≥2 rows.
        # Vectorized — releases are ~half of steady-state sema traffic,
        # so this branch runs on most flushes and must not reintroduce
        # per-request Python into the per-flush path.
        if (deltas_np < 0).any():
            uniq_inv = np.unique(np.asarray(keys, object),
                                 return_inverse=True, return_counts=True)
            _, inv, cnt = uniq_inv
            rel_key = np.zeros(len(cnt), bool)
            rel_key[inv[deltas_np < 0]] = True
            hazard = rel_key[inv] & (cnt[inv] > 1)
        else:
            hazard = np.zeros(n, bool)
        outs = []
        with self.profiler.span("sema_bulk", n), self._lock:
            if len(acq_idx):
                resolved = _resolve_with_reclaim(
                    self._sema_dir, [keys[i] for i in acq_idx.tolist()],
                    lambda pinned: self._sweep_semas(), self._grow_semas)
                slots[acq_idx] = np.asarray(resolved, np.int64)
            for i in other_idx.tolist():
                s = self._sema_dir.lookup(keys[i])
                slots[i] = -1 if s is None else s
            known = slots >= 0
            granted[~known] = True  # unknown-key probe/release: no-op OK
            idx_known = np.nonzero(known & ~hazard)[0]
            for s0 in range(0, len(idx_known), 4096):
                sub = idx_known[s0:s0 + 4096]
                b = _pad_size(len(sub), floor=8)
                packed = np.full((4, b), -1, np.int32)
                packed[1] = 0
                packed[2] = 0
                packed[0, :len(sub)] = slots[sub]
                packed[1, :len(sub)] = deltas_np[sub]
                packed[2, :len(sub)] = limits_np[sub]
                packed[3] = self.now_ticks_checked()
                self._semas, out = K.sema_batch_packed(
                    self._semas, jnp.asarray(packed))
                outs.append((sub, out))
            for i in np.nonzero(known & hazard)[0].tolist():
                d = int(deltas_np[i])
                # Mirror the scalar entry points: acquires and probes
                # carry the row's limit, releases carry 0 (ignored).
                out = self._sema_dispatch(keys[i], d,
                                          int(limits_np[i]) if d >= 0
                                          else 0)
                if out is None:
                    # Key vanished between the top-of-call lookup and
                    # this row (an interleaved acquire row's resolve can
                    # sweep a zero-held stale slot): same contract as
                    # the scalar path — unknown-key release/probe is a
                    # successful no-op.
                    granted[i] = True
                    remaining[i] = 0.0
                else:
                    outs.append((np.array([i]), out))
        loop = asyncio.get_running_loop()
        for sub, out in outs:
            out_np = await loop.run_in_executor(
                None, lambda o=out: np.asarray(o))
            m = len(sub)
            granted[sub] = out_np[0, :m] > 0.5
            remaining[sub] = np.where(deltas_np[sub] < 0, 0.0,
                                      out_np[1, :m])
        return BulkAcquireResult(granted, remaining)

    # -- sliding window ----------------------------------------------------
    async def window_acquire(self, key: str, count: int, limit: float,
                             window_sec: float) -> AcquireResult:
        await self.connect()
        table = self._wtable(limit, window_sec)
        return await table.batcher.submit(_AcquireReq(key, count))

    def window_acquire_blocking(self, key: str, count: int, limit: float,
                                window_sec: float) -> AcquireResult:
        return self._wtable(limit, window_sec).acquire_blocking(key, count)

    # -- fixed window ------------------------------------------------------
    async def fixed_window_acquire(self, key: str, count: int, limit: float,
                                   window_sec: float) -> AcquireResult:
        await self.connect()
        table = self._wtable(limit, window_sec, fixed=True)
        return await table.batcher.submit(_AcquireReq(key, count))

    def fixed_window_acquire_blocking(self, key: str, count: int,
                                      limit: float,
                                      window_sec: float) -> AcquireResult:
        return self._wtable(limit, window_sec,
                            fixed=True).acquire_blocking(key, count)

    # -- TTL maintenance ---------------------------------------------------
    def sweep_all(self) -> None:
        """One TTL-eviction pass over every table (buckets, windows,
        counters). On-demand sweeps already run on allocation pressure
        (invariant 5); this is the *active* expiry pass — Redis's
        background expiration cycle — so an idle store's memory shrinks
        without waiting for the next allocation to force it."""
        with self._lock:
            for t in list(self._tables.values()):
                t._sweep()
            for wt in list(self._wtables.values()):
                wt._sweep()
            self._sweep_counters()
            self._sweep_semas()

    def start_sweeper(self, period_s: float = 30.0) -> None:
        """Start the periodic active-expiry task on the running event loop
        (idempotent). Stops automatically in :meth:`aclose`."""
        if self._sweeper_task is not None and not self._sweeper_task.done():
            return
        self._sweeper_task = start_periodic_sweeper(self.sweep_all, period_s)

    # -- lifecycle / ops ---------------------------------------------------
    async def aclose(self) -> None:
        if self._sweeper_task is not None:
            self._sweeper_task.cancel()
            try:
                await self._sweeper_task
            except (asyncio.CancelledError, Exception):
                pass  # a failed sweeper must not abort batcher cleanup
            self._sweeper_task = None
        for t in self._tables.values():
            await t.batcher.aclose()
        for t in self._wtables.values():
            await t.batcher.aclose()
        # Join until no live warm threads remain: a bulk acquire running
        # concurrently with this aclose can spawn a NEW pregrow thread
        # after any one-shot snapshot — discard only what was joined.
        while True:
            live = [t for t in self._bg_threads if t.is_alive()]
            if not live:
                break
            for t in live:
                await asyncio.to_thread(t.join, 120.0)
            self._bg_threads.difference_update(live)
        self._bg_threads.clear()  # drop finished-thread references

    # -- dirty accounting (incremental checkpoints; OPERATIONS.md §10) ------
    def enable_dirty_tracking(self) -> None:
        """Arm per-table dirty accounting: between two saves, every
        launched-upon slot is counted, so OP_STATS can report the
        dirty/total ratio that predicts the next v4 delta's size
        (runtime/checkpoint.py). Observability only — the delta itself
        is a structural diff, correct with or without this. Counter and
        semaphore tiers are deliberately untracked: their state is a
        handful of fixed arrays, noise next to the key tables."""
        with self._lock:
            self._dirty_tracking = True
            for t in (*self._tables.values(), *self._wtables.values()):
                _arm_dirty(t)

    def mark_snapshot_base(self) -> None:
        """Start a fresh dirty window (called by the checkpoint chain at
        every save — the window is save-to-save)."""
        with self._lock:
            for t in (*self._tables.values(), *self._wtables.values()):
                _dirty_clear(t)

    def dirty_stats(self) -> dict:
        with self._lock:
            dirty = total = 0
            for t in (*self._tables.values(), *self._wtables.values()):
                dirty += _dirty_count(t)
                total += t.n_slots
            return {"dirty": dirty, "total": total}

    def snapshot(self) -> dict:
        """Pull all live state to host (planned-restart checkpoint).
        ``now_ticks`` is captured so restore into a *different* process
        (fresh clock epoch) can re-align every timestamp."""
        with self._lock:
            tables = {}
            for (cap, rate), t in self._tables.items():
                tables[(cap, rate)] = t.to_snap()
            wtables = {}
            for (limit, wt, fixed), t in self._wtables.items():
                wtables[(limit, wt, fixed)] = t.to_snap()
            return {
                "now_ticks": self.clock.now_ticks(),
                "tables": tables,
                "wtables": wtables,
                "counter_dir": self._counter_dir.to_dict(),
                "counters": {
                    "value": np.asarray(self._counters.value),
                    "period": np.asarray(self._counters.period),
                    "last_ts": np.asarray(self._counters.last_ts),
                    "exists": np.asarray(self._counters.exists),
                },
                "sema_dir": self._sema_dir.to_dict(),
                "semas": {
                    "active": np.asarray(self._semas.active),
                    "last_ts": np.asarray(self._semas.last_ts),
                    "exists": np.asarray(self._semas.exists),
                },
            }

    def restore(self, snap: dict) -> None:
        """Restore a checkpoint, re-aligning timestamps to THIS process's
        clock epoch: elapsed-since-touch is preserved by shifting every
        stored timestamp by ``now_here − now_at_snapshot`` (without this, a
        restore into a fresh process would clamp all elapsed time to zero
        and restored buckets would stop refilling)."""
        with self._lock:
            shift = int(self.clock.now_ticks()) - int(snap["now_ticks"])
            for (cap, rate), data in snap["tables"].items():
                self._table(cap, rate).load_snap(data, shift)
            for wkey, data in snap.get("wtables", {}).items():
                # Pre-fixed-window snapshots carry 2-tuple keys (sliding).
                limit, wt = wkey[0], wkey[1]
                fixed = wkey[2] if len(wkey) > 2 else False
                self._wtable(limit, wt / bm.TICKS_PER_SECOND,
                             fixed).load_snap(data, shift)
            c = snap["counters"]
            self._counters = K.CounterState(
                value=jnp.asarray(c["value"]),
                period=jnp.asarray(c["period"]),
                last_ts=jnp.asarray(_shift_ts(c["last_ts"], shift)),
                exists=jnp.asarray(c["exists"]),
            )
            self._counter_dir.load(snap["counter_dir"],
                                   self._counters.value.shape[0])
            if "semas" in snap:  # absent in pre-semaphore checkpoints
                s = snap["semas"]
                self._semas = K.SemaState(
                    active=jnp.asarray(s["active"]),
                    last_ts=jnp.asarray(_shift_ts(s["last_ts"], shift)),
                    exists=jnp.asarray(s["exists"]),
                )
                self._sema_dir.load(snap["sema_dir"],
                                    self._semas.active.shape[0])


# Table classes are defined after DeviceBucketStore, so the bindings
# land here (subclasses override the attributes, not the methods).
DeviceBucketStore._TABLE_CLS = _DeviceTable
DeviceBucketStore._WTABLE_CLS = _DeviceWindowTable


class InProcessBucketStore(BucketStore):
    """Pure-Python store with identical semantics, executed serially per
    request — the test fake and the Redis-class CPU baseline (one 'script'
    per op, no batching)."""

    def __init__(self, clock: Clock | None = None) -> None:
        self.clock = clock or MonotonicClock()
        self._buckets: dict[tuple, tuple[float, int]] = {}   # (tokens, ts)
        self._counters: dict[str, tuple[float, float, int]] = {}  # (v, p, ts)
        self._windows: dict[tuple, tuple[float, float, int]] = {}
        self._semas: dict[str, int] = {}                     # active permits
        self._connected = False
        # Dirty-key accounting for incremental checkpoints (OPERATIONS.md
        # §10) — None (one falsy check per write) until armed.
        self._dirty: "set | None" = None

    async def connect(self) -> None:
        self._connected = True

    def _acquire_core(self, key, count, capacity, rate_per_sec) -> AcquireResult:
        now = self.clock.now_ticks()
        rate = _rate_per_tick(rate_per_sec)
        bkey = (key, float(capacity), float(rate_per_sec))
        entry = self._buckets.get(bkey)
        if entry is None:
            refilled = float(capacity)
        else:
            tokens, ts = entry
            refilled = min(float(capacity), tokens + max(0, now - ts) * rate)
        granted = refilled >= count
        self._buckets[bkey] = (refilled - (count if granted else 0), now)
        if self._dirty is not None:
            self._dirty.add(bkey)
        return AcquireResult(granted, self._buckets[bkey][0])

    async def acquire(self, key, count, capacity, fill_rate_per_sec):
        await self.connect()
        return self._acquire_core(key, count, capacity, fill_rate_per_sec)

    def acquire_blocking(self, key, count, capacity, fill_rate_per_sec):
        return self._acquire_core(key, count, capacity, fill_rate_per_sec)

    async def acquire_many(self, keys, counts, capacity, fill_rate_per_sec,
                           *, with_remaining: bool = True):
        """Serial-core bulk: one in-order pass over the batch with NO
        task-per-key (the base class's gather spends ~10µs/key on task
        scheduling — measurable when this store backs the native front-end
        as the zero-cost-kernel stand-in). Still awaits ``self.acquire``
        per key: the per-key method stays the single override point for
        test fakes and subclasses, and awaiting a non-suspending
        coroutine costs no loop round trip."""
        await self.connect()
        n = len(keys)
        granted = np.empty(n, bool)
        remaining = np.empty(n, np.float32) if with_remaining else None
        # Direct-core loop only when per-key acquire is NOT overridden:
        # subclasses/test fakes that intercept acquire() must see every
        # bulk key too (a coroutine frame per key costs ~20% on the
        # native-front-end stand-in, so the unsubclassed store skips it).
        direct = type(self).acquire is InProcessBucketStore.acquire
        for i, (k, c) in enumerate(zip(keys, counts)):
            r = (self._acquire_core(k, int(c), capacity, fill_rate_per_sec)
                 if direct else
                 await self.acquire(k, int(c), capacity, fill_rate_per_sec))
            granted[i] = r.granted
            if remaining is not None:
                remaining[i] = r.remaining
        return BulkAcquireResult(granted, remaining)

    async def window_acquire_many(self, keys, counts, limit, window_sec, *,
                                  fixed: bool = False,
                                  with_remaining: bool = True):
        await self.connect()
        op = (self.fixed_window_acquire if fixed else self.window_acquire)
        n = len(keys)
        granted = np.empty(n, bool)
        remaining = np.empty(n, np.float32) if with_remaining else None
        for i, (k, c) in enumerate(zip(keys, counts)):
            r = await op(k, int(c), limit, window_sec)
            granted[i] = r.granted
            if remaining is not None:
                remaining[i] = r.remaining
        return BulkAcquireResult(granted, remaining)

    def peek_blocking(self, key, capacity, fill_rate_per_sec):
        now = self.clock.now_ticks()
        bkey = (key, float(capacity), float(fill_rate_per_sec))
        entry = self._buckets.get(bkey)
        if entry is None:
            return float(np.floor(capacity))
        tokens, ts = entry
        rate = _rate_per_tick(fill_rate_per_sec)
        return float(np.floor(min(float(capacity), tokens + max(0, now - ts) * rate)))

    async def debit_many(self, keys, amounts, capacity, fill_rate_per_sec):
        """Serial saturating debit — identical semantics to the device
        kernel (:func:`~.ops.kernels.debit_batch_packed`): refill, then
        subtract clamped at zero, reporting the clamped shortfall."""
        now = self.clock.now_ticks()
        rate = _rate_per_tick(fill_rate_per_sec)
        n = len(keys)
        remaining = np.empty(n, np.float64)
        shortfall = np.empty(n, np.float64)
        for i, (k, amt) in enumerate(zip(keys, amounts)):
            amt = float(amt)
            bkey = (k, float(capacity), float(fill_rate_per_sec))
            entry = self._buckets.get(bkey)
            if entry is None:
                refilled = float(capacity)
            else:
                tokens, ts = entry
                refilled = min(float(capacity),
                               tokens + max(0, now - ts) * rate)
            applied = min(amt, max(refilled, 0.0))
            self._buckets[bkey] = (refilled - applied, now)
            if self._dirty is not None:
                self._dirty.add(bkey)
            remaining[i] = refilled - applied
            shortfall[i] = amt - applied
        return remaining, shortfall

    # -- hierarchical tenant → key admission (exact serial core) -----------
    def _hier_refill(self, bkey: tuple, capacity: float,
                     rate_per_sec: float, now: int) -> float:
        entry = self._buckets.get(bkey)
        if entry is None:
            return float(capacity)
        tokens, ts = entry
        rate = _rate_per_tick(rate_per_sec)
        return min(float(capacity), tokens + max(0, now - ts) * rate)

    def _hier_core(self, tenant, key, count, tcap, trate, cap, rate
                   ) -> AcquireResult:
        """Atomic two-level decision — the serial reference the fused
        kernel (:func:`~.ops.kernels.acquire_hierarchical_packed`) is
        differential-tested against: refill both levels, grant iff
        both cover ``count``, debit both-or-neither, advance BOTH
        timestamps either way (a denied request leaves each bucket
        exactly as a refill-only touch would — the refund contract,
        closed algebraically)."""
        check_hierarchical_args(count, tcap, trate, cap, rate)
        now = self.clock.now_ticks()
        ckey = (key, float(cap), float(rate))
        pkey = (tenant, float(tcap), float(trate))
        c_ref = self._hier_refill(ckey, cap, rate, now)
        p_ref = self._hier_refill(pkey, tcap, trate, now)
        granted = c_ref >= count and p_ref >= count
        spend = count if granted else 0
        self._buckets[ckey] = (c_ref - spend, now)
        self._buckets[pkey] = (p_ref - spend, now)
        if self._dirty is not None:
            self._dirty.add(ckey)
            self._dirty.add(pkey)
        return AcquireResult(granted,
                             min(c_ref - spend, p_ref - spend))

    async def acquire_hierarchical(self, tenant, key, count,
                                   tenant_capacity,
                                   tenant_fill_rate_per_sec, capacity,
                                   fill_rate_per_sec, *, priority=0):
        await self.connect()
        return self._hier_core(tenant, key, int(count), tenant_capacity,
                               tenant_fill_rate_per_sec, capacity,
                               fill_rate_per_sec)

    def acquire_hierarchical_blocking(self, tenant, key, count,
                                      tenant_capacity,
                                      tenant_fill_rate_per_sec, capacity,
                                      fill_rate_per_sec, *, priority=0):
        return self._hier_core(tenant, key, int(count), tenant_capacity,
                               tenant_fill_rate_per_sec, capacity,
                               fill_rate_per_sec)

    async def acquire_hierarchical_many(self, tenants, keys, counts,
                                        tenant_capacity,
                                        tenant_fill_rate_per_sec,
                                        capacity, fill_rate_per_sec, *,
                                        with_remaining: bool = True,
                                        priority: int = 0):
        """Serial-core bulk: one in-order pass, no per-row coroutine —
        the per-row cost stays within 2× of the flat serial core (one
        extra dict round per row), which is the llm_workload bench's
        hierarchical-overhead contract on the in-memory backing."""
        await self.connect()
        return self.acquire_hierarchical_many_blocking(
            tenants, keys, counts, tenant_capacity,
            tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
            with_remaining=with_remaining, priority=priority)

    def acquire_hierarchical_many_blocking(self, tenants, keys, counts,
                                           tenant_capacity,
                                           tenant_fill_rate_per_sec,
                                           capacity, fill_rate_per_sec,
                                           *, with_remaining: bool = True,
                                           priority: int = 0):
        n = len(keys)
        granted = np.empty(n, bool)
        remaining = np.empty(n, np.float32) if with_remaining else None
        core = self._hier_core
        for i in range(n):
            r = core(tenants[i], keys[i], int(counts[i]),
                     tenant_capacity, tenant_fill_rate_per_sec,
                     capacity, fill_rate_per_sec)
            granted[i] = r.granted
            if remaining is not None:
                remaining[i] = r.remaining
        return BulkAcquireResult(granted, remaining)

    async def sync_counter(self, key, local_count, decay_rate_per_sec):
        return self.sync_counter_blocking(key, local_count, decay_rate_per_sec)

    def sync_counter_blocking(self, key, local_count, decay_rate_per_sec):
        now = self.clock.now_ticks()
        rate = _rate_per_tick(decay_rate_per_sec)
        entry = self._counters.get(key)
        if entry is None:
            v, p = float(local_count), float(now)
        else:
            v0, p0, ts = entry
            delta = max(0, now - ts)
            v = max(0.0, v0 - delta * rate) + local_count
            p = (1 - bm.PERIOD_EWMA_ALPHA) * p0 + bm.PERIOD_EWMA_ALPHA * delta
        self._counters[key] = (v, p, now)
        if self._dirty is not None:
            self._dirty.add(key)
        return SyncResult(v, p)

    async def concurrency_acquire(self, key, count, limit):
        return self.concurrency_acquire_blocking(key, count, limit)

    def concurrency_acquire_blocking(self, key, count, limit):
        active = self._semas.get(key, 0)
        if active + count <= limit:
            if count > 0:  # count == 0 is a read-only probe
                self._semas[key] = active + count
                if self._dirty is not None:
                    self._dirty.add(key)
            return AcquireResult(True, float(active + count))
        return AcquireResult(False, float(active))

    async def concurrency_release(self, key, count):
        self.concurrency_release_blocking(key, count)

    def concurrency_release_blocking(self, key, count):
        if key not in self._semas:
            return  # unknown key: nothing to release, create nothing
        self._semas[key] = max(0, self._semas[key] - count)
        if self._dirty is not None:
            self._dirty.add(key)

    async def window_acquire(self, key, count, limit, window_sec):
        return self.window_acquire_blocking(key, count, limit, window_sec)

    def window_acquire_blocking(self, key, count, limit, window_sec):
        return self._window_core(key, count, limit, window_sec,
                                 interpolate=True)

    async def fixed_window_acquire(self, key, count, limit, window_sec):
        return self._window_core(key, count, limit, window_sec,
                                 interpolate=False)

    def fixed_window_acquire_blocking(self, key, count, limit, window_sec):
        return self._window_core(key, count, limit, window_sec,
                                 interpolate=False)

    def _window_core(self, key, count, limit, window_sec, *, interpolate):
        now = self.clock.now_ticks()
        wt = int(window_sec * bm.TICKS_PER_SECOND)
        wkey = (key, float(limit), wt, interpolate)
        entry = self._windows.get(wkey)
        idx_now = now // wt
        if entry is None:
            prev = curr = 0.0
        else:
            prev, curr, idx = entry
            steps = idx_now - idx
            if steps == 1:
                prev, curr = curr, 0.0
            elif steps >= 2:
                prev = curr = 0.0
        if interpolate:
            frac = (now - idx_now * wt) / wt
            est = curr + prev * (1.0 - frac)
        else:
            est = curr
        granted = est + count <= limit
        if granted:
            curr += count
        self._windows[wkey] = (prev, curr, idx_now)
        if self._dirty is not None:
            self._dirty.add(wkey)
        return AcquireResult(granted, max(0.0, limit - est - (count if granted else 0)))

    async def aclose(self) -> None:
        pass

    async def import_entries(self, entries: dict) -> int:
        """Exact merge lane for migration handoffs (the generic replay
        in :func:`placement.import_entries` is for stores whose state
        only the kernels can write). Conservative on collisions — a
        re-pushed batch or pre-existing local state must never inflate
        a budget: buckets keep the smaller balance, windows sum their
        counts (clamped to the limit), counters and semaphores keep the
        larger value."""
        now = self.clock.now_ticks()
        n = 0
        for key, cap, rate, tokens, age in entries.get("buckets", ()):
            bkey = (key, float(cap), float(rate))
            ts = now - int(age)
            entry = self._buckets.get(bkey)
            if entry is None:
                self._buckets[bkey] = (float(tokens), ts)
            else:
                self._buckets[bkey] = (min(entry[0], float(tokens)),
                                       max(entry[1], ts))
            if self._dirty is not None:
                self._dirty.add(bkey)
            n += 1
        for key, limit, wt, interp, prev, curr, behind in \
                entries.get("windows", ()):
            wkey = (key, float(limit), int(wt), bool(interp))
            idx = now // int(wt) - int(behind)
            entry = self._windows.get(wkey)
            if entry is None or entry[2] < idx:
                # no local state, or the LOCAL entry is the stale one
                # (an earlier epoch's leftovers): the push wins outright
                self._windows[wkey] = (float(prev), float(curr), idx)
            elif entry[2] == idx:
                self._windows[wkey] = (
                    min(float(limit), entry[0] + float(prev)),
                    min(float(limit), entry[1] + float(curr)), idx)
            # a stale PUSHED window (older idx) carries no usage to keep
            if self._dirty is not None:
                self._dirty.add(wkey)
            n += 1
        for key, value, period, age in entries.get("counters", ()):
            entry = self._counters.get(key)
            if entry is None or entry[0] < value:
                self._counters[key] = (float(value), float(period),
                                       now - int(age))
            if self._dirty is not None:
                self._dirty.add(key)
            n += 1
        for key, active in entries.get("semas", ()):
            self._semas[key] = max(self._semas.get(key, 0), int(active))
            if self._dirty is not None:
                self._dirty.add(key)
            n += 1
        return n

    # -- dirty accounting (incremental checkpoints; OPERATIONS.md §10) ------
    def enable_dirty_tracking(self) -> None:
        """Arm exact per-entry dirty accounting (the device store's
        counterpart tracks slots): between two saves every written entry
        key is counted, so OP_STATS reports the dirty/total ratio that
        predicts the next v4 delta's size. Observability only — the
        delta is a structural diff either way."""
        if self._dirty is None:
            self._dirty = set()

    def mark_snapshot_base(self) -> None:
        if self._dirty is not None:
            self._dirty.clear()

    def dirty_stats(self) -> dict:
        return {"dirty": len(self._dirty or ()),
                "total": (len(self._buckets) + len(self._windows)
                          + len(self._counters) + len(self._semas))}

    def snapshot(self) -> dict:
        return {
            "now_ticks": self.clock.now_ticks(),
            "buckets": dict(self._buckets),
            "counters": dict(self._counters),
            "windows": dict(self._windows),
            "semas": dict(self._semas),
        }

    def restore(self, snap: dict) -> None:
        """Same clock-epoch re-alignment as the device store: stored
        timestamps shift by ``now_here − now_at_snapshot`` so elapsed time
        (refill/decay) survives a restore into a fresh process."""
        # Snapshots from before the epoch field behave as same-process.
        shift = (int(self.clock.now_ticks()) - int(snap["now_ticks"])
                 if "now_ticks" in snap else 0)
        self._buckets = {
            k: (tokens, ts + shift)
            for k, (tokens, ts) in snap["buckets"].items()
        }
        self._counters = {
            k: (v, p, ts + shift)
            for k, (v, p, ts) in snap["counters"].items()
        }
        # Pre-fixed-window snapshots carry 3-tuple window keys (sliding);
        # normalize to the 4-tuple (key, limit, wt, interpolate=True).
        self._windows = {
            (k if len(k) == 4 else (*k, True)): (prev, curr, idx + shift // k[2])
            for k, (prev, curr, idx) in snap["windows"].items()
        }
        self._semas = dict(snap.get("semas", {}))  # counts are epoch-free
