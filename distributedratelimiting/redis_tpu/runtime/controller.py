"""Autonomous control plane — the reconciliation loop that closes the
sensor→actuator gap (ROADMAP item 2).

Every actuator in this repo already exists: ``add_node`` / ``drain_node``
/ ``rejoin_node`` / ``rebalance`` / ``split_hot_keys`` (the membership
plane, PR 6), live ``OP_CONFIG`` limit mutation (PR 7), and the
``shed_level`` brownout knob on the admission gateway (PR 9). Every
sensor exists too: per-tenant ``drl_token_velocity`` and its monotonic
``admitted`` companion, the cost-weighted heavy-hitter sketch, per-stage
latency histograms, breaker/shed counters. What "TokenScale" (PAPERS.md)
argues — the token-velocity signal is precisely what should drive
scaling and shedding — and what "Designing Scalable Rate Limiting
Systems" names as the frontier past static topologies, is the LOOP:
until now an operator read the metrics and called the methods by hand.

:class:`Controller` is that loop. On a fixed tick it:

1. **Scrapes** the fleet's own observability plane —
   ``ClusterBucketStore.stats()``, the OP_STATS fan-out that carries the
   same counters the OpenMetrics families render (the series it
   subscribes to are declared in :data:`SENSOR_SERIES` and statically
   checked against the emitting registries by drl-check's
   ``metric-name`` rule).
2. **Derives rates from monotonic counter deltas**
   (:class:`~..utils.metrics.CounterDeltas`) — never ``reset=True``:
   the operator's measurement windows stay intact, any number of
   concurrent scrapers compose, and — the determinism contract — the
   derived rates are a pure function of the traffic schedule, not of
   when the scrape happened to land.
3. **Decides** through per-actuator hysteresis (a threshold must hold
   for N consecutive ticks), per-actuator cooldown windows, and a
   global rolling actuation budget — the three flap guards; a decision
   starved by the budget is still logged (outcome
   ``budget_exhausted``), never silently dropped.
4. **Actuates** through the same health-gated, ``_membership_lock``-
   serialized paths an operator would call: ``split_hot_keys`` for
   hot-COST shards (sketch-fed), ``rebalance`` on slot-ownership
   imbalance, ``drain_node``/``rejoin_node`` on sustained breaker
   state, and the shed ladder (``None → scavenger → batch``, never
   interactive) pushed to every attached admission gateway.

Every decision lands as a structured flight-recorder frame
(``kind="controller"``), a bounded action-log entry (:attr:`Controller.
actions`, ``migration_log`` posture: newest 512), a structured log
event (id 6), and the ``drl_controller_*`` metric families — the loop
is fully auditable after the fact. ``dry_run=True`` decides IDENTICALLY
(all gating state — streaks, cooldowns, budget, the decided shed level
— evolves exactly as live) but executes nothing: the recommended first
rollout posture (docs/OPERATIONS.md §13).

**Determinism.** ``decide`` consumes only the sensor snapshot and the
controller's own state; ticks are counted, not clocked; there is no
randomness. Driven by a seeded traffic schedule (the diurnal +
flash-crowd soak in tests/test_controller.py), the same seed produces
the same action schedule bit for bit. The chaos plane participates
through the ``controller.tick`` seam (utils/faults.py): an injected
fault fails that tick loudly (counted + frame), and the seeded fault
schedule keeps the failure pattern reproducible too.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Callable, Sequence

from distributedratelimiting.redis_tpu.runtime.admission import (
    PRIORITY_BATCH,
    PRIORITY_SCAVENGER,
)
from distributedratelimiting.redis_tpu.utils import faults, log
from distributedratelimiting.redis_tpu.utils.metrics import CounterDeltas

__all__ = ["Controller", "ControllerConfig", "SENSOR_SERIES"]

#: The controller's sensor contract: every OpenMetrics series name the
#: reconciliation loop subscribes to (through the OP_STATS fan-out that
#: carries the same counters). drl-check's ``metric-name`` rule holds
#: each of these to a registration site in the registry that emits it —
#: a rename on the emitting side is a failed ``make check``, not a
#: silently blinded sensor.
SENSOR_SERIES = (
    "drl_requests_served",        # server.py — per-node load (rate via deltas)
    "drl_admitted_tokens",        # server.py — fleet token-pressure numerator
    "drl_token_velocity",         # server.py — per-tenant decayed tokens/sec
    "drl_hot_key_count",          # server.py — cost-weighted top-K sketch
    "drl_requests_shed",          # server.py — shed feedback
    "drl_reservations_outstanding",  # server.py — unsettled reserved tokens
    "drl_cluster_breaker_state",  # cluster.py — membership health
    "drl_cluster_node_errors",    # cluster.py — node failure counters
    "drl_federation_outstanding_leases",  # server.py — home lease count
    "drl_federation_region_degraded_now",  # server.py — slices serving
    # their degraded envelope (the partition symptom the federation
    # actuator reacts to between its cadence renews)
    "drl_audit_breaches",         # server.py — conservation-identity
    # violations observed by the audit plane (runtime/audit.py)
    "drl_slo_alerts",             # server.py — burn-rate watchdog
    # trip/clear transitions (utils/slo.py)
    "drl_retry_attempts_seen",    # server.py — attempt-tail-stamped
    # admissions (the retry-storm numerator; docs/DESIGN.md §24)
    "drl_goodput_settled_in_deadline",  # server.py — settles inside
    # the propagated deadline (the goodput numerator)
    "drl_goodput_deadline_expired_grants",  # server.py — grants whose
    # deadline passed before settle: admitted-but-doomed work, the
    # sensor that arms the doomed-work gate
)


@dataclass(frozen=True)
class ControllerConfig:
    """Knobs of one reconciliation loop (docs/OPERATIONS.md §13).

    Thresholds come in (high, low) pairs with distinct raise/release
    streak lengths — classic hysteresis, so a signal hovering AT a
    threshold can never flap the actuator. ``cooldown_ticks`` then
    spaces consecutive firings of the same actuator, and the global
    rolling budget (``budget_actions`` per ``budget_window_ticks``)
    bounds total actuation no matter how many actuators want to move.
    """

    #: Reconciliation cadence. Every rate below is a per-second value
    #: derived as ``counter_delta / tick_s``.
    tick_s: float = 0.5

    # -- shed ladder (token pressure → edge brownout) -----------------------
    #: Sustainable fleet admitted-tokens/sec. ``None`` disarms the shed
    #: actuator (the controller then only observes token velocity).
    token_rate_capacity: "float | None" = None
    #: Pressure (= token rate / capacity) at/above which the ladder
    #: steps UP one level after ``shed_raise_ticks`` consecutive ticks.
    shed_high: float = 0.9
    #: Pressure at/below which the ladder steps DOWN after
    #: ``shed_lower_ticks`` consecutive ticks. Must sit strictly below
    #: ``shed_high`` — the gap IS the hysteresis band.
    shed_low: float = 0.6
    shed_raise_ticks: int = 2
    shed_lower_ticks: int = 3
    #: Deepest shed level the controller may reach (priorities at/above
    #: the level shed). PRIORITY_BATCH sheds batch + scavenger;
    #: interactive traffic is NEVER shed autonomously.
    shed_floor: int = PRIORITY_BATCH
    #: Outstanding-reservation horizon: reserved-but-unsettled tokens
    #: (the ``drl_reservations_outstanding`` gauge, summed fleet-wide)
    #: are load that WILL land — fold them into the shed pressure as a
    #: prospective rate, ``outstanding / horizon`` tokens/sec (they are
    #: expected to settle within about one horizon — the reservation
    #: TTL's scale). This is what lets a brownout start BEFORE a wave
    #: of admitted-but-still-streaming requests hits the settled-token
    #: rate. The existing shed hysteresis (raise/lower streaks + the
    #: shed_low/shed_high dead band) guards the combined signal.
    reservation_horizon_s: float = 10.0

    # -- hot-cost key splitting (sketch-fed) --------------------------------
    #: One key's share of the fleet's per-tick admitted-token delta
    #: at/above which it is a split candidate.
    split_share: float = 0.35
    #: Absolute per-tick token-delta floor — idle fleets where one key
    #: is 100% of nothing must not split.
    split_min_tokens: float = 1.0
    split_streak_ticks: int = 2

    # -- slot rebalance -----------------------------------------------------
    #: Slot-count spread over active nodes, ``(max − min) / mean``,
    #: at/above which a rebalance is proposed.
    rebalance_imbalance: float = 0.25
    rebalance_streak_ticks: int = 2

    # -- membership (breaker-driven) ----------------------------------------
    #: Consecutive ticks a node's breaker must be OPEN before the
    #: controller drains it, and CLOSED again before it rejoins one the
    #: controller itself drained (it never rejoins operator drains).
    drain_after_open_ticks: int = 3

    # -- federation (WAN lease agent) ---------------------------------------
    #: Cadence, in ticks, of the federation actuator when a region
    #: agent is attached: every N ticks the controller drives one
    #: ``RegionFederation.tick`` with its per-tenant velocity-delta
    #: rates as the demand report — the demand-proportional slice
    #: sizing signal ("TokenScale"). A degraded slice (partition
    #: symptom in the drl_federation_region_* sensors) fires the
    #: actuator off-cadence, hysteresis-guarded like every other.
    federation_renew_ticks: int = 4
    federation_degraded_streak_ticks: int = 2

    # -- retry-storm defense (goodput under overload) -----------------------
    #: Retries' share of the fleet request rate at/above which the
    #: retry-storm rung arms the retry-shed + doomed-work gates after
    #: ``retry_storm_raise_ticks`` consecutive ticks. This rung sits
    #: BEFORE the priority shed ladder: retries and doomed work shed
    #: before any priority class browns out (docs/DESIGN.md §24).
    retry_storm_high: float = 0.5
    #: Share at/below which the gates release after
    #: ``retry_storm_lower_ticks`` ticks. Must sit strictly below
    #: ``retry_storm_high`` — the gap is the hysteresis band.
    retry_storm_low: float = 0.1
    retry_storm_raise_ticks: int = 2
    retry_storm_lower_ticks: int = 3
    #: Absolute retry-rate floor (attempts/sec): an idle fleet where
    #: one of two requests is a retry must not arm the defense.
    retry_storm_min_rate: float = 1.0

    # -- flap guards ---------------------------------------------------------
    #: Ticks after an actuator fires before the SAME actuator may fire
    #: again (per action kind).
    cooldown_ticks: int = 4
    #: Global rolling actuation budget: at most this many decided
    #: actions per ``budget_window_ticks`` window. Exhaustion is logged
    #: per starved decision, never silent.
    budget_actions: int = 8
    budget_window_ticks: int = 60

    #: Decide identically, execute nothing (log-only rollout posture).
    dry_run: bool = False

    def __post_init__(self) -> None:
        if self.tick_s <= 0:
            raise ValueError("tick_s must be positive")
        if self.token_rate_capacity is not None \
                and self.token_rate_capacity <= 0:
            raise ValueError("token_rate_capacity must be positive")
        if not self.shed_low < self.shed_high:
            raise ValueError("shed_low must sit strictly below shed_high "
                             "(the gap is the hysteresis band)")
        if not self.retry_storm_low < self.retry_storm_high:
            raise ValueError("retry_storm_low must sit strictly below "
                             "retry_storm_high (the gap is the "
                             "hysteresis band)")
        for name in ("shed_raise_ticks", "shed_lower_ticks",
                     "split_streak_ticks", "rebalance_streak_ticks",
                     "drain_after_open_ticks", "budget_actions",
                     "budget_window_ticks", "federation_renew_ticks",
                     "federation_degraded_streak_ticks",
                     "retry_storm_raise_ticks",
                     "retry_storm_lower_ticks"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")
        if self.cooldown_ticks < 0:
            raise ValueError("cooldown_ticks must be >= 0")
        if self.reservation_horizon_s <= 0:
            raise ValueError("reservation_horizon_s must be positive")
        if self.shed_floor < PRIORITY_BATCH:
            raise ValueError("shed_floor below PRIORITY_BATCH would shed "
                             "interactive traffic autonomously — refused")


@dataclass
class Sensors:
    """One tick's derived sensor snapshot — everything ``decide``
    consumes, already rate-form (per-second via counter deltas)."""

    tick: int
    #: requests/sec per node index (0.0 for nodes without stats).
    node_rates: "list[float]"
    active_nodes: "list[int]"
    #: breaker state string per node ("closed" when no breaker plane).
    breaker_states: "list[str]"
    slot_counts: "list[int]"
    #: keys currently pinned by placement overrides.
    override_keys: "set[str]"
    #: fleet admitted tokens/sec (delta of the monotonic totals).
    token_rate: float
    #: per-tenant admitted tokens/sec (delta of per-tenant totals).
    tenant_rates: "dict[str, float]"
    #: fleet-aggregated per-key admitted-token delta THIS tick,
    #: descending — the sketch-fed hot-cost ranking.
    hot_key_deltas: "list[tuple[str, float]]"
    #: fleet-summed outstanding reserved tokens (reserve issued, settle
    #: pending — a LEVEL gauge, not a counter delta: the holds
    #: themselves are the prospective load).
    outstanding_tokens: float = 0.0
    #: federation sensors (LEVEL gauges): outstanding leases at any
    #: home in the fleet, and slices currently serving their degraded
    #: envelope at any region agent — the partition symptom.
    fed_outstanding: float = 0.0
    fed_degraded: float = 0.0
    #: Audit-plane sensors (cumulative fleet sums, zero when no node
    #: carries an auditor — the pre-audit soak schedules stay bit-for-
    #: bit): conservation breaches observed and watchdog alerts.
    audit_breaches: float = 0.0
    slo_alerts: float = 0.0
    #: Goodput-plane sensors (rates via counter deltas; zero on fleets
    #: with no attempt/deadline-stamped traffic, so pre-storm soak
    #: schedules stay bit-for-bit): attempt-tail-stamped admissions/sec
    #: and doomed-work/sec (deadline-expired grants + late settles).
    retry_rate: float = 0.0
    doomed_rate: float = 0.0

    @property
    def skew(self) -> float:
        """Max/mean per-node request rate over active nodes (1.0 when
        idle or single-node) — the load-imbalance gauge."""
        rates = [self.node_rates[j] for j in self.active_nodes
                 if j < len(self.node_rates)]
        if not rates:
            return 1.0
        mean = sum(rates) / len(rates)
        return max(rates) / mean if mean > 0 else 1.0

    @property
    def slot_spread(self) -> float:
        """(max − min)/mean slot ownership over active nodes."""
        counts = [self.slot_counts[j] for j in self.active_nodes
                  if j < len(self.slot_counts)]
        if not counts:
            return 0.0
        mean = sum(counts) / len(counts)
        return (max(counts) - min(counts)) / mean if mean > 0 else 0.0


class Controller:
    """The reconciliation loop (module docstring). One instance binds a
    :class:`~.cluster.ClusterBucketStore` (the actuator surface AND the
    sensor plane), zero or more shed targets (objects with
    ``set_shed_level`` — :class:`~.admission.AdmissionPolicy`), and a
    config. Drive it with :meth:`run` (wall-clock cadence) or call
    :meth:`tick` directly (the seeded soaks' deterministic drive)."""

    _ACTIONS_CAP = 512  # migration_log posture: newest events win

    def __init__(self, cluster, *,
                 config: "ControllerConfig | None" = None,
                 shed_targets: Sequence = (),
                 federation=None,
                 flight_recorder=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.cluster = cluster
        self.config = config or ControllerConfig()
        self._shed_targets = list(shed_targets)
        #: Optional :class:`~.federation.RegionFederation`: when
        #: attached, the ``federation`` actuator drives its WAN
        #: renew/lease rounds on the tick cadence, feeding the
        #: controller's own per-tenant velocity-delta rates as the
        #: demand report (hysteresis + cooldown + budget guarded,
        #: dry-run parity — like every actuator).
        self.federation = federation
        self.flight_recorder = (flight_recorder
                                if flight_recorder is not None
                                else getattr(cluster, "flight_recorder",
                                             None))
        self._clock = clock
        # Sensor state: one delta window per counter, owned by THIS
        # consumer (CounterDeltas — the destructive-reset contract).
        self._deltas = CounterDeltas()
        # Decision state (all of it evolves identically in dry-run —
        # the dry-run parity contract).
        self._tick = 0
        self._streaks: dict[str, int] = {}      # gate name → consecutive
        self._cooldowns: dict[str, int] = {}    # action kind → next ok tick
        self._budget_ticks: list[int] = []      # decided-action ticks
        self._open_streak: dict[int, int] = {}  # node → consecutive OPEN
        self._closed_streak: dict[int, int] = {}
        #: Nodes THIS controller drained (only these may auto-rejoin).
        self.auto_drained: set[int] = set()
        #: The decided shed level (None = shed nothing). Pushed to shed
        #: targets only when live; the decided value itself evolves in
        #: dry-run too, so the decision stream stays comparable.
        self.shed_level: "int | None" = None
        #: Retry-storm defense posture (decided here, pushed to shed
        #: targets' set_retry_shed/set_doomed_gate when live; evolves
        #: identically in dry-run — the parity contract).
        self.retry_shed_on = False
        # Audit surface.
        self.actions: list[dict] = []
        self.actions_recorded = 0
        self.ticks = 0
        self.tick_failures = 0
        self.scrape_errors = 0
        self.actuation_errors = 0
        self._actions_by_outcome: dict[tuple[str, str], int] = {}
        self.last_pressure = 0.0
        self.last_skew = 1.0
        self.last_token_rate = 0.0
        self.last_outstanding = 0.0
        self.last_fed_degraded = 0.0
        self.last_fed_outstanding = 0.0
        self.last_audit_breaches = 0.0
        self.last_slo_alerts = 0.0
        self.last_retry_ratio = 0.0
        self.last_doomed_rate = 0.0
        self._stop = asyncio.Event()
        # Announce on the audit surfaces that can splice us in
        # (cluster.stats() "controller" section, cluster_metrics()).
        try:
            cluster.controller = self
        except AttributeError:
            pass

    # -- sensing -------------------------------------------------------------
    async def scrape(self) -> Sensors:
        """One OP_STATS fan-out turned into rate-form sensors. Never
        resets server windows; all rates are this consumer's own
        counter deltas over ``tick_s``.

        Deltas are taken PER NODE and then summed — never the other
        way around. A fleet-summed counter is not monotonic: a node
        missing from one scrape (timeout → ``{}`` in the fan-out, the
        cluster's down-node posture) would drop the sum, and the
        reset convention would then report the entire remaining sum as
        one tick's "increase" — a phantom pressure spike that could
        shed real traffic during a mere sensor-plane blip. Per-node
        windows confine a node's outage to that node's contribution:
        an unobserved counter simply doesn't advance its window, and
        recovery folds the gap into one wide (true) delta."""
        cfg = self.config
        st = await self.cluster.stats()
        nodes = st.get("nodes", [])
        node_rates = []
        tenant_rates: dict[str, float] = {}
        hot_totals: dict[str, float] = {}
        outstanding = 0.0
        fed_outstanding = fed_degraded = 0.0
        audit_breaches = slo_alerts = 0.0
        retry_rate = doomed_rate = 0.0
        for j, ns in enumerate(nodes):
            if not ns:
                node_rates.append(0.0)
                continue
            node_rates.append(self._deltas.rate(
                f"node{j}/requests", ns.get("requests_served", 0),
                cfg.tick_s))
            # Outstanding reservations are a level, summed as-is (an
            # unobserved node contributes nothing — conservative: its
            # holds neither spike nor mask the fleet pressure).
            outstanding += float((ns.get("reservations") or {})
                                 .get("outstanding_tokens", 0.0))
            # Federation levels: home lease count + region degraded
            # slices (the partition symptom the actuator reacts to).
            fed_outstanding += float((ns.get("federation") or {})
                                     .get("outstanding_leases", 0.0))
            fed_degraded += float((ns.get("federation_region") or {})
                                  .get("degraded_now", 0.0))
            # Audit plane (cumulative counters summed as levels — the
            # controller only watches for growth; a node without an
            # auditor contributes zero, so pre-audit soaks replay
            # unchanged).
            au = ns.get("audit") or {}
            audit_breaches += float(au.get("breaches", 0.0))
            slo_alerts += float((au.get("slo") or {}).get("alerts", 0.0))
            # Goodput plane (docs/DESIGN.md §24): both sections are
            # emitted only once stamped traffic exists — absent means
            # zeros, and the per-node delta windows simply don't
            # advance. Deadline-expired grants + late settles sum into
            # one monotonic doomed-work counter per node.
            rt = ns.get("retry") or {}
            retry_rate += self._deltas.rate(
                f"node{j}/retry_attempts",
                float(rt.get("attempts_seen", 0.0)), cfg.tick_s)
            gp = ns.get("goodput") or {}
            doomed_rate += self._deltas.rate(
                f"node{j}/goodput_doomed",
                float(gp.get("deadline_expired_grants", 0.0))
                + float(gp.get("settled_late", 0.0)), cfg.tick_s)
            tv = ns.get("token_velocity") or {}
            for tenant, total in (tv.get("admitted") or {}).items():
                tenant_rates[tenant] = tenant_rates.get(tenant, 0.0) \
                    + self._deltas.rate(f"node{j}/tenant/{tenant}",
                                        float(total), cfg.tick_s)
            for row in (ns.get("hot_keys") or {}).get("top", ()):
                key = row["key"]
                hot_totals[key] = hot_totals.get(key, 0.0) \
                    + self._deltas.delta(f"node{j}/hot/{key}",
                                         float(row["count"]))
        token_rate = sum(tenant_rates.values())
        hot_deltas = sorted(hot_totals.items(), key=lambda kv: -kv[1])
        resil = st.get("resilience", {})
        breakers = resil.get("breakers")
        n_nodes = len(nodes)
        if breakers:
            breaker_states = [b.get("state", "closed") for b in breakers]
        else:
            breaker_states = ["closed"] * n_nodes
        placement = st.get("placement", {})
        drained = set(placement.get("drained", ()))
        active = [j for j in range(n_nodes) if j not in drained]
        overrides = set(getattr(getattr(self.cluster, "placement", None),
                                "overrides", {}) or {})
        return Sensors(
            tick=self._tick,
            node_rates=node_rates,
            active_nodes=active,
            breaker_states=breaker_states,
            slot_counts=list(placement.get("slot_counts",
                                           [0] * n_nodes)),
            override_keys=overrides,
            token_rate=token_rate,
            tenant_rates=tenant_rates,
            hot_key_deltas=hot_deltas,
            outstanding_tokens=outstanding,
            fed_outstanding=fed_outstanding,
            fed_degraded=fed_degraded,
            audit_breaches=audit_breaches,
            slo_alerts=slo_alerts,
            retry_rate=retry_rate,
            doomed_rate=doomed_rate,
        )

    # -- flap guards ---------------------------------------------------------
    def _streak(self, name: str, condition: bool) -> int:
        """Advance/reset a named hysteresis streak; returns its length."""
        n = self._streaks.get(name, 0) + 1 if condition else 0
        self._streaks[name] = n
        return n

    def _gate(self, kind: str) -> "str | None":
        """Cooldown + budget gate for an actuator that wants to fire.
        Returns None (clear to decide) or the blocking outcome. Both
        guards consume state identically in dry-run (parity)."""
        if self._cooldowns.get(kind, -1) > self._tick:
            return "cooldown"
        window_start = self._tick - self.config.budget_window_ticks
        self._budget_ticks = [t for t in self._budget_ticks
                              if t > window_start]
        if len(self._budget_ticks) >= self.config.budget_actions:
            return "budget_exhausted"
        return None

    def _commit_gate(self, kind: str) -> None:
        """A decision fired: start its cooldown, spend the budget."""
        self._cooldowns[kind] = self._tick + self.config.cooldown_ticks \
            + 1
        self._budget_ticks.append(self._tick)

    def budget_remaining(self) -> int:
        window_start = self._tick - self.config.budget_window_ticks
        spent = sum(1 for t in self._budget_ticks if t > window_start)
        return max(0, self.config.budget_actions - spent)

    # -- deciding ------------------------------------------------------------
    def decide(self, sensors: Sensors) -> list[dict]:
        """The pure policy half: sensor snapshot + controller state →
        intents. Every intent carries ``action``/``target``/``reason``;
        a flap-guard-starved one carries its blocking ``outcome``
        pre-set (``cooldown`` never logs — it is the steady state of
        hysteresis — but ``budget_exhausted`` does: a starved loop must
        be visible). Identical in dry-run by construction."""
        cfg = self.config
        intents: list[dict] = []
        self.last_skew = sensors.skew
        self.last_token_rate = sensors.token_rate
        self.last_fed_degraded = sensors.fed_degraded
        self.last_fed_outstanding = sensors.fed_outstanding
        self.last_audit_breaches = sensors.audit_breaches
        self.last_slo_alerts = sensors.slo_alerts

        def want(kind: str, target, reason: str, **extra) -> bool:
            """Returns True when the intent passed every gate (it WILL
            be executed in live mode) — callers key their own decision
            state off this, so that state evolves identically in
            dry-run (the parity contract)."""
            gate = self._gate(kind)
            if gate == "cooldown":
                return False  # waiting out a cooldown is not an event
            intent = {"action": kind, "target": target, "reason": reason,
                      **extra}
            if gate is not None:
                # Starved (budget): logged but not executed — and the
                # cooldown starts anyway, so a stalled loop reports
                # once per cooldown window, not once per tick.
                intent["outcome"] = gate
                self._cooldowns[kind] = self._tick \
                    + cfg.cooldown_ticks + 1
            else:
                self._commit_gate(kind)
            intents.append(intent)
            return gate is None

        # 1. Membership: sustained breaker OPEN → drain; recovery of a
        # node WE drained → rejoin. Consecutive-tick streaks per node.
        for j, state in enumerate(sensors.breaker_states):
            is_open = state == "open"
            self._open_streak[j] = (self._open_streak.get(j, 0) + 1
                                    if is_open else 0)
            self._closed_streak[j] = (self._closed_streak.get(j, 0) + 1
                                      if state == "closed" else 0)
            if (is_open and j in sensors.active_nodes
                    and len(sensors.active_nodes) > 1
                    and j not in self.auto_drained
                    and self._open_streak[j] >= cfg.drain_after_open_ticks):
                # auto_drained is DECISION state (it gates re-drain and
                # the rejoin path), so it mutates here — dry-run's
                # membership stream must match live's. A live drain
                # that then fails (outcome "error") stays marked: the
                # decision was made; retrying it for free would be a
                # flap-amplifier exactly when the fleet is sick, and
                # the later rejoin of a never-drained node is a no-op.
                if want("drain", j,
                        f"breaker open {self._open_streak[j]} ticks"):
                    self.auto_drained.add(j)
            elif (j in self.auto_drained
                    and self._closed_streak[j]
                    >= cfg.drain_after_open_ticks):
                if want("rejoin", j,
                        f"breaker closed {self._closed_streak[j]} ticks "
                        "after an autonomous drain"):
                    self.auto_drained.discard(j)

        # 2. Hot-COST split: one key's share of this tick's admitted
        # tokens, sustained. Only meaningful with somewhere to split to.
        split_cond = False
        if sensors.hot_key_deltas and len(sensors.active_nodes) > 1:
            key, delta = sensors.hot_key_deltas[0]
            total = sum(d for _, d in sensors.hot_key_deltas)
            share = delta / total if total > 0 else 0.0
            split_cond = (delta >= cfg.split_min_tokens
                          and share >= cfg.split_share
                          and key not in sensors.override_keys)
            if self._streak("split", split_cond) >= cfg.split_streak_ticks:
                want("split", key,
                     f"key carries {share:.0%} of admitted tokens "
                     f"({delta:.0f}/tick)", share=round(share, 4))
                self._streaks["split"] = 0
        else:
            self._streak("split", False)

        # 3. Slot rebalance on sustained ownership imbalance.
        spread = sensors.slot_spread
        if self._streak("rebalance",
                        spread >= cfg.rebalance_imbalance
                        and len(sensors.active_nodes) > 1) \
                >= cfg.rebalance_streak_ticks:
            want("rebalance", None,
                 f"slot spread {spread:.2f} over active nodes",
                 spread=round(spread, 4))
            self._streaks["rebalance"] = 0

        # 4. Federation: when a region agent is attached, drive its
        # WAN renew round on the tick cadence — the controller's
        # velocity-delta rates ARE the demand report the home's
        # demand-proportional slice sizing consumes — and off-cadence
        # when a slice is serving its degraded envelope (partition
        # symptom, hysteresis-guarded: a one-scrape blip never fires).
        if self.federation is not None:
            self._last_tenant_rates = dict(sensors.tenant_rates)
            due = self._tick % cfg.federation_renew_ticks == 0
            deg = self._streak("fed_degraded",
                               sensors.fed_degraded > 0)
            if due:
                want("federation", None,
                     f"renew cadence (every "
                     f"{cfg.federation_renew_ticks} ticks)")
            elif deg >= cfg.federation_degraded_streak_ticks:
                want("federation", None,
                     f"{sensors.fed_degraded:.0f} slice(s) degraded "
                     f"{deg} ticks — attempting heal")
                self._streaks["fed_degraded"] = 0
        else:
            self._streak("fed_degraded", False)

        # 5. Retry-storm defense — the rung BEFORE the priority shed
        # ladder (docs/DESIGN.md §24): when retries become a sustained
        # share of the fleet request rate, arm the retry-shed and
        # doomed-work gates so duplicate and unmeetable work sheds
        # before any priority class browns out. Hysteresis-guarded
        # like every rung; the decided posture evolves in dry-run too.
        request_rate = sum(sensors.node_rates)
        ratio = (sensors.retry_rate / request_rate
                 if request_rate > 0 else 0.0)
        self.last_retry_ratio = ratio
        self.last_doomed_rate = sensors.doomed_rate
        hi_r = self._streak(
            "retry_high",
            ratio >= cfg.retry_storm_high
            and sensors.retry_rate >= cfg.retry_storm_min_rate)
        lo_r = self._streak("retry_low", ratio <= cfg.retry_storm_low)
        if hi_r >= cfg.retry_storm_raise_ticks and not self.retry_shed_on:
            if want("retry_shed_on", None,
                    f"retries are {ratio:.0%} of the fleet request "
                    f"rate ({sensors.retry_rate:.1f}/s; doomed work "
                    f"{sensors.doomed_rate:.1f}/s)",
                    ratio=round(ratio, 4)):
                self.retry_shed_on = True
            self._streaks["retry_high"] = 0
        elif lo_r >= cfg.retry_storm_lower_ticks and self.retry_shed_on:
            if want("retry_shed_off", None,
                    f"retry share {ratio:.0%} ≤ {cfg.retry_storm_low}",
                    ratio=round(ratio, 4)):
                self.retry_shed_on = False
            self._streaks["retry_low"] = 0

        # 6. Shed ladder from token-velocity pressure PLUS outstanding-
        # reservation pressure: reserved-but-unsettled tokens are load
        # that WILL land, folded in as a prospective rate over the
        # reservation horizon — brownouts start before a wave of
        # still-streaming requests reaches the settled-token rate. The
        # decided level evolves here (dry-run included); execution only
        # pushes it to the attached gateways.
        self.last_outstanding = sensors.outstanding_tokens
        if cfg.token_rate_capacity:
            prospective = (sensors.outstanding_tokens
                           / cfg.reservation_horizon_s)
            pressure = ((sensors.token_rate + prospective)
                        / cfg.token_rate_capacity)
            self.last_pressure = pressure
            hi = self._streak("shed_high", pressure >= cfg.shed_high)
            lo = self._streak("shed_low", pressure <= cfg.shed_low)
            if hi >= cfg.shed_raise_ticks:
                nxt = (PRIORITY_SCAVENGER if self.shed_level is None
                       else self.shed_level - 1)
                if self.shed_level is None or nxt >= cfg.shed_floor:
                    top = max(sensors.tenant_rates.items(),
                              key=lambda kv: kv[1],
                              default=(None, 0.0))
                    if want("shed_raise", nxt,
                            f"token pressure {pressure:.2f} ≥ "
                            f"{cfg.shed_high} (hottest tenant: "
                            f"{top[0]})",
                            pressure=round(pressure, 4)):
                        self.shed_level = nxt
                self._streaks["shed_high"] = 0
            elif lo >= cfg.shed_lower_ticks and self.shed_level is not None:
                nxt = (None if self.shed_level >= PRIORITY_SCAVENGER
                       else self.shed_level + 1)
                if want("shed_lower", nxt,
                        f"token pressure {pressure:.2f} ≤ {cfg.shed_low}",
                        pressure=round(pressure, 4)):
                    self.shed_level = nxt
                self._streaks["shed_low"] = 0
        return intents

    # -- actuating -----------------------------------------------------------
    async def _execute(self, intent: dict) -> str:
        """Run one intent through the real actuator paths (all of them
        health-gated and serialized under the cluster's
        ``_membership_lock`` where membership is involved). Returns the
        outcome string."""
        if self.config.dry_run:
            return "dry_run"
        kind, target = intent["action"], intent["target"]
        try:
            if kind == "split":
                # Sketch-fed: split_hot_keys re-ranks from the fleet's
                # own heavy-hitter sketch and pins the winner — the
                # sensed candidate rides along in the record for audit.
                keys = await self.cluster.split_hot_keys(top_n=1)
                intent["split_keys"] = keys
                return "executed" if keys else "noop"
            if kind == "rebalance":
                await self.cluster.rebalance(reason="controller")
                return "executed"
            if kind == "drain":
                await self.cluster.drain_node(target)
                return "executed"
            if kind == "rejoin":
                await self.cluster.rejoin_node(target)
                return "executed"
            if kind == "federation":
                if self.federation is None:   # pragma: no cover
                    return "noop"             # decide() gates on it
                summary = await self.federation.tick(
                    demands=getattr(self, "_last_tenant_rates", None))
                intent["summary"] = summary
                if summary.get("errors") and not (
                        summary.get("renewed") or summary.get("leased")):
                    # Every WAN call failed: a partition symptom, not
                    # an actuator error — counted on the agent, and
                    # the outcome says so for the audit trail.
                    return "partitioned"
                return "executed"
            if kind in ("shed_raise", "shed_lower"):
                if not self._shed_targets:
                    # No gateway to actuate: the decided level still
                    # evolves (and is scrapeable), but claiming
                    # "executed" would put a brownout in the audit
                    # trail that never reached any admission edge.
                    return "noop"
                for policy in self._shed_targets:
                    policy.set_shed_level(target)
                return "executed"
            if kind in ("retry_shed_on", "retry_shed_off"):
                if not self._shed_targets:
                    return "noop"  # same posture as the shed ladder
                on = kind == "retry_shed_on"
                hit = False
                for policy in self._shed_targets:
                    # Both gates arm together: duplicate work (retries)
                    # and unmeetable work (doomed deadlines) shed as
                    # one defense. getattr-probed — a bare
                    # AdmissionPolicy target has the retry gate only,
                    # a server target has both.
                    for meth in ("set_retry_shed", "set_doomed_gate"):
                        fn = getattr(policy, meth, None)
                        if callable(fn):
                            fn(on)
                            hit = True
                return "executed" if hit else "noop"
            return "noop"  # unknown intent kinds are inert, visibly
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            # Routed, not swallowed: counted here, carried on the action
            # record (flight frame + log event 6 + actions_total series).
            self.actuation_errors += 1
            intent["error"] = repr(exc)
            return "error"

    def _log_action(self, record: dict) -> None:
        self.actions.append(record)
        if len(self.actions) > self._ACTIONS_CAP:
            del self.actions[: -self._ACTIONS_CAP]
        self.actions_recorded += 1
        key = (record["action"], record["outcome"])
        self._actions_by_outcome[key] = \
            self._actions_by_outcome.get(key, 0) + 1
        if self.flight_recorder is not None:
            self.flight_recorder.record("controller", **record)
        log.controller_action(record)

    # -- the loop ------------------------------------------------------------
    async def tick(self) -> list[dict]:
        """One reconciliation round: seam → scrape → decide → actuate →
        audit. Returns this tick's action records (gated ones
        included). A faulted or failed tick counts + records a frame
        and decides nothing — the next tick re-derives from fresh
        deltas, so a lost round costs one window, never drift."""
        self._tick += 1
        try:
            await faults.seam("controller.tick")
            sensors = await self.scrape()
        except asyncio.CancelledError:
            raise
        except faults.BlackholeFault:
            self.tick_failures += 1
            if self.flight_recorder is not None:
                self.flight_recorder.record("controller", tick=self._tick,
                                            action="tick",
                                            outcome="blackhole")
            return []
        except Exception as exc:
            self.tick_failures += 1
            self.scrape_errors += 1
            if self.flight_recorder is not None:
                self.flight_recorder.record(
                    "controller", tick=self._tick, action="tick",
                    outcome="fault", error=repr(exc))
            return []
        intents = self.decide(sensors)
        records: list[dict] = []
        for intent in intents:
            outcome = intent.pop("outcome", None)
            if outcome is None:
                outcome = await self._execute(intent)
            record = {"tick": self._tick, "t": self._clock(),
                      "outcome": outcome, **intent}
            self._log_action(record)
            records.append(record)
        self.ticks += 1
        return records

    async def run(self) -> None:
        """Tick on the configured wall-clock cadence until
        :meth:`stop`. The soaks drive :meth:`tick` directly instead —
        cadence is an operational concern, not a semantic one."""
        self._stop.clear()
        while not self._stop.is_set():
            await self.tick()
            try:
                await asyncio.wait_for(self._stop.wait(),
                                       self.config.tick_s)
            except asyncio.TimeoutError:
                continue

    def stop(self) -> None:
        self._stop.set()

    # -- audit surfaces ------------------------------------------------------
    def numeric_stats(self) -> dict:
        """Flat numeric dict for ``register_numeric_dict`` — the
        ``drl_controller_*`` gauge/counter families."""
        return {
            "ticks": self.ticks,
            "tick_failures": self.tick_failures,
            "actions_recorded": self.actions_recorded,
            "actuation_errors": self.actuation_errors,
            "shed_level": -1 if self.shed_level is None
            else self.shed_level,
            "pressure": self.last_pressure,
            "skew": self.last_skew,
            "token_rate": self.last_token_rate,
            "outstanding_tokens": self.last_outstanding,
            "fed_degraded": self.last_fed_degraded,
            "fed_outstanding_leases": self.last_fed_outstanding,
            "audit_breaches_seen": self.last_audit_breaches,
            "slo_alerts_seen": self.last_slo_alerts,
            "retry_ratio": self.last_retry_ratio,
            "doomed_rate": self.last_doomed_rate,
            "retry_shed_on": int(self.retry_shed_on),
            "budget_remaining": self.budget_remaining(),
            "dry_run": int(self.config.dry_run),
            "auto_drained": len(self.auto_drained),
        }

    def action_series(self) -> list[tuple[dict, float]]:
        """``drl_controller_actions_total{action=,outcome=}`` series."""
        return [({"action": a, "outcome": o}, float(n))
                for (a, o), n in sorted(self._actions_by_outcome.items())]

    def register_metrics(self, reg) -> None:
        """Splice the controller families into an existing registry
        (the server's or the cluster's). Callables read live state, so
        registering before the first tick costs nothing."""
        reg.register_numeric_dict(
            "controller", "autonomous control plane",
            self.numeric_stats,
            counters={"ticks", "tick_failures", "actions_recorded",
                      "actuation_errors"})
        reg.labeled_counters(
            "controller_actions",
            "Controller decisions by action and outcome",
            self.action_series)

    def metrics_registry(self):
        from distributedratelimiting.redis_tpu.utils.metrics import (
            MetricsRegistry,
        )

        reg = MetricsRegistry()
        self.register_metrics(reg)
        return reg

    def stats(self) -> dict:
        """JSON-shaped audit summary for OP_STATS embedding (the full
        bounded action log lives on :attr:`actions`; stats carries the
        newest 50)."""
        return {
            **self.numeric_stats(),
            "scrape_errors": self.scrape_errors,
            "actions_total": {f"{a}:{o}": n for (a, o), n
                              in sorted(self._actions_by_outcome.items())},
            "actions": self.actions[-50:],
        }
