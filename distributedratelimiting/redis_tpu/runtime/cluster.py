"""Cluster store — client-side key sharding across N store servers.

The reference's deployment is a star: every client talks to ONE shared
Redis (SURVEY.md §5.8). One TPU host already replaces that Redis
(:class:`~.server.BucketStoreServer` fronting a device store, or a whole
pod slice via :class:`~..parallel.mesh_store.MeshBucketStore`). This module
adds the horizontal dimension the reference's README gestured at with
partitioning (``README.md:7-8``) at *cluster* scale: N independent store
servers — each its own time authority for the keys it owns — with clients
routing ``key → node`` by the same stable crc32 the in-mesh sharding uses
(:func:`~..parallel.sharded_store.shard_of_key`). This is the
Redis-Cluster shape, re-hosted: hash-slot routing lives in the client,
nodes share nothing, and the DCN between hosts carries only each key's own
traffic — no cross-node collectives, because keys never interact
(SURVEY.md §5.7).

Semantics carried over from the single-node client:

- **Per-key semantics are exactly single-node semantics.** A key's
  requests always land on the same node, and bulk splitting is
  order-stable per node, so duplicate-key serialization (invariant 3 at
  batch granularity) and store-as-time-authority (invariant 1) hold
  per key. There is no cross-key ordering guarantee across nodes — the
  same property as the reference's partitioned design (one Redis hash per
  partition, no cross-partition atomicity).
- **Degraded mode is per node** (invariant 9): a node failure affects only
  the keys it owns. Single-key ops surface the error to the caller (the
  approximate limiter's refresh already logs-and-skips; event id 1/2).
  Bulk ops choose via ``partial_failures``: ``"raise"`` (default —
  all-or-error, the caller retries) or ``"deny"`` (decide what we can:
  failed nodes' rows come back denied with ``remaining == 0``, logged
  once per failing node).
- The **global decaying counter** of the approximate algorithm is itself
  just a key (``sync_counter(key=instance_name)``), so it routes to one
  node — every client instance syncs the same named counter against the
  same node's clock, preserving the EWMA instance-count estimate
  unchanged.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Sequence

import numpy as np

from distributedratelimiting.redis_tpu.parallel.sharded_store import (
    route_keys,
    shard_of_key,
)
from distributedratelimiting.redis_tpu.runtime.clock import Clock, MonotonicClock
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.store import (
    AcquireResult,
    BucketStore,
    BulkAcquireResult,
    SyncResult,
)
from distributedratelimiting.redis_tpu.utils import log, tracing

__all__ = ["ClusterBucketStore"]


class ClusterBucketStore(BucketStore):
    """Key-sharded façade over N :class:`BucketStore` nodes.

    Exactly one of ``stores``, ``addresses``, or ``urls`` must be given
    (highest-precedence one wins — the same config ladder as
    :class:`RemoteBucketStore`, lifted to lists)::

        store = ClusterBucketStore(addresses=[("tpu-a", 6380), ("tpu-b", 6380)])
        store = ClusterBucketStore(urls=["tpu-a:6380", "tpu-b:6380"])
        store = ClusterBucketStore(stores=[node_a, node_b])   # tests / mixed

    ``remote_kwargs`` (auth token, timeouts, coalescing knobs …) pass
    through to each constructed :class:`RemoteBucketStore` when addresses
    or urls are given.
    """

    def __init__(
        self,
        *,
        stores: Sequence[BucketStore] | None = None,
        addresses: Sequence[tuple[str, int]] | None = None,
        urls: Sequence[str] | None = None,
        partial_failures: str = "raise",
        clock: Clock | None = None,
        **remote_kwargs,
    ) -> None:
        if stores is not None:
            nodes = list(stores)
        elif addresses is not None:
            nodes = [RemoteBucketStore(address=a, **remote_kwargs)
                     for a in addresses]
        elif urls is not None:
            nodes = [RemoteBucketStore(url=u, **remote_kwargs) for u in urls]
        else:
            raise ValueError("one of stores, addresses, or urls is required")
        if not nodes:
            raise ValueError("cluster needs at least one node")
        if partial_failures not in ("raise", "deny"):
            raise ValueError("partial_failures must be 'raise' or 'deny'")
        self.nodes: list[BucketStore] = nodes
        self.n_nodes = len(nodes)
        self._partial_failures = partial_failures
        # Local clock satisfies the BucketStore interface (diagnostics
        # only); each NODE is the time authority for the keys it owns.
        self.clock = clock or MonotonicClock()

        # Background loop for the blocking surface (same pattern as
        # RemoteBucketStore): lets blocking callers fan out to all nodes
        # concurrently from any thread, loop or no loop.
        self._io_loop: asyncio.AbstractEventLoop | None = None
        self._io_thread: threading.Thread | None = None
        self._thread_gate = threading.Lock()
        self._closed = False

    # -- routing -----------------------------------------------------------
    def node_of(self, key: str) -> BucketStore:
        """The node that owns ``key`` (stable crc32 — every client on every
        host routes identically, no coordination)."""
        return self.nodes[shard_of_key(key, self.n_nodes)]

    # -- blocking-surface plumbing ------------------------------------------
    def _ensure_loop(self) -> asyncio.AbstractEventLoop:
        loop = self._io_loop
        if loop is not None:
            return loop
        with self._thread_gate:
            if self._io_loop is None:
                loop = asyncio.new_event_loop()
                ready = threading.Event()

                def run() -> None:
                    asyncio.set_event_loop(loop)
                    ready.set()
                    loop.run_forever()

                t = threading.Thread(target=run, name="cluster-store-io",
                                     daemon=True)
                t.start()
                ready.wait()
                self._io_loop = loop
                self._io_thread = t
        return self._io_loop

    def _blocking(self, coro):
        return asyncio.run_coroutine_threadsafe(
            coro, self._ensure_loop()).result()

    # -- lifecycle ----------------------------------------------------------
    async def connect(self) -> None:
        """Eagerly connect every node (each node also lazily connects on
        first use, the reference's posture — this is for fail-fast setups)."""
        await asyncio.gather(*(n.connect() for n in self.nodes))

    async def aclose(self) -> None:
        if self._closed:
            return
        self._closed = True
        # return_exceptions: one node's failed close must not skip the
        # others or leak the I/O loop thread below.
        outs = await asyncio.gather(*(n.aclose() for n in self.nodes),
                                    return_exceptions=True)
        loop = self._io_loop
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)
            if self._io_thread is not None:
                # to_thread: a 5s worst-case join must not stall the
                # CALLER's event loop (drl-check async-blocking).
                await asyncio.to_thread(self._io_thread.join, 5.0)
            # Close only a stopped loop: if the join timed out the loop
            # thread is still running, and loop.close() would raise
            # RuntimeError here — masking any node-close exception
            # collected above (the daemon thread dies with the process).
            if self._io_thread is None or not self._io_thread.is_alive():
                loop.close()
            self._io_loop = None
        for out in outs:
            if isinstance(out, BaseException):
                raise out

    # -- single-key ops: route and forward ----------------------------------
    async def acquire(self, key: str, count: int, capacity: float,
                      fill_rate_per_sec: float) -> AcquireResult:
        return await self.node_of(key).acquire(key, count, capacity,
                                               fill_rate_per_sec)

    def acquire_blocking(self, key: str, count: int, capacity: float,
                         fill_rate_per_sec: float) -> AcquireResult:
        return self.node_of(key).acquire_blocking(key, count, capacity,
                                                  fill_rate_per_sec)

    def peek_blocking(self, key: str, capacity: float,
                      fill_rate_per_sec: float) -> float:
        return self.node_of(key).peek_blocking(key, capacity,
                                               fill_rate_per_sec)

    def acquire_submitter(self, capacity: float, fill_rate_per_sec: float):
        # Hoist per-node submitters once; per request only the route runs.
        subs = [n.acquire_submitter(capacity, fill_rate_per_sec)
                for n in self.nodes]
        n_nodes = self.n_nodes

        async def submit(key: str, count: int) -> AcquireResult:
            return await subs[shard_of_key(key, n_nodes)](key, count)

        return submit

    async def sync_counter(self, key: str, local_count: float,
                           decay_rate_per_sec: float) -> SyncResult:
        return await self.node_of(key).sync_counter(key, local_count,
                                                    decay_rate_per_sec)

    def sync_counter_blocking(self, key: str, local_count: float,
                              decay_rate_per_sec: float) -> SyncResult:
        return self.node_of(key).sync_counter_blocking(key, local_count,
                                                       decay_rate_per_sec)

    async def window_acquire(self, key: str, count: int, limit: float,
                             window_sec: float) -> AcquireResult:
        return await self.node_of(key).window_acquire(key, count, limit,
                                                      window_sec)

    def window_acquire_blocking(self, key: str, count: int, limit: float,
                                window_sec: float) -> AcquireResult:
        return self.node_of(key).window_acquire_blocking(key, count, limit,
                                                         window_sec)

    async def fixed_window_acquire(self, key: str, count: int, limit: float,
                                   window_sec: float) -> AcquireResult:
        return await self.node_of(key).fixed_window_acquire(
            key, count, limit, window_sec)

    def fixed_window_acquire_blocking(self, key: str, count: int,
                                      limit: float,
                                      window_sec: float) -> AcquireResult:
        return self.node_of(key).fixed_window_acquire_blocking(
            key, count, limit, window_sec)

    async def concurrency_acquire(self, key: str, count: int,
                                  limit: int) -> AcquireResult:
        return await self.node_of(key).concurrency_acquire(key, count, limit)

    def concurrency_acquire_blocking(self, key: str, count: int,
                                     limit: int) -> AcquireResult:
        return self.node_of(key).concurrency_acquire_blocking(key, count,
                                                              limit)

    async def concurrency_release(self, key: str, count: int) -> None:
        await self.node_of(key).concurrency_release(key, count)

    def concurrency_release_blocking(self, key: str, count: int) -> None:
        self.node_of(key).concurrency_release_blocking(key, count)

    # -- bulk ops: split by route, fan out, merge ---------------------------
    def _split(self, keys: Sequence[str]):
        """Group a bulk call by owning node, order-stably.

        Returns ``(order, bounds, keys_list)`` where ``order`` is a stable
        permutation grouping requests by node and ``bounds[j]:bounds[j+1]``
        slices node ``j``'s group. Stability keeps each node's sub-batch in
        arrival order, so per-node duplicate serialization is exactly the
        single-node bulk semantics.
        """
        keys = keys if isinstance(keys, list) else list(keys)
        routes = route_keys(keys, self.n_nodes)  # one native C pass
        order = np.argsort(routes, kind="stable")
        bounds = np.searchsorted(routes[order],
                                 np.arange(self.n_nodes + 1))
        return order, bounds, keys

    async def _bulk_fan_out(self, keys, counts, call, with_remaining: bool
                            ) -> BulkAcquireResult:
        n = len(keys)
        if n == 0:
            return BulkAcquireResult(
                np.zeros(0, bool),
                np.zeros(0, np.float32) if with_remaining else None)
        counts_np = np.asarray(counts, np.int64)
        if self.n_nodes == 1:
            return await call(self.nodes[0], keys, counts_np)
        order, bounds, keys = self._split(keys)

        tracer = tracing.get_tracer()
        live = [(j, int(bounds[j]), int(bounds[j + 1]))
                for j in range(self.n_nodes) if bounds[j] < bounds[j + 1]]
        # The whole fan-out is one span (a new root when the caller has
        # none, subject to the head-sampling coin): the per-node
        # children parent on it EXPLICITLY — if the coin fails here,
        # the nodes must not re-flip it N times and litter the buffer
        # with unrooted single-node traces.
        fspan = (tracer.start_span("cluster.fan_out",
                                   attrs={"nodes": len(live),
                                          "rows": int(n)})
                 if tracer.enabled else tracing._NULL_SPAN)
        fctx = fspan.context

        async def node_call(j: int, lo: int, hi: int):
            idx = order[lo:hi]
            sub_keys = [keys[i] for i in idx]
            # One child span per node: the fan-out share of a traced bulk
            # call decomposes into which node was slow.
            nspan = (tracer.start_span("cluster.node", parent=fctx,
                                       attrs={"node": j,
                                              "rows": int(hi - lo)})
                     if fctx is not None else tracing._NULL_SPAN)
            with nspan:
                try:
                    return await call(self.nodes[j], sub_keys,
                                      counts_np[idx])
                except Exception as exc:
                    if self._partial_failures == "raise":
                        raise
                    nspan.set_status("degraded")
                    log.could_not_connect_to_store(exc)
                    return None  # rows stay denied

        with fspan:
            outs = await asyncio.gather(*(node_call(*t) for t in live))

        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32) if with_remaining else None
        for (j, lo, hi), out in zip(live, outs):
            if out is None:
                continue
            idx = order[lo:hi]
            granted[idx] = out.granted
            if remaining is not None and out.remaining is not None:
                remaining[idx] = out.remaining
        return BulkAcquireResult(granted, remaining)

    async def acquire_many(self, keys: Sequence[str], counts: Sequence[int],
                           capacity: float, fill_rate_per_sec: float, *,
                           with_remaining: bool = True) -> BulkAcquireResult:
        async def call(node, sub_keys, sub_counts):
            return await node.acquire_many(
                sub_keys, sub_counts, capacity, fill_rate_per_sec,
                with_remaining=with_remaining)

        return await self._bulk_fan_out(keys, counts, call, with_remaining)

    def acquire_many_blocking(self, keys: Sequence[str],
                              counts: Sequence[int], capacity: float,
                              fill_rate_per_sec: float, *,
                              with_remaining: bool = True
                              ) -> BulkAcquireResult:
        return self._blocking(self.acquire_many(
            keys, counts, capacity, fill_rate_per_sec,
            with_remaining=with_remaining))

    async def window_acquire_many(self, keys: Sequence[str],
                                  counts: Sequence[int], limit: float,
                                  window_sec: float, *, fixed: bool = False,
                                  with_remaining: bool = True
                                  ) -> BulkAcquireResult:
        async def call(node, sub_keys, sub_counts):
            return await node.window_acquire_many(
                sub_keys, sub_counts, limit, window_sec, fixed=fixed,
                with_remaining=with_remaining)

        return await self._bulk_fan_out(keys, counts, call, with_remaining)

    def window_acquire_many_blocking(self, keys: Sequence[str],
                                     counts: Sequence[int], limit: float,
                                     window_sec: float, *,
                                     fixed: bool = False,
                                     with_remaining: bool = True
                                     ) -> BulkAcquireResult:
        return self._blocking(self.window_acquire_many(
            keys, counts, limit, window_sec, fixed=fixed,
            with_remaining=with_remaining))

    # -- ops fan-out ---------------------------------------------------------
    async def ping(self) -> None:
        await asyncio.gather(*(n.ping() for n in self.nodes
                               if hasattr(n, "ping")))

    async def save(self) -> None:
        """Checkpoint every node that supports it (≙ cluster-wide BGSAVE)."""
        await asyncio.gather(*(n.save() for n in self.nodes
                               if hasattr(n, "save")))

    async def cluster_metrics(self) -> str:
        """Fleet-wide OpenMetrics exposition: scrape every node's
        ``OP_METRICS`` text and merge — each sample re-emitted per node
        with a ``node="<j>"`` label (positional, same convention as
        :meth:`stats`) plus an aggregated summed series without it, so
        one scrape answers both "what is the fleet doing" and "which
        node is the outlier". Nodes without a metrics surface (bare
        in-process stores in tests) contribute nothing rather than
        failing the scrape."""
        from distributedratelimiting.redis_tpu.utils.metrics import (
            aggregate_openmetrics,
        )

        async def one(n: BucketStore) -> str:
            # callable check: on device stores `metrics` is the
            # StoreMetrics ATTRIBUTE, not the remote scrape method.
            if not callable(getattr(n, "metrics", None)):
                return ""
            try:
                return await n.metrics()
            except Exception as exc:  # a down node must not kill the
                log.could_not_connect_to_store(exc)  # fleet scrape
                return ""

        texts = await asyncio.gather(*(one(n) for n in self.nodes))
        return aggregate_openmetrics(texts)

    def cluster_metrics_blocking(self) -> str:
        return self._blocking(self.cluster_metrics())

    async def stats(self) -> dict:
        """Per-node stats plus cluster-level sums of the numeric metrics.
        ``nodes[j]`` is positionally node ``j``'s stats (``{}`` for nodes
        without a stats surface) — consumers correlate by index."""

        async def one(n: BucketStore) -> dict:
            return await n.stats() if hasattr(n, "stats") else {}

        per_node = await asyncio.gather(*(one(n) for n in self.nodes))
        total: dict = {}
        for s in per_node:
            for k, v in s.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    total[k] = total.get(k, 0) + v
        return {"n_nodes": self.n_nodes, "nodes": list(per_node),
                "total": total}

    # -- checkpoint ----------------------------------------------------------
    def snapshot(self) -> dict:
        """Cluster checkpoint = each node's snapshot, keyed by position.
        Remote nodes raise by design (state lives with the server — use
        :meth:`save` for server-side checkpoints); in-process nodes
        snapshot locally."""
        return {"cluster": True, "n_nodes": self.n_nodes,
                "nodes": [n.snapshot() for n in self.nodes]}

    def restore(self, snap: dict) -> None:
        if not snap.get("cluster") or snap.get("n_nodes") != self.n_nodes:
            raise ValueError(
                "snapshot is not a cluster snapshot for this topology "
                f"(need n_nodes={self.n_nodes})")
        for node, sub in zip(self.nodes, snap["nodes"]):
            node.restore(sub)
