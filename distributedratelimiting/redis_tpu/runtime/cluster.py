"""Cluster store — client-side key sharding across N store servers.

The reference's deployment is a star: every client talks to ONE shared
Redis (SURVEY.md §5.8). One TPU host already replaces that Redis
(:class:`~.server.BucketStoreServer` fronting a device store, or a whole
pod slice via :class:`~..parallel.mesh_store.MeshBucketStore`). This module
adds the horizontal dimension the reference's README gestured at with
partitioning (``README.md:7-8``) at *cluster* scale: N independent store
servers — each its own time authority for the keys it owns — with clients
routing ``key → node`` through an **epoch-versioned placement map**
(:class:`~.placement.PlacementMap`): the same stable crc32 the in-mesh
sharding uses picks a fixed *slot*, and the map assigns slots (plus
per-key hot-split overrides) to nodes. This is the Redis-Cluster shape,
re-hosted — hash-slot routing lives in the client, nodes share nothing,
and the DCN between hosts carries only each key's own traffic
(SURVEY.md §5.7) — but since round 6 the slot table is *live*:
:meth:`~ClusterBucketStore.add_node` / :meth:`~ClusterBucketStore.
drain_node` / :meth:`~ClusterBucketStore.split_hot_key` migrate slots
(and their bucket state, through the MIGRATE_PULL/PUSH handoff with its
bounded dual-ownership window — placement.py) instead of re-homing half
the keyspace by arithmetic. The epoch-0 map routes bit-identically to
the old ``crc32 % N``, so a cluster that never reshapes behaves exactly
as before. A node answering the routable ``placement moved`` error
makes the client refetch the map and re-route — the MOVED-redirect
posture, no coordination service.

Semantics carried over from the single-node client:

- **Per-key semantics are exactly single-node semantics.** A key's
  requests always land on the same node, and bulk splitting is
  order-stable per node, so duplicate-key serialization (invariant 3 at
  batch granularity) and store-as-time-authority (invariant 1) hold
  per key. There is no cross-key ordering guarantee across nodes — the
  same property as the reference's partitioned design (one Redis hash per
  partition, no cross-partition atomicity).
- **Degraded mode is per node** (invariant 9): a node failure affects only
  the keys it owns. Single-key ops surface the error to the caller (the
  approximate limiter's refresh already logs-and-skips; event id 1/2).
  Bulk ops choose via ``partial_failures``: ``"raise"`` (default —
  all-or-error, the caller retries) or ``"deny"`` (decide what we can:
  failed nodes' rows come back denied with ``remaining == 0``, logged
  once per failing node).
- The **global decaying counter** of the approximate algorithm is itself
  just a key (``sync_counter(key=instance_name)``), so it routes to one
  node — every client instance syncs the same named counter against the
  same node's clock, preserving the EWMA instance-count estimate
  unchanged.

The chaos plane (docs/OPERATIONS.md §8) adds per-node **circuit
breakers** and a **degraded-mode fallback** on top:

- ``breaker=True`` (or a :class:`~..utils.resilience.BreakerConfig`)
  gives each node a closed/open/half-open breaker. While OPEN the
  node's keyspace is never dialed — callers shed fast
  (:class:`NodeUnavailableError`) instead of queueing behind a dead
  peer's timeout; after the recovery window ONE request probes the node
  with a health op (``ping``) and a success re-closes it (rejoin).
- ``degraded_fallback=True`` serves a quarantined node's admission
  traffic from a client-local fair-share envelope instead of erroring:
  each key admits against ``headroom_budget(capacity,
  fraction=degraded_fraction)`` tokens refilled at ``fraction ×
  fill_rate`` — the approximate limiter's confidence policy re-used at
  the cluster edge, so over-admission during an outage window stays
  bounded by the same ``overadmit_epsilon`` family of formulas. The
  degraded state is DISCARDED when the node rejoins: the authoritative
  store rules again (the reference's wiped-state self-heal posture).
- Every node failure is a structured log event (id 3) plus a
  ``cluster_node_errors`` counter; breaker transitions are event id 4,
  flight-recorder frames, and OpenMetrics gauges
  (:meth:`metrics_registry`) — partitions are visible, not invisible.
"""

from __future__ import annotations

import asyncio
import math
import threading
import time
from typing import Callable, Mapping, Sequence

import numpy as np

from distributedratelimiting.redis_tpu.runtime import placement as placement_mod
from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.clock import Clock, MonotonicClock
from distributedratelimiting.redis_tpu.runtime.placement import (
    MOVED_ERROR_PREFIX,
    PlacementError,
    PlacementMap,
)
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.store import (
    AcquireResult,
    BucketStore,
    BulkAcquireResult,
    SyncResult,
)
from distributedratelimiting.redis_tpu.utils import faults, log, tracing
from distributedratelimiting.redis_tpu.utils.resilience import (
    BreakerConfig,
    CircuitBreaker,
)

__all__ = ["ClusterBucketStore", "NodeUnavailableError", "PlacementError"]


class NodeUnavailableError(ConnectionError):
    """The key's owning node is quarantined (circuit open) and no
    degraded fallback is configured — shed fast, by design."""


class _DegradedKeyspace:
    """Client-local fair-share admission for keys whose owning node is
    quarantined.

    Each ``(node, key, config)`` serves from a conservative local
    envelope: ``headroom_budget(capacity, fraction)`` tokens refilled at
    ``fraction × fill_rate`` — the same confidence policy the
    approximate limiter and the tier-0 edge cache use, re-hosted at the
    cluster edge (models/approximate.py's shared-formula discipline).
    Windows degrade as token buckets with ``(limit, limit/window)``.
    State is per-client; on rejoin the envelope's GRANTS are drained
    (``drain_node``) and debited against the authoritative node's
    buckets — closing the unaccounted over-admission window the
    discard-on-rejoin posture left open (a grant served locally during
    the outage now costs the real bucket, so the post-rejoin admission
    total stays inside the same epsilon bound as the outage itself).
    """

    #: Bounded memory under hostile key cardinality: oldest-inserted
    #: entries evict first (a re-touched key re-inserts at full budget —
    #: conservative only in the over-admission direction by one budget,
    #: which the epsilon bound already charges for).
    _MAX_KEYS = 1 << 16

    #: Grants-ledger eviction batch: at the 2×_MAX_KEYS cap, the
    #: smallest _EVICT_BATCH debts are shed in one heap pass instead of
    #: one min() scan per insert (the next scan is this many inserts
    #: away, so the amortized per-insert cost is ~O(log batch)).
    _EVICT_BATCH = 1 << 12

    def __init__(self, fraction: float = 0.5,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if not 0.0 < fraction <= 1.0:
            raise ValueError("degraded_fraction must be in (0, 1]")
        self._fraction = fraction
        self._clock = clock
        self._buckets: dict[tuple, tuple[float, float]] = {}
        #: Grants served per ``(node, key, kind, a, b)`` during the
        #: CURRENT outage — the rejoin-debit ledger (``drain_node``).
        self._grants: dict[tuple, float] = {}

    def acquire(self, node: int, key: str, count: int, capacity: float,
                fill_rate_per_sec: float,
                kind: str = "bucket",
                priority: int = 0) -> AcquireResult:
        now = self._clock()
        k = (node, key, kind, float(capacity), float(fill_rate_per_sec))
        entry = self._buckets.get(k)
        if entry is None:
            if len(self._buckets) >= self._MAX_KEYS:
                # Evict the BUCKET only: its grants ledger row survives
                # (bounded separately below) so the rejoin debit still
                # charges every grant the outage served — eviction must
                # not reopen the unaccounted-over-admission window.
                del self._buckets[next(iter(self._buckets))]
            if len(self._grants) >= 2 * self._MAX_KEYS:
                # Ledger cap under truly hostile cardinality: shed the
                # SMALLEST debts (least unaccounted admission) in one
                # amortized batch — a per-insert min() scan of 128K
                # entries would turn the degraded fallback into an O(n)
                # hotspot on exactly the path meant to keep serving
                # while a node is down. One heap pass per _EVICT_BATCH
                # inserts ≈ O(log batch) per insert.
                import heapq

                for gk, _ in heapq.nsmallest(
                        self._EVICT_BATCH, self._grants.items(),
                        key=lambda kv: kv[1]):
                    del self._grants[gk]
        # The shared envelope formula (placement.envelope_step): the
        # epsilon bound's two halves must never drift apart. Priority
        # routes through the one shed gate — scavenger is never served
        # from a degraded envelope, batch can't spend its reserve.
        granted, tokens = placement_mod.envelope_step(
            entry, now, count, capacity, fill_rate_per_sec,
            self._fraction, priority)
        if granted and count > 0:
            self._grants[k] = self._grants.get(k, 0.0) + count
        self._buckets[k] = (tokens, now)
        return AcquireResult(granted, max(tokens, 0.0))

    def drain_node(self, node: int) -> list[tuple[str, str, float,
                                                  float, float]]:
        """Rejoin: collect the outage's grants as ``(key, kind, a, b,
        count)`` debit rows and clear the node's degraded state — the
        caller charges them to the authoritative buckets."""
        out = [(k[1], k[2], k[3], k[4], granted)
               for k, granted in self._grants.items() if k[0] == node]
        self.clear_node(node)
        return out

    def clear_node(self, node: int) -> None:
        for k in [k for k in self._buckets if k[0] == node]:
            del self._buckets[k]
        for k in [k for k in self._grants if k[0] == node]:
            del self._grants[k]

    def __len__(self) -> int:
        return len(self._buckets)


class ClusterBucketStore(BucketStore):
    """Key-sharded façade over N :class:`BucketStore` nodes.

    Exactly one of ``stores``, ``addresses``, or ``urls`` must be given
    (highest-precedence one wins — the same config ladder as
    :class:`RemoteBucketStore`, lifted to lists)::

        store = ClusterBucketStore(addresses=[("tpu-a", 6380), ("tpu-b", 6380)])
        store = ClusterBucketStore(urls=["tpu-a:6380", "tpu-b:6380"])
        store = ClusterBucketStore(stores=[node_a, node_b])   # tests / mixed

    ``remote_kwargs`` (auth token, timeouts, coalescing knobs …) pass
    through to each constructed :class:`RemoteBucketStore` when addresses
    or urls are given.

    Resilience knobs (all off by default — behavior is then exactly the
    pre-chaos-plane cluster): ``breaker`` arms per-node circuit
    breakers, ``degraded_fallback`` serves quarantined keyspaces from
    the local fair-share envelope, ``flight_recorder`` receives breaker
    and node-error frames. Breaker state mutates under the GIL from
    whichever loop carries the request — transitions are coarse
    (per-node, per-failure) and tolerate that by construction.
    """

    def __init__(
        self,
        *,
        stores: Sequence[BucketStore] | None = None,
        addresses: Sequence[tuple[str, int]] | None = None,
        urls: Sequence[str] | None = None,
        partial_failures: str = "raise",
        clock: Clock | None = None,
        breaker: "BreakerConfig | bool | None" = None,
        breaker_clock: Callable[[], float] = time.monotonic,
        degraded_fallback: bool = False,
        degraded_fraction: float = 0.5,
        probe_timeout_s: float = 1.0,
        flight_recorder=None,
        placement: "PlacementMap | None" = None,
        slots_per_node: int = placement_mod.DEFAULT_SLOTS_PER_NODE,
        handoff_window_s: float = 2.0,
        **remote_kwargs,
    ) -> None:
        if stores is not None:
            nodes = list(stores)
        elif addresses is not None:
            nodes = [RemoteBucketStore(address=a, **remote_kwargs)
                     for a in addresses]
        elif urls is not None:
            nodes = [RemoteBucketStore(url=u, **remote_kwargs) for u in urls]
        else:
            raise ValueError("one of stores, addresses, or urls is required")
        if not nodes:
            raise ValueError("cluster needs at least one node")
        if partial_failures not in ("raise", "deny"):
            raise ValueError("partial_failures must be 'raise' or 'deny'")
        self.nodes: list[BucketStore] = nodes
        self.n_nodes = len(nodes)
        self._remote_kwargs = dict(remote_kwargs)
        self._partial_failures = partial_failures
        # Epoch-versioned keyspace ownership (placement.py). The default
        # initial map routes bit-identically to the legacy crc32 % N, so
        # a never-reshaped cluster behaves exactly as before.
        self.placement = placement or PlacementMap.initial(
            self.n_nodes, slots_per_node)
        self._handoff_window_s = handoff_window_s
        #: Nodes currently drained out of the slot table (still in
        #: ``nodes`` — indices are stable identities; rejoin_node folds
        #: them back in).
        self.drained: set[int] = set()
        #: Committed/aborted membership changes, in order — the reshard
        #: soak's differential-audit source of truth (each event carries
        #: the moved slots/keys plus the handoff window's [t_start,
        #: t_end] in time.monotonic()). Bounded like every other ledger
        #: here: a long-lived cluster resharding periodically keeps the
        #: newest _MIGRATION_LOG_CAP events.
        self.migration_log: list[dict] = []
        self.migrations = 0
        self.migration_aborts = 0
        #: Live config mutations driven by this coordinator
        #: (mutate_config; docs/OPERATIONS.md §10).
        self.config_mutations = 0
        self.config_aborts = 0
        self.config_rebased_rows = 0
        #: Degraded-envelope grants debited against rejoining nodes'
        #: authoritative buckets (the rejoin-reconcile satellite).
        self.rejoin_debits = 0
        self._announced = False
        #: The autonomous control plane, when one is reconciling this
        #: cluster (runtime/controller.py assigns itself here) — its
        #: audit surface rides stats() and cluster_metrics() so the
        #: loop's decisions are visible wherever the fleet's are.
        self.controller = None
        # Membership ops serialize on this coordinator: two concurrent
        # reshapes would read the same epoch, build conflicting targets,
        # and cross-wire the per-epoch pull/push ledgers (the server
        # side has _control_lock; this is the coordinator's half).
        self._membership_lock = asyncio.Lock()
        self._bg_tasks: set[asyncio.Task] = set()
        # Local clock satisfies the BucketStore interface (diagnostics
        # only); each NODE is the time authority for the keys it owns.
        self.clock = clock or MonotonicClock()

        # -- chaos plane ---------------------------------------------------
        self.flight_recorder = flight_recorder
        self._degraded = (_DegradedKeyspace(degraded_fraction)
                          if degraded_fallback else None)
        self._breaker_clock = breaker_clock
        if breaker:
            self._breaker_config = breaker if isinstance(
                breaker, BreakerConfig) else BreakerConfig()
            self._breakers: "list[CircuitBreaker] | None" = [
                self._make_breaker(j, self._breaker_config, breaker_clock)
                for j in range(self.n_nodes)]
        else:
            self._breaker_config = BreakerConfig()
            self._breakers = None
        self._probe_timeout_s = probe_timeout_s
        #: Per-node store-operation failures (satellite: partitions are
        #: visible — every increment pairs with log event id 3).
        self.node_errors = [0] * self.n_nodes
        #: Requests failed fast against quarantined nodes (no fallback).
        self.shed = 0
        #: Decisions served by the local degraded fallback.
        self.degraded_decisions = 0
        self._registry = None

        # Background loop for the blocking surface (same pattern as
        # RemoteBucketStore): lets blocking callers fan out to all nodes
        # concurrently from any thread, loop or no loop.
        self._io_loop: asyncio.AbstractEventLoop | None = None
        self._io_thread: threading.Thread | None = None
        self._thread_gate = threading.Lock()
        self._closed = False

    @property
    def _resilient(self) -> bool:
        return self._breakers is not None or self._degraded is not None

    def _make_breaker(self, j: int, config: BreakerConfig,
                      clock: Callable[[], float]) -> CircuitBreaker:
        def on_transition(old: str, new: str) -> None:
            log.breaker_transition(j, old, new)
            if self.flight_recorder is not None:
                self.flight_recorder.record("breaker", node=j, old=old,
                                            new=new)
                if new == CircuitBreaker.OPEN:
                    self.flight_recorder.auto_dump("breaker_open",
                                                   {"node": j})
            if new == CircuitBreaker.CLOSED and self._degraded is not None:
                # Rejoin: the authoritative node rules again. The
                # outage's envelope GRANTS are debited against its
                # buckets (best-effort, async) instead of silently
                # discarded — otherwise every degraded grant would be
                # over-admission the authoritative state never heard of.
                grants = self._degraded.drain_node(j)
                if grants:
                    self._spawn(self._rejoin_debit(j, grants))

        return CircuitBreaker(config, clock=clock,
                              on_transition=on_transition)

    # -- background work ---------------------------------------------------
    def _spawn(self, coro) -> None:
        """Run a coroutine in the background, always on the cluster's
        OWN I/O loop (a breaker transition can fire on any caller's
        loop — cancelling foreign-loop tasks from aclose would not be
        thread-safe). Tracked as concurrent futures, whose ``cancel`` is
        thread-safe from wherever aclose runs."""
        if self._closed:
            coro.close()
            return
        fut = asyncio.run_coroutine_threadsafe(coro, self._ensure_loop())
        self._bg_tasks.add(fut)
        fut.add_done_callback(self._bg_tasks.discard)

    async def _rejoin_debit(self, j: int,
                            grants: "list[tuple[str, str, float, float, float]]"
                            ) -> None:
        """Charge a rejoined node's buckets for the grants its degraded
        envelope served (satellite bugfix). Saturating by construction
        (:func:`placement.saturating_drain`): the bucket lands at (or
        near) empty, never negative, and a failure just leaves the grant
        unreconciled (bounded by the envelope budget, the pre-existing
        posture)."""
        node = self.nodes[j]
        for key, kind, a, b, count in grants:
            n = int(math.ceil(count))
            if n <= 0:
                continue
            try:
                if kind in ("window", "fwindow"):
                    window_sec = a / b if b > 0 else 1.0
                    op = (node.fixed_window_acquire if kind == "fwindow"
                          else node.window_acquire)
                    await placement_mod.saturating_drain(
                        lambda m: op(key, m, a, window_sec), n)
                else:
                    await placement_mod.saturating_drain(
                        lambda m: node.acquire(key, m, a, b), n)
                self.rejoin_debits += 1
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # The node just rejoined; if it flaps again the breaker
                # owns it — the unreconciled grant stays inside the
                # envelope bound. Visible, not silent.
                self._note_scrape_error(j, exc)

    # -- routing -----------------------------------------------------------
    def node_index_of(self, key: str) -> int:
        """Index of the node that owns ``key`` under the current
        placement epoch — THE routing truth; every lane (scalar, bulk,
        blocking, submitter) and every external consumer (examples,
        benchmarks) goes through the map, never a modulus."""
        return int(self.placement.node_of(key))

    def node_of(self, key: str) -> BucketStore:
        """The node that owns ``key`` under the current placement epoch
        (every client holding the same epoch routes identically)."""
        return self.nodes[self.node_index_of(key)]

    # -- failure bookkeeping -------------------------------------------------
    def _note_node_error(self, j: int, exc: BaseException) -> None:
        """Every SERVING-path node failure funnels here: counter +
        structured log (event id 3) + breaker failure + flight-recorder
        frame. Nothing is silently swallowed (the old ``except: pass``
        posture). Diagnostics scrapes use :meth:`_note_scrape_error`
        instead — a failed scrape is visible but must not advance the
        breaker that gates admission traffic."""
        self._note_scrape_error(j, exc)
        if self._breakers is not None:
            self._breakers[j].record_failure()
        if self.flight_recorder is not None:
            self.flight_recorder.record("node_error", node=j,
                                        error=repr(exc))

    def _note_scrape_error(self, j: int, exc: BaseException) -> None:
        """Counter + log for a failed metrics/stats scrape (no breaker,
        no flight frame — see :meth:`_note_node_error`)."""
        self.node_errors[j] += 1
        log.cluster_node_error(j, exc)

    def _shed_or_fallback(self, j: int, fallback):
        """The quarantined-node decision: serve the degraded fallback
        when configured, else shed fast with a typed error."""
        if fallback is None or self._degraded is None:
            self.shed += 1
            raise NodeUnavailableError(
                f"cluster node {j} is quarantined (circuit open)")
        self.degraded_decisions += 1
        return fallback()

    async def _ping_node(self, j: int) -> bool:
        """Await node ``j``'s ping surface under the probe timeout.
        Returns False when the node has none (in-process nodes whose
        liveness is settled elsewhere); ping failures propagate."""
        ping = getattr(self.nodes[j], "ping", None)
        if not callable(ping):
            return False
        try:
            coro = ping(timeout_s=self._probe_timeout_s)
        except TypeError:  # in-process nodes: plain ping()
            coro = ping()
        await coro
        return True

    async def _probe(self, j: int) -> bool:
        """Half-open health probe: ping the node (nodes without a ping
        surface let the real request itself settle the probe). Returns
        whether the node may be used for the request that won the
        probe slot."""
        assert self._breakers is not None
        try:
            if not await self._ping_node(j):
                return True
        except asyncio.CancelledError:
            # Cancellation is no verdict on the node: free the slot so
            # the next caller probes instead of rejecting forever.
            self._breakers[j].release_probe()
            raise
        except Exception as exc:
            self._note_node_error(j, exc)  # records the breaker failure
            return False                   # → back to OPEN
        self._breakers[j].record_success()
        return True

    async def _guarded_call(self, j: int, call, fallback=None):
        """Run one node operation under the node's breaker: OPEN sheds
        (or serves the fallback), HALF_OPEN probes first, failures are
        noted (counter + log + breaker) and — when a fallback exists —
        absorbed into a degraded decision instead of an error."""
        br = self._breakers[j] if self._breakers is not None else None
        if br is not None:
            verdict = br.allow()
            if verdict == "probe" and not await self._probe(j):
                verdict = "reject"
            if verdict == "reject":
                return self._shed_or_fallback(j, fallback)
        try:
            res = await call()
        except asyncio.CancelledError:
            if br is not None:
                # The probe-winning request may be the one cancelled (a
                # ping-less node settles via the real call): free the
                # slot — no-op otherwise.
                br.release_probe()
            raise
        except Exception as exc:
            if (isinstance(exc, wire.RemoteStoreError)
                    and (MOVED_ERROR_PREFIX in str(exc)
                         or placement_mod.HANDOFF_DEFERRAL_PREFIX
                         in str(exc))):
                # Stale routing or a parked-key deferral mid-handoff:
                # the node is HEALTHY — no breaker advance, no degraded
                # absorption. _routed chases a move; a deferral clears
                # within one handoff window (a breaker trip here would
                # quarantine the node's whole keyspace as a side effect
                # of a routine migration).
                if br is not None:
                    br.record_success()
                raise
            self._note_node_error(j, exc)
            if fallback is not None and self._degraded is not None:
                self.degraded_decisions += 1
                return fallback()
            raise
        if br is not None:
            br.record_success()
        return res

    async def _routed(self, key: str, make_call, make_fallback=None):
        """Route ``key`` through the placement map, run the op under the
        node's breaker, and chase at most one placement move: a node
        answering the routable ``placement moved`` error means this
        client's map is stale — refetch and re-route once, then let the
        error surface (a second move mid-call is indistinguishable from
        a flapping coordinator)."""
        for attempt in (0, 1):
            j = self.node_index_of(key)
            try:
                if not self._resilient:
                    return await make_call(j)
                return await self._guarded_call(
                    j, lambda: make_call(j),
                    fallback=(None if make_fallback is None
                              else lambda: make_fallback(j)))
            except wire.RemoteStoreError as exc:
                if attempt == 0 and MOVED_ERROR_PREFIX in str(exc):
                    await self.refresh_placement()
                    if self.node_index_of(key) != j:
                        continue
                raise

    # -- blocking-surface plumbing ------------------------------------------
    def _ensure_loop(self) -> asyncio.AbstractEventLoop:
        loop = self._io_loop
        if loop is not None:
            return loop
        with self._thread_gate:
            if self._io_loop is None:
                loop = asyncio.new_event_loop()
                ready = threading.Event()

                def run() -> None:
                    asyncio.set_event_loop(loop)
                    ready.set()
                    loop.run_forever()

                t = threading.Thread(target=run, name="cluster-store-io",
                                     daemon=True)
                t.start()
                ready.wait()
                self._io_loop = loop
                self._io_thread = t
        return self._io_loop

    def _blocking(self, coro):
        return asyncio.run_coroutine_threadsafe(
            coro, self._ensure_loop()).result()

    # -- lifecycle ----------------------------------------------------------
    async def connect(self) -> None:
        """Eagerly connect every node (each node also lazily connects on
        first use, the reference's posture — this is for fail-fast setups)."""
        await asyncio.gather(*(n.connect() for n in self.nodes))

    async def aclose(self) -> None:
        if self._closed:
            return
        self._closed = True
        # Background work (rejoin debits, placement refreshes) must not
        # outlive the clients it would call through. These are
        # concurrent futures on OUR I/O loop: cancel is thread-safe,
        # and anything already running dies with the loop teardown
        # below (never the caller's loop).
        for f in list(self._bg_tasks):
            f.cancel()
        # return_exceptions: one node's failed close must not skip the
        # others or leak the I/O loop thread below.
        outs = await asyncio.gather(*(n.aclose() for n in self.nodes),
                                    return_exceptions=True)
        loop = self._io_loop
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)
            if self._io_thread is not None:
                # to_thread: a 5s worst-case join must not stall the
                # CALLER's event loop (drl-check async-blocking).
                await asyncio.to_thread(self._io_thread.join, 5.0)
            # Close only a stopped loop: if the join timed out the loop
            # thread is still running, and loop.close() would raise
            # RuntimeError here — masking any node-close exception
            # collected above (the daemon thread dies with the process).
            if self._io_thread is None or not self._io_thread.is_alive():
                loop.close()
            self._io_loop = None
        for out in outs:
            if isinstance(out, BaseException):
                raise out

    # -- elastic membership / live migration (docs/OPERATIONS.md §9) --------
    @property
    def active_nodes(self) -> list[int]:
        """Node indices currently eligible to own slots (not drained)."""
        return [j for j in range(self.n_nodes) if j not in self.drained]

    async def refresh_placement(self) -> int:
        """Adopt the highest placement epoch any reachable node reports
        (the client half of the MOVED-redirect loop). A map naming node
        indices this client has no transport for is ignored — this
        client's topology must be extended (``add_node``) first."""
        async def one(j: int, node: BucketStore) -> "dict | None":
            fetch = getattr(node, "placement_fetch", None)
            if not callable(fetch):
                return None
            if self._breakers is not None \
                    and self._breakers[j].quarantined():
                return None  # don't stall a refresh behind a dead node
            try:
                return await fetch(timeout_s=self._probe_timeout_s)
            except Exception as exc:
                self._note_scrape_error(j, exc)
                return None

        # Concurrent fan-out: a stale-mapped caller's MOVED chase waits
        # one probe timeout, not one per node.
        payloads = await asyncio.gather(*(one(j, n) for j, n in
                                          enumerate(self.nodes)))
        best = self.placement
        for payload in payloads:
            if payload is None:
                continue
            if payload.get("epoch", -1) > best.epoch and "map" in payload:
                candidate = PlacementMap.from_dict(payload["map"])
                if max(candidate.nodes_in_use(), default=0) < self.n_nodes:
                    best = candidate
        self.placement = best
        return best.epoch

    async def _health_gate(self, j: int) -> None:
        """A node must prove liveness before taking ownership (the PR-5
        health-gated-membership posture): its breaker must not be open,
        and its ping must answer inside the probe timeout."""
        if self._breakers is not None and self._breakers[j].quarantined():
            raise PlacementError(
                f"node {j} is quarantined (circuit open); it cannot "
                "take ownership")
        try:
            await self._ping_node(j)
        except Exception as exc:
            if self._breakers is not None:
                self._breakers[j].record_failure()
            raise PlacementError(
                f"node {j} failed its health probe: {exc!r}") from exc

    async def _announce_to(self, j: int, payload: dict,
                           strict: bool) -> None:
        node = self.nodes[j]
        ann = getattr(node, "placement_announce", None)
        if not callable(ann):
            return  # in-process node: client-side routing only
        try:
            await ann(payload)
        except Exception as exc:
            self._note_scrape_error(j, exc)
            if strict:
                raise

    def _keep_for(self, slots: "set[int]", keys: "set[str]"):
        # One shared selection rule with the server-side pull — the two
        # lanes diverging here is exactly the drive-caught class of bug.
        return placement_mod.keep_predicate(
            self.placement.n_slots, self.placement.overrides, slots, keys)

    async def _pull_from(self, src: int, slots: "list[int]",
                         keys: "list[str]", target_epoch: int
                         ) -> "dict | None":
        """One source's export: the wire pull for remote nodes (parks +
        debits server-side), a direct snapshot extract for in-process
        ones. ``None`` = the source is unreachable — the dead-leave
        case: its state is lost and the new owners serve init-on-miss
        (the reference's wiped-state posture, now scoped to one node)."""
        node = self.nodes[src]
        req = {"target_epoch": target_epoch,
               "window_s": self._handoff_window_s}
        if slots:
            req["slots"] = slots
        if keys:
            req["keys"] = keys
        pull = getattr(node, "migrate_pull", None)
        try:
            if callable(pull):
                out = await pull(req)
                entries = out.get("entries") or {}
                # Paged reply: a big export chunks server-side so every
                # frame fits MAX_FRAME; pages 1..N-1 come from the
                # handoff cache (idempotent — retries included).
                for page in range(1, int(out.get("pages", 1))):
                    more = await pull({**req, "page": page})
                    entries = placement_mod.merge_entries(
                        entries, more.get("entries") or {})
                return entries
            if hasattr(node, "snapshot"):
                # In-process lane: balances ship EXACTLY (no envelope —
                # there is no server-side park to serve one from), and
                # the source is drained of the shipped amount in the
                # same breath so a task interleaving between this pull
                # and the commit cannot spend a balance the new owner
                # already received (the remote lane's debit_source
                # contract, keep_envelope=False).
                # to_thread mirrors the server-side pull: a device
                # store's snapshot() pulls whole slot arrays to host —
                # run it off-loop so the export never stalls the
                # coordinator's serving path.
                entries = await asyncio.to_thread(
                    placement_mod._export_from_store,
                    node, self._keep_for(set(slots), set(keys)))
                await placement_mod.debit_source(
                    node, entries,
                    placement_mod.DEFAULT_ENVELOPE_FRACTION,
                    keep_envelope=False)
                return entries
        except (ConnectionError, OSError, asyncio.TimeoutError,
                NodeUnavailableError) as exc:
            self._note_node_error(src, exc)
            # Ambiguity guard: a timed-out/reset pull may still have
            # EXECUTED (parking + debiting the source). Declaring state
            # lost is only sound when the node is actually dead — so
            # probe it. Alive ⇒ abort the migration instead (the parked
            # state unparks on the abort announce, or self-heals at
            # window expiry); truly dead ⇒ init-on-miss is all there is.
            try:
                alive = await self._ping_node(src)
            except asyncio.CancelledError:
                raise
            # The probe failing IS the verdict (node dead ⇒ state
            # genuinely lost); the pull failure was counted above.
            # drl-check: ok(swallowed-exception)
            except Exception:
                alive = False
            if alive:
                raise PlacementError(
                    f"pull from node {src} failed ({exc!r}) but the "
                    "node is alive — aborting rather than guessing "
                    "its state was lost") from exc
            return None
        return {}

    async def _apply_placement(self, target: PlacementMap,
                               moves: "Mapping[int, int]",
                               moved_keys: "Mapping[str, int] | None" = None,
                               reason: str = "rebalance") -> None:
        """One membership change, end to end: health-gate the new
        owners, pull (park + debit) from the old ones, push the state
        batches, then commit by announcing the target epoch — new owners
        first, old owners last, so at every instant each key has at
        least one node willing to serve it (authoritatively, or from the
        old owner's bounded envelope). Any pre-commit failure aborts
        cleanly back to the old epoch (the parked state unparks); the
        soak asserts every migration lands in exactly one of those two
        states. Callers hold ``_membership_lock``."""
        moved_keys = dict(moved_keys or {})
        src_of_slot = {int(s): int(self.placement.slot_owner[s])
                       for s in moves}
        key_src = {k: self.node_index_of(k) for k in moved_keys}
        dsts = set(moves.values()) | set(moved_keys.values())
        # A node may be both (slots in, slots out on one rebalance).
        srcs = set(src_of_slot.values()) | set(key_src.values())
        event = {
            "type": "migrate", "reason": reason,
            "from_epoch": self.placement.epoch,
            "target_epoch": target.epoch,
            "moves": {int(s): int(d) for s, d in moves.items()},
            "keys": {k: int(d) for k, d in moved_keys.items()},
            "t_start": time.monotonic(),
        }
        try:
            # Inside the try: an injected fault here must take the abort
            # path (typed PlacementError, bookkeeping, callers' drained-
            # set rollback), not escape as a raw FaultInjectedError.
            await faults.seam("cluster.migrate")
            for j in sorted(dsts):
                await self._health_gate(j)
            if not self._announced:
                # Bootstrap: nodes must hold the CURRENT map before any
                # pull (the gate and slot arithmetic need it). Strict
                # only for destinations — a DEAD source is the
                # unplanned-leave case, and its pull below degrades to
                # state-lost rather than blocking the drain.
                for j in range(self.n_nodes):
                    await self._announce_to(
                        j, {"map": self.placement.to_dict(),
                            "node_id": j},
                        strict=(j in dsts))
                self._announced = True
            pulls: dict[int, dict] = {}
            lost: list[int] = []
            for src in sorted(srcs):
                slots = [s for s, owner in src_of_slot.items()
                         if owner == src]
                keys = [k for k, owner in key_src.items()
                        if owner == src]
                if not slots and not keys:
                    continue
                await faults.seam("cluster.migrate")
                entries = await self._pull_from(src, slots, keys,
                                                target.epoch)
                if entries is None:
                    lost.append(src)
                elif placement_mod.entry_count(entries):
                    pulls[src] = entries
            if lost:
                event["state_lost_from"] = lost
            for src, entries in pulls.items():
                per_dst = placement_mod.split_entries(entries,
                                                      target.node_of)
                for dst, sub in sorted(per_dst.items()):
                    if dst == src:
                        continue  # state already lives there
                    node = self.nodes[dst]
                    push = getattr(node, "migrate_push", None)
                    for bid, chunk in enumerate(
                            placement_mod.chunk_entries(sub)):
                        await faults.seam("cluster.migrate")
                        if callable(push):
                            # Batch ids are the receiver's exactly-once
                            # dedup unit — namespace them by SOURCE so
                            # two sources' chunk 0 never collide.
                            await push({"target_epoch": target.epoch,
                                        "batch": (src << 20) | bid,
                                        "entries": chunk})
                        else:
                            await placement_mod.import_entries(node,
                                                               chunk)
        except Exception as exc:
            # Clean abort to the old epoch: unpark every pulled source
            # AND clear every destination's push ledger for the dead
            # target epoch — a retried migration reuses it, and stale
            # dedup entries would silently drop the retry's batches.
            for j in sorted(srcs | dsts):
                await self._announce_to(
                    j, {"abort_epoch": target.epoch}, strict=False)
            event.update(type="abort", error=repr(exc),
                         t_end=time.monotonic())
            self.migration_aborts += 1
            self._log_migration(event)
            if isinstance(exc, PlacementError):
                raise
            raise PlacementError(
                f"migration to epoch {target.epoch} aborted: "
                f"{exc!r}") from exc
        # Commit: destinations adopt first (they start serving the
        # moment a client learns the epoch), sources last (their parked
        # state drops and 'moved' answers take over), bystanders after.
        order = (sorted(dsts) + [j for j in sorted(srcs)
                                 if j not in dsts]
                 + [j for j in range(self.n_nodes)
                    if j not in dsts and j not in srcs])
        commit_errors = 0
        for j in order:
            try:
                await faults.seam("cluster.migrate")
                await self._announce_to(
                    j, {"map": target.to_dict(), "node_id": j},
                    strict=False)
            except Exception as exc:
                # Past the point of no return (state batches applied):
                # the commit presses on. A straggler node keeps the old
                # epoch until it answers a request with 'placement
                # moved' or the next announce reaches it; visible here,
                # in the event record, and in the node-error counter.
                commit_errors += 1
                self._note_scrape_error(j, exc)
        self.placement = target
        self.migrations += 1
        event.update(type="commit", t_end=time.monotonic(),
                     commit_errors=commit_errors)
        self._log_migration(event)

    _MIGRATION_LOG_CAP = 512

    def _log_migration(self, event: dict) -> None:
        self.migration_log.append(event)
        if len(self.migration_log) > self._MIGRATION_LOG_CAP:
            del self.migration_log[: -self._MIGRATION_LOG_CAP]
        log.cluster_migration(event)

    async def rebalance(self, reason: str = "rebalance") -> int:
        """Even slot ownership over the active nodes, migrating state
        along. No-op (same epoch) when already balanced."""
        async with self._membership_lock:
            return await self._rebalance_locked(reason)

    async def _rebalance_locked(self, reason: str) -> int:
        if not self._announced:
            # A fresh coordinator may be attaching to an already-
            # resharded fleet: adopt the fleet's highest epoch BEFORE
            # computing a target, or the bootstrap announce below would
            # push a stale map (and the destinations would rightly
            # refuse it as stale, wedging every membership op until
            # someone called refresh_placement by hand).
            await self.refresh_placement()
        active = self.active_nodes
        moves = self.placement.rebalance_moves(active)
        # Overrides pinned to a drained node follow the rebalance too.
        stranded = {k: j for k, j in self.placement.overrides.items()
                    if j not in active}
        if not moves and not stranded:
            return self.placement.epoch
        counts = self.placement.slot_counts(self.n_nodes)
        moved_keys = {k: min(active, key=lambda a: counts[a])
                      for k in stranded}
        target = self.placement.with_assignments(
            moves, set_overrides=moved_keys or None)
        await self._apply_placement(target, moves, moved_keys, reason)
        return target.epoch

    async def add_node(self, store: "BucketStore | None" = None, *,
                       address: "tuple[str, int] | None" = None,
                       url: "str | None" = None,
                       rebalance: bool = True) -> int:
        """Join: append a node (same config ladder as the constructor),
        health-gate it, and — unless ``rebalance=False`` — migrate an
        even share of slots (with their state) onto it. Returns the new
        node's index. Node indices are stable identities: the list only
        ever appends."""
        if store is not None:
            node: BucketStore = store
        elif address is not None:
            node = RemoteBucketStore(address=address,
                                     **self._remote_kwargs)
        elif url is not None:
            node = RemoteBucketStore(url=url, **self._remote_kwargs)
        else:
            raise ValueError("one of store, address, or url is required")
        async with self._membership_lock:
            j = self.n_nodes
            self.nodes.append(node)
            self.n_nodes += 1
            self.node_errors.append(0)
            if self._breakers is not None:
                self._breakers.append(self._make_breaker(
                    j, self._breaker_config, self._breaker_clock))
            self._registry = None  # per-node families re-enumerate lazily
            try:
                await self._health_gate(j)
            except PlacementError:
                self.drained.add(j)  # joined but unfit: owns nothing yet
                raise
            if rebalance:
                await self._rebalance_locked(reason=f"join:{j}")
            return j

    async def drain_node(self, j: int) -> int:
        """Planned leave: migrate node ``j``'s slots (and their state)
        to the survivors, then stop routing to it. The node object stays
        in ``nodes`` — indices are identities — and ``rejoin_node``
        folds it back in."""
        if not 0 <= j < self.n_nodes:
            raise ValueError(f"no node {j}")
        async with self._membership_lock:
            if len(self.active_nodes) <= 1 and j in self.active_nodes:
                raise PlacementError(
                    "cannot drain the last active node")
            self.drained.add(j)
            try:
                return await self._rebalance_locked(reason=f"drain:{j}")
            except PlacementError:
                self.drained.discard(j)  # the drain never happened
                raise

    async def rejoin_node(self, j: int) -> int:
        """Fold a drained node back into the slot table (health-gated),
        migrating an even share of slots back onto it."""
        async with self._membership_lock:
            if j not in self.drained:
                return self.placement.epoch
            await self._health_gate(j)
            self.drained.discard(j)
            try:
                return await self._rebalance_locked(reason=f"rejoin:{j}")
            except PlacementError:
                self.drained.add(j)
                raise

    async def replace_node(self, j: int, *,
                           address: "tuple[str, int] | None" = None,
                           store: "BucketStore | None" = None) -> None:
        """The rolling-restart "LB switch": swap node ``j``'s transport
        for its restarted successor. The INDEX — the placement identity
        — keeps its slots, so no map change and no migration happens
        here; the state itself rode the drain-and-handoff shutdown
        (``BucketStoreServer.shutdown(successor=…)``) or the restarted
        process's checkpoint restore (docs/OPERATIONS.md §10). The
        successor is health-gated before it takes the slot (its breaker
        is rebuilt closed); on a failed gate the old transport stays —
        a botched restart must not unseat a still-working node."""
        if not 0 <= j < self.n_nodes:
            raise ValueError(f"no node {j}")
        if (address is None) == (store is None):
            raise ValueError("exactly one of address= / store= required")
        new = store if store is not None else RemoteBucketStore(
            address=address, **self._remote_kwargs)
        async with self._membership_lock:
            old = self.nodes[j]
            self.nodes[j] = new
            try:
                await self._health_gate(j)
            except PlacementError:
                self.nodes[j] = old
                aclose = getattr(new, "aclose", None)
                if callable(aclose) and store is None:
                    await aclose()
                raise
            if self._breakers is not None:
                # Fresh breaker, born closed: the restart gap's failures
                # belong to the RETIRED transport, not the successor.
                self._breakers[j] = self._make_breaker(
                    j, self._breaker_config, self._breaker_clock)
            await self._replay_config_to(j)
        aclose = getattr(old, "aclose", None)
        if callable(aclose):
            try:
                await aclose()
            except Exception as exc:
                self._note_scrape_error(j, exc)

    async def _replay_config_to(self, j: int) -> None:
        """Hand a (re)joining node the fleet's committed live-config
        rules (the restart-survival half of mutate_config): fetch the
        highest-version snapshot any OTHER node holds and adopt it onto
        node ``j``. Idempotent and version-monotonic server-side, so a
        duplicate replay is a no-op; a node restored from its
        predecessor's drain already adopted the same rules there."""
        ann = getattr(self.nodes[j], "config_announce", None)
        if not callable(ann):
            return
        best: "dict | None" = None
        for i, node in enumerate(self.nodes):
            if i == j:
                continue
            fetch = getattr(node, "config_fetch", None)
            if not callable(fetch):
                continue
            try:
                payload = await fetch(timeout_s=self._probe_timeout_s)
            except Exception as exc:
                self._note_scrape_error(i, exc)
                continue
            if best is None or int(payload.get("version", 0)) > \
                    int(best.get("version", 0)):
                best = payload
        if best and int(best.get("version", 0)) > 0:
            try:
                await ann({"adopt": best})
            except Exception as exc:
                # Visible, not fatal: the node serves; a stale gate is
                # re-replayed by the next membership op or mutation.
                self._note_scrape_error(j, exc)

    async def split_hot_key(self, key: str,
                            target: "int | None" = None) -> int:
        """Hot-shard split: pin one key to its own node via a placement
        override, migrating its state along — the heavy-hitter sketch's
        top-K is the feed (:meth:`split_hot_keys`). Returns the node the
        key now lives on."""
        async with self._membership_lock:
            if not self._announced:
                await self.refresh_placement()  # see _rebalance_locked
            src = self.node_index_of(key)
            if target is None:
                counts = self.placement.slot_counts(self.n_nodes)
                candidates = [j for j in self.active_nodes if j != src]
                if not candidates:
                    return src  # nowhere to split to
                target = min(candidates, key=lambda a: int(counts[a]))
            if target == src:
                return src
            if target in self.drained:
                raise PlacementError(f"node {target} is drained")
            new_map = self.placement.with_assignments(
                set_overrides={key: target})
            await self._apply_placement(new_map, {}, {key: target},
                                        reason=f"hot-split:{key!r}")
            return target

    async def split_hot_keys(self, top_n: int = 1,
                             min_count: float = 0.0) -> list[str]:
        """Consult every node's heavy-hitter sketch (OP_STATS
        ``hot_keys``) and split the fleet-wide top ``top_n`` keys that
        are not already overrides. Returns the keys split.

        Sketch offers are COST-weighted on every lane (an N-token
        admission weighs N — utils/heavy_hitters.py), so the ranking
        here is admitted TOKENS, not request count: a key taking few
        huge-cost requests is as much a split candidate as one taking
        many small ones, and ``min_count`` is a token threshold. The
        per-tenant tokens/sec companion signal is OP_STATS
        ``token_velocity`` / ``drl_token_velocity``."""
        scores: dict[str, float] = {}
        st = await self.stats()
        for node_stats in st["nodes"]:
            for row in (node_stats.get("hot_keys") or {}).get("top", ()):
                scores[row["key"]] = scores.get(row["key"], 0.0) \
                    + float(row["count"])
        ranked = sorted(scores.items(), key=lambda kv: -kv[1])
        split: list[str] = []
        for key, count in ranked:
            if len(split) >= top_n:
                break
            if count < min_count or key in self.placement.overrides:
                continue
            await self.split_hot_key(key)
            # split_hot_key no-ops when there is nowhere to split to
            # (single active node): only report keys actually pinned,
            # or automation would claim an isolation that never
            # happened — and re-claim it on every invocation.
            if key in self.placement.overrides:
                split.append(key)
        return split

    # -- live config mutation (docs/OPERATIONS.md §10) -----------------------
    async def mutate_config(self, kind: str,
                            old: "tuple[float, float]",
                            new: "tuple[float, float]") -> int:
        """Cluster-wide live limit mutation: rewrite every node's
        ``(kind, old) → new`` config in place — balances carried through
        the epoch-rebase (runtime/liveconfig.py) — with no restart.

        Two-phase under the coordinator lock, the placement plane's
        discipline: **prepare** stages the rule on every node (pure
        validation — any failure aborts the whole mutation cleanly back
        to the old version, nothing served differently anywhere), then
        **commit** flips the gates in node order (first node → rest;
        from each node's flip, its stale traffic chases one routable
        "config moved" error onto the new config). The target version
        adopts the fleet's highest committed version + 1, so a fresh
        coordinator attaching to an already-mutated fleet can't go
        backwards — and a re-sent prepare/commit is idempotent at its
        version, making the whole op post-send-retry-safe
        (``_IDEMPOTENT_OPS``).

        In-process nodes (no wire, no gate) rebase directly at their
        commit position; their callers see the new config the moment
        this returns. Returns the committed config version."""
        from distributedratelimiting.redis_tpu.runtime import liveconfig

        rule = liveconfig.ConfigRule(kind, tuple(old), tuple(new))
        async with self._membership_lock:
            # Adopt the fleet's highest committed version (reachable
            # nodes only — a dead node catches up via re-prepare when
            # the operator re-runs the mutation after its restart).
            best = 0
            for j, node in enumerate(self.nodes):
                fetch = getattr(node, "config_fetch", None)
                if not callable(fetch):
                    continue
                try:
                    payload = await fetch(
                        timeout_s=self._probe_timeout_s)
                    best = max(best, int(payload.get("version", 0)))
                except Exception as exc:
                    self._note_scrape_error(j, exc)
            version = best + 1
            event = {"type": "config", "kind": kind,
                     "old": list(rule.old), "new": list(rule.new),
                     "version": version, "t_start": time.monotonic()}
            wired = [j for j, n in enumerate(self.nodes)
                     if callable(getattr(n, "config_announce", None))]
            try:
                await faults.seam("cluster.config")
                # Phase 1 — prepare everywhere, strictly: a node that
                # cannot stage the rule vetoes the mutation while the
                # old config still serves everywhere.
                for j in wired:
                    await self.nodes[j].config_announce(
                        {"prepare": rule.to_dict(), "version": version})
            except Exception as exc:
                for j in wired:
                    try:
                        await self.nodes[j].config_announce(
                            {"abort": version})
                    except Exception as abort_exc:
                        self._note_scrape_error(j, abort_exc)
                event.update(type="config_abort", error=repr(exc),
                             t_end=time.monotonic())
                self.config_aborts += 1
                self._log_migration(event)
                if isinstance(exc, liveconfig.ConfigError):
                    raise
                raise liveconfig.ConfigError(
                    f"config mutation to version {version} aborted: "
                    f"{exc!r}") from exc
            # Phase 2 — commit, first node → rest. Past the first
            # successful flip the mutation presses on (a straggler keeps
            # serving the old table until the operator re-runs the
            # mutation — visible in the event record, never silent).
            commit_errors = 0
            for j in wired:
                try:
                    await self.nodes[j].config_announce(
                        {"commit": version})
                except Exception as exc:
                    commit_errors += 1
                    self._note_scrape_error(j, exc)
            for j, node in enumerate(self.nodes):
                if j in wired:
                    continue
                try:
                    self.config_rebased_rows += \
                        await liveconfig._rebase_state(node, rule)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    # Past the point of no return (wired nodes already
                    # committed): ANY rebase failure — not just the
                    # typed enumeration one — degrades to init-on-miss
                    # for this node's keys, counted + logged, never an
                    # exception out of a mutation the fleet committed.
                    commit_errors += 1
                    self._note_scrape_error(j, exc)
            self.config_mutations += 1
            event.update(type="config_commit", t_end=time.monotonic(),
                         commit_errors=commit_errors)
            self._log_migration(event)
            return version

    # -- single-key ops: route, guard, forward -------------------------------
    async def acquire(self, key: str, count: int, capacity: float,
                      fill_rate_per_sec: float) -> AcquireResult:
        return await self._routed(
            key,
            lambda j: self.nodes[j].acquire(key, count, capacity,
                                            fill_rate_per_sec),
            lambda j: self._degraded.acquire(
                j, key, count, capacity, fill_rate_per_sec, "bucket"))

    def acquire_blocking(self, key: str, count: int, capacity: float,
                         fill_rate_per_sec: float) -> AcquireResult:
        if self._resilient:
            return self._blocking(self.acquire(key, count, capacity,
                                               fill_rate_per_sec))
        return self.node_of(key).acquire_blocking(key, count, capacity,
                                                  fill_rate_per_sec)

    # -- hierarchical tenant → key admission (runtime/admission.py) ----------
    def _degraded_hier(self, j: int, tenant: str, key: str, count: int,
                       tcap: float, trate: float, cap: float,
                       rate: float, priority: int) -> AcquireResult:
        """Two-level degraded fallback for a quarantined tenant node:
        tenant envelope then key envelope, grant iff both, priority
        shed order applied at both levels via the shared gate (a
        tenant-envelope debit on a key deny stays debited — envelope
        over-conservatism, the safe direction)."""
        par = self._degraded.acquire(j, tenant, count, tcap, trate,
                                     "bucket", priority)
        if not par.granted:
            return AcquireResult(False, par.remaining)
        ch = self._degraded.acquire(j, key, count, cap, rate,
                                    "bucket", priority)
        return AcquireResult(ch.granted,
                             min(par.remaining, ch.remaining))

    async def acquire_hierarchical(self, tenant: str, key: str,
                                   count: int, tenant_capacity: float,
                                   tenant_fill_rate_per_sec: float,
                                   capacity: float,
                                   fill_rate_per_sec: float, *,
                                   priority: int = 0) -> AcquireResult:
        """Routed by TENANT, not key: the parent tenant bucket must
        live whole on one node (a per-node split would multiply the
        tenant's budget by the node count), so a tenant's hierarchical
        admission — and its keys' child buckets — all land on the
        tenant's owner under the placement map. The degraded fallback
        honors the priority shed order (scavenger sheds first)."""
        return await self._routed(
            tenant,
            lambda j: self.nodes[j].acquire_hierarchical(
                tenant, key, count, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority=priority),
            lambda j: self._degraded_hier(
                j, tenant, key, count, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority))

    def acquire_hierarchical_blocking(self, tenant: str, key: str,
                                      count: int,
                                      tenant_capacity: float,
                                      tenant_fill_rate_per_sec: float,
                                      capacity: float,
                                      fill_rate_per_sec: float, *,
                                      priority: int = 0) -> AcquireResult:
        if self._resilient:
            return self._blocking(self.acquire_hierarchical(
                tenant, key, count, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority=priority))
        return self.node_of(tenant).acquire_hierarchical_blocking(
            tenant, key, count, tenant_capacity,
            tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
            priority=priority)

    async def acquire_hierarchical_many(self, tenants, keys, counts,
                                        tenant_capacity: float,
                                        tenant_fill_rate_per_sec: float,
                                        capacity: float,
                                        fill_rate_per_sec: float, *,
                                        with_remaining: bool = True,
                                        priority: int = 0
                                        ) -> "BulkAcquireResult":
        """Bulk hierarchical: rows fan out BY TENANT (each tenant's
        group is one node's call — see :meth:`acquire_hierarchical`),
        results scatter back in row order. Quarantined groups serve the
        two-level degraded envelope row-by-row."""
        n = len(keys)
        granted = np.zeros(n, bool)
        remaining = (np.zeros(n, np.float32) if with_remaining
                     else None)
        counts_np = np.asarray(counts, np.int64)
        by_tenant: dict[str, list[int]] = {}
        for i, t in enumerate(tenants):
            by_tenant.setdefault(t, []).append(i)

        async def one_tenant(tenant: str, idx: list[int]):
            sub_keys = [keys[i] for i in idx]
            sub_counts = counts_np[idx]

            def fallback(j):
                g = np.zeros(len(sub_keys), bool)
                r = np.zeros(len(sub_keys), np.float32)
                for i2, (k, c) in enumerate(zip(sub_keys, sub_counts)):
                    res = self._degraded_hier(
                        j, tenant, k, int(c), tenant_capacity,
                        tenant_fill_rate_per_sec, capacity,
                        fill_rate_per_sec, priority)
                    g[i2] = res.granted
                    r[i2] = res.remaining
                return BulkAcquireResult(g, r)

            return await self._routed(
                tenant,
                lambda j: self.nodes[j].acquire_hierarchical_many(
                    [tenant] * len(sub_keys), sub_keys, sub_counts,
                    tenant_capacity, tenant_fill_rate_per_sec,
                    capacity, fill_rate_per_sec,
                    with_remaining=with_remaining, priority=priority),
                fallback)

        # Tenant groups fan out concurrently (the flat bulk lane's
        # posture): one call's wall clock is the slowest node, not the
        # sum over tenants. Distinct tenants' decisions are independent.
        groups = list(by_tenant.items())
        results = await asyncio.gather(
            *(one_tenant(t, idx) for t, idx in groups))
        for (_t, idx), res in zip(groups, results):
            granted[idx] = res.granted
            if remaining is not None and res.remaining is not None:
                remaining[idx] = res.remaining
        return BulkAcquireResult(granted, remaining)

    # -- estimate-reserve-settle (runtime/reservations.py) -------------------
    async def reserve(self, rid: str, tenant: str, key: str,
                      estimate: "float | None",
                      tenant_capacity: float,
                      tenant_fill_rate_per_sec: float,
                      capacity: float, fill_rate_per_sec: float, *,
                      priority: int = 0,
                      ttl_s: "float | None" = None,
                      attempt: int = 0,
                      deadline_s: "float | None" = None):
        """Routed by TENANT like every hierarchical lane (the ledger
        entry must live with the tenant's owner so its settle finds
        it). The degraded fallback admits the estimate through the
        two-level envelope — bounded availability with NO hold (the
        quarantined owner's ledger is unreachable); the eventual
        settle answers the counted "unknown" no-op, conservative."""
        from distributedratelimiting.redis_tpu.runtime.reservations import (
            ReserveResult,
            fallback_charge,
        )

        charge = fallback_charge(estimate)

        def fallback(j):
            res = self._degraded_hier(
                j, tenant, key, charge, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority)
            return ReserveResult(res.granted,
                                 float(charge) if res.granted else 0.0,
                                 res.remaining, 0.0, fallback=True)

        return await self._routed(
            tenant,
            lambda j: self.nodes[j].reserve(
                rid, tenant, key, estimate, tenant_capacity,
                tenant_fill_rate_per_sec, capacity, fill_rate_per_sec,
                priority=priority, ttl_s=ttl_s, attempt=attempt,
                deadline_s=deadline_s),
            fallback)

    async def settle(self, rid: str, tenant: str, actual: float):
        """Settle routes to the tenant's owner (one MOVED chase like
        every keyed lane — the op is idempotent by rid, so the re-send
        after a placement refresh is safe). No degraded fallback: a
        settle against a quarantined owner surfaces the typed
        unavailability and the caller retries after rejoin — the TTL
        auto-settle bounds how long an unreachable ledger can hold."""
        return await self._routed(
            tenant,
            lambda j: self.nodes[j].settle(rid, tenant, actual))

    def peek_blocking(self, key: str, capacity: float,
                      fill_rate_per_sec: float) -> float:
        # No degraded value exists for a peek — it reports the
        # AUTHORITATIVE balance; a quarantined node surfaces the typed
        # shed error instead of a made-up number.
        for attempt in (0, 1):
            j = self.node_index_of(key)
            if self._breakers is not None \
                    and self._breakers[j].quarantined():
                self.shed += 1
                raise NodeUnavailableError(
                    f"cluster node {j} is quarantined (circuit open)")
            try:
                return self.nodes[j].peek_blocking(key, capacity,
                                                   fill_rate_per_sec)
            except wire.RemoteStoreError as exc:
                # Same one-MOVED chase as every other keyed lane: a
                # balance monitor doing only peeks must still converge
                # a stale map after a migration.
                if attempt == 0 and MOVED_ERROR_PREFIX in str(exc):
                    self._blocking(self.refresh_placement())
                    if self.node_index_of(key) != j:
                        continue
                raise

    def acquire_submitter(self, capacity: float, fill_rate_per_sec: float):
        if self._resilient:
            # The guarded path costs a route + breaker check per
            # request; resilience was asked for explicitly.
            async def submit(key: str, count: int) -> AcquireResult:
                return await self.acquire(key, count, capacity,
                                          fill_rate_per_sec)

            return submit
        # Hoist per-node submitters once; per request only the route
        # runs. A node that joins after the hoist gets its submitter
        # lazily — the list only ever appends (indices are stable).
        subs = [n.acquire_submitter(capacity, fill_rate_per_sec)
                for n in self.nodes]

        async def submit(key: str, count: int) -> AcquireResult:
            # Same one-MOVED chase as _routed: the fast lane must still
            # converge a stale map, or every call for a migrated key
            # fails forever. The refresh costs only on the error path.
            for attempt in (0, 1):
                j = self.node_index_of(key)
                while j >= len(subs):
                    subs.append(self.nodes[len(subs)].acquire_submitter(
                        capacity, fill_rate_per_sec))
                try:
                    return await subs[j](key, count)
                except wire.RemoteStoreError as exc:
                    if attempt == 0 and MOVED_ERROR_PREFIX in str(exc):
                        await self.refresh_placement()
                        if self.node_index_of(key) != j:
                            continue
                    raise

        return submit

    async def sync_counter(self, key: str, local_count: float,
                           decay_rate_per_sec: float) -> SyncResult:
        # No fallback on purpose: the approximate limiter OWNS its
        # degraded mode (keep serving from the last-known global score);
        # it needs the error, not a made-up sync result.
        return await self._routed(
            key, lambda j: self.nodes[j].sync_counter(
                key, local_count, decay_rate_per_sec))

    def sync_counter_blocking(self, key: str, local_count: float,
                              decay_rate_per_sec: float) -> SyncResult:
        if self._resilient:
            return self._blocking(self.sync_counter(key, local_count,
                                                    decay_rate_per_sec))
        return self.node_of(key).sync_counter_blocking(key, local_count,
                                                       decay_rate_per_sec)

    async def window_acquire(self, key: str, count: int, limit: float,
                             window_sec: float) -> AcquireResult:
        return await self._routed(
            key,
            lambda j: self.nodes[j].window_acquire(key, count, limit,
                                                   window_sec),
            lambda j: self._degraded.acquire(
                j, key, count, limit, limit / window_sec, "window"))

    def window_acquire_blocking(self, key: str, count: int, limit: float,
                                window_sec: float) -> AcquireResult:
        if self._resilient:
            return self._blocking(self.window_acquire(key, count, limit,
                                                      window_sec))
        return self.node_of(key).window_acquire_blocking(key, count, limit,
                                                         window_sec)

    async def fixed_window_acquire(self, key: str, count: int, limit: float,
                                   window_sec: float) -> AcquireResult:
        return await self._routed(
            key,
            lambda j: self.nodes[j].fixed_window_acquire(
                key, count, limit, window_sec),
            lambda j: self._degraded.acquire(
                j, key, count, limit, limit / window_sec, "fwindow"))

    def fixed_window_acquire_blocking(self, key: str, count: int,
                                      limit: float,
                                      window_sec: float) -> AcquireResult:
        if self._resilient:
            return self._blocking(self.fixed_window_acquire(
                key, count, limit, window_sec))
        return self.node_of(key).fixed_window_acquire_blocking(
            key, count, limit, window_sec)

    async def concurrency_acquire(self, key: str, count: int,
                                  limit: int) -> AcquireResult:
        # Semaphores are strict: a made-up degraded grant could exceed
        # the concurrency limit the moment the node returns. Deny.
        return await self._routed(
            key,
            lambda j: self.nodes[j].concurrency_acquire(key, count,
                                                        limit),
            lambda j: AcquireResult(False, 0.0))

    def concurrency_acquire_blocking(self, key: str, count: int,
                                     limit: int) -> AcquireResult:
        if self._resilient:
            return self._blocking(self.concurrency_acquire(key, count,
                                                           limit))
        return self.node_of(key).concurrency_acquire_blocking(key, count,
                                                              limit)

    async def concurrency_release(self, key: str, count: int) -> None:
        # A release against a quarantined node is absorbed (None): the
        # node's semaphore state resets with it anyway (init-on-miss).
        await self._routed(
            key, lambda j: self.nodes[j].concurrency_release(key, count),
            lambda j: None)

    def concurrency_release_blocking(self, key: str, count: int) -> None:
        if self._resilient:
            self._blocking(self.concurrency_release(key, count))
            return
        self.node_of(key).concurrency_release_blocking(key, count)

    # -- bulk ops: split by route, fan out, merge ---------------------------
    def _split(self, keys: Sequence[str]):
        """Group a bulk call by owning node, order-stably.

        Returns ``(order, bounds, keys_list)`` where ``order`` is a stable
        permutation grouping requests by node and ``bounds[j]:bounds[j+1]``
        slices node ``j``'s group. Stability keeps each node's sub-batch in
        arrival order, so per-node duplicate serialization is exactly the
        single-node bulk semantics.
        """
        keys = keys if isinstance(keys, list) else list(keys)
        # One native crc32 pass over the slot table, then the placement
        # take — the map (not a modulus) is the routing truth.
        routes = self.placement.route(keys)
        order = np.argsort(routes, kind="stable")
        bounds = np.searchsorted(routes[order],
                                 np.arange(self.n_nodes + 1))
        return order, bounds, keys

    def _bulk_degraded(self, j: int, sub_keys, sub_counts,
                       degraded_row) -> BulkAcquireResult:
        """Serve one node's bulk rows from the degraded fallback (a
        Python loop — this is the outage path, not the hot path)."""
        n = len(sub_keys)
        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32)
        for i, (k, c) in enumerate(zip(sub_keys, sub_counts)):
            res = degraded_row(j, k, int(c))
            granted[i] = res.granted
            remaining[i] = res.remaining
        self.degraded_decisions += n
        return BulkAcquireResult(granted, remaining)

    def _bulk_reject(self, j: int, sub_keys, sub_counts, degraded_row
                     ) -> "BulkAcquireResult | None":
        """A quarantined node's bulk group: degraded rows when possible,
        else the partial_failures contract ('deny' → None, rows stay
        denied; 'raise' → typed shed error)."""
        if degraded_row is not None and self._degraded is not None:
            return self._bulk_degraded(j, sub_keys, sub_counts,
                                       degraded_row)
        self.shed += len(sub_keys)
        if self._partial_failures == "raise":
            raise NodeUnavailableError(
                f"cluster node {j} is quarantined (circuit open)")
        return None

    async def _bulk_fan_out(self, keys, counts, call, with_remaining: bool,
                            degraded_row=None) -> BulkAcquireResult:
        n = len(keys)
        if n == 0:
            return BulkAcquireResult(
                np.zeros(0, bool),
                np.zeros(0, np.float32) if with_remaining else None)
        counts_np = np.asarray(counts, np.int64)
        if self.n_nodes == 1 and not self._resilient:
            return await call(self.nodes[0], keys, counts_np)
        order, bounds, keys = self._split(keys)

        tracer = tracing.get_tracer()
        live = [(j, int(bounds[j]), int(bounds[j + 1]))
                for j in range(self.n_nodes) if bounds[j] < bounds[j + 1]]
        # The whole fan-out is one span (a new root when the caller has
        # none, subject to the head-sampling coin): the per-node
        # children parent on it EXPLICITLY — if the coin fails here,
        # the nodes must not re-flip it N times and litter the buffer
        # with unrooted single-node traces.
        fspan = (tracer.start_span("cluster.fan_out",
                                   attrs={"nodes": len(live),
                                          "rows": int(n)})
                 if tracer.enabled else tracing._NULL_SPAN)
        fctx = fspan.context

        async def node_call(j: int, lo: int, hi: int):
            idx = order[lo:hi]
            sub_keys = [keys[i] for i in idx]
            sub_counts = counts_np[idx]
            # One child span per node: the fan-out share of a traced bulk
            # call decomposes into which node was slow.
            nspan = (tracer.start_span("cluster.node", parent=fctx,
                                       attrs={"node": j,
                                              "rows": int(hi - lo)})
                     if fctx is not None else tracing._NULL_SPAN)
            with nspan:
                br = (self._breakers[j] if self._breakers is not None
                      else None)
                if br is not None:
                    verdict = br.allow()
                    if verdict == "probe" and not await self._probe(j):
                        verdict = "reject"
                    if verdict == "reject":
                        nspan.set_status("degraded")
                        nspan.set_attr("breaker", br.state)
                        return self._bulk_reject(j, sub_keys, sub_counts,
                                                 degraded_row)
                try:
                    out = await call(self.nodes[j], sub_keys, sub_counts)
                except asyncio.CancelledError:
                    if br is not None:
                        br.release_probe()  # no-op unless we held it
                    raise
                except Exception as exc:
                    if (isinstance(exc, wire.RemoteStoreError)
                            and MOVED_ERROR_PREFIX in str(exc)):
                        # Stale map, not node failure: the node is
                        # HEALTHY — settle a half-open probe as a
                        # success (the scalar lane's rule; leaking the
                        # probe slot would quarantine the keyspace for a
                        # recovery window per stale bulk frame); refresh
                        # in the background so the NEXT call re-routes,
                        # and this frame's rows follow the
                        # partial_failures contract.
                        if br is not None:
                            br.record_success()
                        self._spawn(self.refresh_placement())
                        nspan.set_status("degraded")
                        if self._partial_failures == "raise":
                            raise
                        return None  # rows stay denied
                    self._note_node_error(j, exc)
                    nspan.set_status("degraded")
                    if degraded_row is not None \
                            and self._degraded is not None:
                        return self._bulk_degraded(j, sub_keys,
                                                   sub_counts,
                                                   degraded_row)
                    if self._partial_failures == "raise":
                        raise
                    return None  # rows stay denied
                if br is not None:
                    br.record_success()
                return out

        with fspan:
            outs = await asyncio.gather(*(node_call(*t) for t in live))

        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32) if with_remaining else None
        for (j, lo, hi), out in zip(live, outs):
            if out is None:
                continue
            idx = order[lo:hi]
            granted[idx] = out.granted
            if remaining is not None and out.remaining is not None:
                remaining[idx] = out.remaining
        return BulkAcquireResult(granted, remaining)

    async def acquire_many(self, keys: Sequence[str], counts: Sequence[int],
                           capacity: float, fill_rate_per_sec: float, *,
                           with_remaining: bool = True) -> BulkAcquireResult:
        async def call(node, sub_keys, sub_counts):
            return await node.acquire_many(
                sub_keys, sub_counts, capacity, fill_rate_per_sec,
                with_remaining=with_remaining)

        degraded_row = (
            (lambda j, k, c: self._degraded.acquire(
                j, k, c, capacity, fill_rate_per_sec, "bucket"))
            if self._degraded is not None else None)
        return await self._bulk_fan_out(keys, counts, call, with_remaining,
                                        degraded_row)

    def acquire_many_blocking(self, keys: Sequence[str],
                              counts: Sequence[int], capacity: float,
                              fill_rate_per_sec: float, *,
                              with_remaining: bool = True
                              ) -> BulkAcquireResult:
        return self._blocking(self.acquire_many(
            keys, counts, capacity, fill_rate_per_sec,
            with_remaining=with_remaining))

    async def window_acquire_many(self, keys: Sequence[str],
                                  counts: Sequence[int], limit: float,
                                  window_sec: float, *, fixed: bool = False,
                                  with_remaining: bool = True
                                  ) -> BulkAcquireResult:
        async def call(node, sub_keys, sub_counts):
            return await node.window_acquire_many(
                sub_keys, sub_counts, limit, window_sec, fixed=fixed,
                with_remaining=with_remaining)

        degraded_row = (
            (lambda j, k, c: self._degraded.acquire(
                j, k, c, limit, limit / window_sec,
                "fwindow" if fixed else "window"))
            if self._degraded is not None else None)
        return await self._bulk_fan_out(keys, counts, call, with_remaining,
                                        degraded_row)

    def window_acquire_many_blocking(self, keys: Sequence[str],
                                     counts: Sequence[int], limit: float,
                                     window_sec: float, *,
                                     fixed: bool = False,
                                     with_remaining: bool = True
                                     ) -> BulkAcquireResult:
        return self._blocking(self.window_acquire_many(
            keys, counts, limit, window_sec, fixed=fixed,
            with_remaining=with_remaining))

    # -- ops fan-out ---------------------------------------------------------
    async def ping(self) -> None:
        await asyncio.gather(*(n.ping() for n in self.nodes
                               if hasattr(n, "ping")))

    async def save(self) -> None:
        """Checkpoint every node that supports it (≙ cluster-wide BGSAVE)."""
        await asyncio.gather(*(n.save() for n in self.nodes
                               if hasattr(n, "save")))

    # -- metrics -------------------------------------------------------------
    def metrics_registry(self):
        """The cluster client's own OpenMetrics families: per-node error
        counters and breaker state, shed / degraded decision counters,
        and the wire clients' retry/timeout sums. Appended to the fleet
        scrape by :meth:`cluster_metrics`."""
        from distributedratelimiting.redis_tpu.utils.metrics import (
            MetricsRegistry,
        )

        if self._registry is not None:
            return self._registry
        reg = MetricsRegistry()
        for j in range(self.n_nodes):
            reg.counter("cluster_node_errors",
                        "Store-operation failures per cluster node",
                        lambda j=j: self.node_errors[j],
                        labels={"node": str(j)})
        if self._breakers is not None:
            for j, br in enumerate(self._breakers):
                reg.gauge("cluster_breaker_state",
                          "Circuit state per node: 0 closed, 1 "
                          "half-open, 2 open",
                          br.state_gauge, labels={"node": str(j)})
                reg.counter("cluster_breaker_opens",
                            "Times the node's circuit tripped open",
                            lambda b=br: b.opens,
                            labels={"node": str(j)})
                reg.counter("cluster_breaker_probes",
                            "Half-open probes admitted",
                            lambda b=br: b.probes,
                            labels={"node": str(j)})
        reg.counter("cluster_shed",
                    "Requests failed fast against quarantined nodes",
                    lambda: self.shed)
        reg.counter("cluster_degraded_decisions",
                    "Decisions served by the local fair-share fallback",
                    lambda: self.degraded_decisions)
        reg.gauge("cluster_degraded_keys",
                  "Keys currently held by the degraded fallback",
                  lambda: (len(self._degraded)
                           if self._degraded is not None else 0))
        reg.gauge("cluster_placement_epoch",
                  "Adopted placement map epoch",
                  lambda: float(self.placement.epoch))
        reg.counter("cluster_migrations",
                    "Committed membership migrations",
                    lambda: self.migrations)
        reg.counter("cluster_migration_aborts",
                    "Migrations cleanly aborted to the old epoch",
                    lambda: self.migration_aborts)
        reg.counter("cluster_rejoin_debits",
                    "Degraded-envelope grants debited on node rejoin",
                    lambda: self.rejoin_debits)
        reg.counter("cluster_config_mutations",
                    "Committed live config mutations",
                    lambda: self.config_mutations)
        reg.counter("cluster_config_aborts",
                    "Config mutations cleanly aborted to the old version",
                    lambda: self.config_aborts)
        reg.counter("cluster_client_retries",
                    "Wire-client retries, summed over nodes",
                    lambda: self._sum_node_stat("retries"))
        reg.counter("cluster_client_timeouts",
                    "Wire-client request timeouts, summed over nodes",
                    lambda: self._sum_node_stat("timeouts"))
        # The autonomous controller's families, read dynamically so a
        # controller attached after the first scrape still renders (a
        # None controller renders nothing — register_numeric_dict and
        # the dynamic counter family both skip empty readers).
        reg.register_numeric_dict(
            "controller", "autonomous control plane",
            lambda: (self.controller.numeric_stats()
                     if self.controller is not None else None),
            counters={"ticks", "tick_failures", "actions_recorded",
                      "actuation_errors"})
        reg.labeled_counters(
            "controller_actions",
            "Controller decisions by action and outcome",
            lambda: (self.controller.action_series()
                     if self.controller is not None else []))
        self._registry = reg
        return reg

    def _sum_node_stat(self, key: str) -> int:
        total = 0
        for n in self.nodes:
            stats_fn = getattr(n, "resilience_stats", None)
            if callable(stats_fn):
                total += stats_fn().get(key, 0)
        return total

    async def cluster_metrics(self) -> str:
        """Fleet-wide OpenMetrics exposition: scrape every node's
        ``OP_METRICS`` text and merge — each sample re-emitted per node
        with a ``node="<j>"`` label (positional, same convention as
        :meth:`stats`) plus an aggregated summed series without it, so
        one scrape answers both "what is the fleet doing" and "which
        node is the outlier". Nodes without a metrics surface (bare
        in-process stores in tests) contribute nothing rather than
        failing the scrape. The cluster client's own resilience families
        (breakers, shed, retries) are appended after the merge."""
        from distributedratelimiting.redis_tpu.utils.metrics import (
            aggregate_openmetrics,
        )

        async def one(j: int, n: BucketStore) -> str:
            # callable check: on device stores `metrics` is the
            # StoreMetrics ATTRIBUTE, not the remote scrape method.
            if not callable(getattr(n, "metrics", None)):
                return ""
            try:
                return await n.metrics()
            except Exception as exc:  # a down node must not kill the
                # fleet scrape — but it must be SEEN, not swallowed.
                self._note_scrape_error(j, exc)
                return ""

        texts = await asyncio.gather(*(one(j, n)
                                       for j, n in enumerate(self.nodes)))
        merged = aggregate_openmetrics(texts)
        own = self.metrics_registry().render()
        # Both are complete expositions; splice ours before the EOF
        # terminator (families stay contiguous — each side emits its
        # own distinct family names).
        eof = "# EOF\n"
        if merged.endswith(eof):
            merged = merged[:-len(eof)]
        return merged + own

    def cluster_metrics_blocking(self) -> str:
        return self._blocking(self.cluster_metrics())

    async def stats(self) -> dict:
        """Per-node stats plus cluster-level sums of the numeric metrics.
        ``nodes[j]`` is positionally node ``j``'s stats (``{}`` for nodes
        without a stats surface) — consumers correlate by index. The
        ``resilience`` section carries breaker snapshots and the chaos
        counters."""

        async def one(j: int, n: BucketStore) -> dict:
            if not hasattr(n, "stats"):
                return {}
            try:
                return await n.stats()
            except Exception as exc:
                # A down node must not kill the fleet stats — an ops
                # surface that dies DURING the outage it should be
                # describing. Visible (event 3 + counter), not silent.
                self._note_scrape_error(j, exc)
                return {}

        per_node = await asyncio.gather(*(one(j, n)
                                          for j, n in
                                          enumerate(self.nodes)))
        total: dict = {}
        for s in per_node:
            for k, v in s.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    total[k] = total.get(k, 0) + v
        out = {"n_nodes": self.n_nodes, "nodes": list(per_node),
               "total": total}
        resilience: dict = {
            "node_errors": list(self.node_errors),
            "shed": self.shed,
            "degraded_decisions": self.degraded_decisions,
            "rejoin_debits": self.rejoin_debits,
        }
        if self._breakers is not None:
            resilience["breakers"] = [b.snapshot() for b in self._breakers]
        if self._degraded is not None:
            resilience["degraded_keys"] = len(self._degraded)
        out["resilience"] = resilience
        out["placement"] = {
            "epoch": self.placement.epoch,
            "n_slots": self.placement.n_slots,
            "slot_counts": self.placement.slot_counts(
                self.n_nodes).tolist(),
            "overrides": len(self.placement.overrides),
            "drained": sorted(self.drained),
            "migrations": self.migrations,
            "migration_aborts": self.migration_aborts,
        }
        out["config"] = {
            "mutations": self.config_mutations,
            "aborts": self.config_aborts,
            "rebased_rows": self.config_rebased_rows,
        }
        if self.controller is not None:
            out["controller"] = self.controller.stats()
        return out

    async def audit(self, bundles: int = 0) -> dict:
        """Fleet conservation-audit view (the :meth:`stats` posture):
        ``nodes[j]`` is node ``j``'s OP_AUDIT snapshot positionally
        (``{}`` where the node has no audit surface — down, or audit
        disabled), ``total`` sums the numeric fields, and the fleet
        roll-ups a watch console starts from ride at the top:
        ``breaches``, ``alerts``, ``bundles_assembled``."""

        async def one(j: int, n: BucketStore) -> dict:
            if not hasattr(n, "audit"):
                return {}
            try:
                return await n.audit(bundles=bundles)
            except Exception as exc:
                self._note_scrape_error(j, exc)
                return {}

        per_node = await asyncio.gather(*(one(j, n)
                                          for j, n in
                                          enumerate(self.nodes)))
        total: dict = {}
        for s in per_node:
            for k, v in s.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    total[k] = total.get(k, 0) + v
        return {"n_nodes": self.n_nodes, "nodes": list(per_node),
                "total": total,
                "breaches": total.get("breaches", 0),
                "bundles_assembled": total.get("bundles_assembled", 0)}

    # -- checkpoint ----------------------------------------------------------
    def snapshot(self) -> dict:
        """Cluster checkpoint = each node's snapshot, keyed by position.
        Remote nodes raise by design (state lives with the server — use
        :meth:`save` for server-side checkpoints); in-process nodes
        snapshot locally."""
        return {"cluster": True, "n_nodes": self.n_nodes,
                "nodes": [n.snapshot() for n in self.nodes]}

    def restore(self, snap: dict) -> None:
        if not snap.get("cluster") or snap.get("n_nodes") != self.n_nodes:
            raise ValueError(
                "snapshot is not a cluster snapshot for this topology "
                f"(need n_nodes={self.n_nodes})")
        for node, sub in zip(self.nodes, snap["nodes"]):
            node.restore(sub)
