"""Cluster store — client-side key sharding across N store servers.

The reference's deployment is a star: every client talks to ONE shared
Redis (SURVEY.md §5.8). One TPU host already replaces that Redis
(:class:`~.server.BucketStoreServer` fronting a device store, or a whole
pod slice via :class:`~..parallel.mesh_store.MeshBucketStore`). This module
adds the horizontal dimension the reference's README gestured at with
partitioning (``README.md:7-8``) at *cluster* scale: N independent store
servers — each its own time authority for the keys it owns — with clients
routing ``key → node`` by the same stable crc32 the in-mesh sharding uses
(:func:`~..parallel.sharded_store.shard_of_key`). This is the
Redis-Cluster shape, re-hosted: hash-slot routing lives in the client,
nodes share nothing, and the DCN between hosts carries only each key's own
traffic — no cross-node collectives, because keys never interact
(SURVEY.md §5.7).

Semantics carried over from the single-node client:

- **Per-key semantics are exactly single-node semantics.** A key's
  requests always land on the same node, and bulk splitting is
  order-stable per node, so duplicate-key serialization (invariant 3 at
  batch granularity) and store-as-time-authority (invariant 1) hold
  per key. There is no cross-key ordering guarantee across nodes — the
  same property as the reference's partitioned design (one Redis hash per
  partition, no cross-partition atomicity).
- **Degraded mode is per node** (invariant 9): a node failure affects only
  the keys it owns. Single-key ops surface the error to the caller (the
  approximate limiter's refresh already logs-and-skips; event id 1/2).
  Bulk ops choose via ``partial_failures``: ``"raise"`` (default —
  all-or-error, the caller retries) or ``"deny"`` (decide what we can:
  failed nodes' rows come back denied with ``remaining == 0``, logged
  once per failing node).
- The **global decaying counter** of the approximate algorithm is itself
  just a key (``sync_counter(key=instance_name)``), so it routes to one
  node — every client instance syncs the same named counter against the
  same node's clock, preserving the EWMA instance-count estimate
  unchanged.

The chaos plane (docs/OPERATIONS.md §8) adds per-node **circuit
breakers** and a **degraded-mode fallback** on top:

- ``breaker=True`` (or a :class:`~..utils.resilience.BreakerConfig`)
  gives each node a closed/open/half-open breaker. While OPEN the
  node's keyspace is never dialed — callers shed fast
  (:class:`NodeUnavailableError`) instead of queueing behind a dead
  peer's timeout; after the recovery window ONE request probes the node
  with a health op (``ping``) and a success re-closes it (rejoin).
- ``degraded_fallback=True`` serves a quarantined node's admission
  traffic from a client-local fair-share envelope instead of erroring:
  each key admits against ``headroom_budget(capacity,
  fraction=degraded_fraction)`` tokens refilled at ``fraction ×
  fill_rate`` — the approximate limiter's confidence policy re-used at
  the cluster edge, so over-admission during an outage window stays
  bounded by the same ``overadmit_epsilon`` family of formulas. The
  degraded state is DISCARDED when the node rejoins: the authoritative
  store rules again (the reference's wiped-state self-heal posture).
- Every node failure is a structured log event (id 3) plus a
  ``cluster_node_errors`` counter; breaker transitions are event id 4,
  flight-recorder frames, and OpenMetrics gauges
  (:meth:`metrics_registry`) — partitions are visible, not invisible.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Callable, Sequence

import numpy as np

from distributedratelimiting.redis_tpu.parallel.sharded_store import (
    route_keys,
    shard_of_key,
)
from distributedratelimiting.redis_tpu.runtime.clock import Clock, MonotonicClock
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.store import (
    AcquireResult,
    BucketStore,
    BulkAcquireResult,
    SyncResult,
)
from distributedratelimiting.redis_tpu.utils import log, tracing
from distributedratelimiting.redis_tpu.utils.resilience import (
    BreakerConfig,
    CircuitBreaker,
)

__all__ = ["ClusterBucketStore", "NodeUnavailableError"]


class NodeUnavailableError(ConnectionError):
    """The key's owning node is quarantined (circuit open) and no
    degraded fallback is configured — shed fast, by design."""


class _DegradedKeyspace:
    """Client-local fair-share admission for keys whose owning node is
    quarantined.

    Each ``(node, key, config)`` serves from a conservative local
    envelope: ``headroom_budget(capacity, fraction)`` tokens refilled at
    ``fraction × fill_rate`` — the same confidence policy the
    approximate limiter and the tier-0 edge cache use, re-hosted at the
    cluster edge (models/approximate.py's shared-formula discipline).
    Windows degrade as token buckets with ``(limit, limit/window)``.
    State is per-client and DISCARDED on rejoin (``clear_node``): when
    the authoritative node returns, its state rules — the wiped-state
    self-heal posture of the reference.
    """

    #: Bounded memory under hostile key cardinality: oldest-inserted
    #: entries evict first (a re-touched key re-inserts at full budget —
    #: conservative only in the over-admission direction by one budget,
    #: which the epsilon bound already charges for).
    _MAX_KEYS = 1 << 16

    def __init__(self, fraction: float = 0.5,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if not 0.0 < fraction <= 1.0:
            raise ValueError("degraded_fraction must be in (0, 1]")
        self._fraction = fraction
        self._clock = clock
        self._buckets: dict[tuple, tuple[float, float]] = {}

    def acquire(self, node: int, key: str, count: int, capacity: float,
                fill_rate_per_sec: float) -> AcquireResult:
        from distributedratelimiting.redis_tpu.models.approximate import (
            headroom_budget,
        )

        budget = headroom_budget(capacity, fraction=self._fraction,
                                 min_budget=1.0)
        now = self._clock()
        k = (node, key, float(capacity), float(fill_rate_per_sec))
        entry = self._buckets.get(k)
        if entry is None:
            if len(self._buckets) >= self._MAX_KEYS:
                self._buckets.pop(next(iter(self._buckets)))
            tokens = budget
        else:
            tokens, ts = entry
            tokens = min(budget, tokens + (now - ts)
                         * fill_rate_per_sec * self._fraction)
        granted = tokens >= count
        if granted and count > 0:
            tokens -= count
        self._buckets[k] = (tokens, now)
        return AcquireResult(bool(granted), float(max(tokens, 0.0)))

    def clear_node(self, node: int) -> None:
        for k in [k for k in self._buckets if k[0] == node]:
            del self._buckets[k]

    def __len__(self) -> int:
        return len(self._buckets)


class ClusterBucketStore(BucketStore):
    """Key-sharded façade over N :class:`BucketStore` nodes.

    Exactly one of ``stores``, ``addresses``, or ``urls`` must be given
    (highest-precedence one wins — the same config ladder as
    :class:`RemoteBucketStore`, lifted to lists)::

        store = ClusterBucketStore(addresses=[("tpu-a", 6380), ("tpu-b", 6380)])
        store = ClusterBucketStore(urls=["tpu-a:6380", "tpu-b:6380"])
        store = ClusterBucketStore(stores=[node_a, node_b])   # tests / mixed

    ``remote_kwargs`` (auth token, timeouts, coalescing knobs …) pass
    through to each constructed :class:`RemoteBucketStore` when addresses
    or urls are given.

    Resilience knobs (all off by default — behavior is then exactly the
    pre-chaos-plane cluster): ``breaker`` arms per-node circuit
    breakers, ``degraded_fallback`` serves quarantined keyspaces from
    the local fair-share envelope, ``flight_recorder`` receives breaker
    and node-error frames. Breaker state mutates under the GIL from
    whichever loop carries the request — transitions are coarse
    (per-node, per-failure) and tolerate that by construction.
    """

    def __init__(
        self,
        *,
        stores: Sequence[BucketStore] | None = None,
        addresses: Sequence[tuple[str, int]] | None = None,
        urls: Sequence[str] | None = None,
        partial_failures: str = "raise",
        clock: Clock | None = None,
        breaker: "BreakerConfig | bool | None" = None,
        breaker_clock: Callable[[], float] = time.monotonic,
        degraded_fallback: bool = False,
        degraded_fraction: float = 0.5,
        probe_timeout_s: float = 1.0,
        flight_recorder=None,
        **remote_kwargs,
    ) -> None:
        if stores is not None:
            nodes = list(stores)
        elif addresses is not None:
            nodes = [RemoteBucketStore(address=a, **remote_kwargs)
                     for a in addresses]
        elif urls is not None:
            nodes = [RemoteBucketStore(url=u, **remote_kwargs) for u in urls]
        else:
            raise ValueError("one of stores, addresses, or urls is required")
        if not nodes:
            raise ValueError("cluster needs at least one node")
        if partial_failures not in ("raise", "deny"):
            raise ValueError("partial_failures must be 'raise' or 'deny'")
        self.nodes: list[BucketStore] = nodes
        self.n_nodes = len(nodes)
        self._partial_failures = partial_failures
        # Local clock satisfies the BucketStore interface (diagnostics
        # only); each NODE is the time authority for the keys it owns.
        self.clock = clock or MonotonicClock()

        # -- chaos plane ---------------------------------------------------
        self.flight_recorder = flight_recorder
        self._degraded = (_DegradedKeyspace(degraded_fraction)
                          if degraded_fallback else None)
        if breaker:
            config = breaker if isinstance(breaker, BreakerConfig) \
                else BreakerConfig()
            self._breakers: "list[CircuitBreaker] | None" = [
                self._make_breaker(j, config, breaker_clock)
                for j in range(self.n_nodes)]
        else:
            self._breakers = None
        self._probe_timeout_s = probe_timeout_s
        #: Per-node store-operation failures (satellite: partitions are
        #: visible — every increment pairs with log event id 3).
        self.node_errors = [0] * self.n_nodes
        #: Requests failed fast against quarantined nodes (no fallback).
        self.shed = 0
        #: Decisions served by the local degraded fallback.
        self.degraded_decisions = 0
        self._registry = None

        # Background loop for the blocking surface (same pattern as
        # RemoteBucketStore): lets blocking callers fan out to all nodes
        # concurrently from any thread, loop or no loop.
        self._io_loop: asyncio.AbstractEventLoop | None = None
        self._io_thread: threading.Thread | None = None
        self._thread_gate = threading.Lock()
        self._closed = False

    @property
    def _resilient(self) -> bool:
        return self._breakers is not None or self._degraded is not None

    def _make_breaker(self, j: int, config: BreakerConfig,
                      clock: Callable[[], float]) -> CircuitBreaker:
        def on_transition(old: str, new: str) -> None:
            log.breaker_transition(j, old, new)
            if self.flight_recorder is not None:
                self.flight_recorder.record("breaker", node=j, old=old,
                                            new=new)
                if new == CircuitBreaker.OPEN:
                    self.flight_recorder.auto_dump("breaker_open",
                                                   {"node": j})
            if new == CircuitBreaker.CLOSED and self._degraded is not None:
                # Rejoin: the authoritative node rules again; local
                # degraded state self-heals away (wiped-state posture).
                self._degraded.clear_node(j)

        return CircuitBreaker(config, clock=clock,
                              on_transition=on_transition)

    # -- routing -----------------------------------------------------------
    def node_of(self, key: str) -> BucketStore:
        """The node that owns ``key`` (stable crc32 — every client on every
        host routes identically, no coordination)."""
        return self.nodes[shard_of_key(key, self.n_nodes)]

    # -- failure bookkeeping -------------------------------------------------
    def _note_node_error(self, j: int, exc: BaseException) -> None:
        """Every SERVING-path node failure funnels here: counter +
        structured log (event id 3) + breaker failure + flight-recorder
        frame. Nothing is silently swallowed (the old ``except: pass``
        posture). Diagnostics scrapes use :meth:`_note_scrape_error`
        instead — a failed scrape is visible but must not advance the
        breaker that gates admission traffic."""
        self._note_scrape_error(j, exc)
        if self._breakers is not None:
            self._breakers[j].record_failure()
        if self.flight_recorder is not None:
            self.flight_recorder.record("node_error", node=j,
                                        error=repr(exc))

    def _note_scrape_error(self, j: int, exc: BaseException) -> None:
        """Counter + log for a failed metrics/stats scrape (no breaker,
        no flight frame — see :meth:`_note_node_error`)."""
        self.node_errors[j] += 1
        log.cluster_node_error(j, exc)

    def _shed_or_fallback(self, j: int, fallback):
        """The quarantined-node decision: serve the degraded fallback
        when configured, else shed fast with a typed error."""
        if fallback is None or self._degraded is None:
            self.shed += 1
            raise NodeUnavailableError(
                f"cluster node {j} is quarantined (circuit open)")
        self.degraded_decisions += 1
        return fallback()

    async def _probe(self, j: int) -> bool:
        """Half-open health probe: ping the node (nodes without a ping
        surface let the real request itself settle the probe). Returns
        whether the node may be used for the request that won the
        probe slot."""
        node = self.nodes[j]
        assert self._breakers is not None
        ping = getattr(node, "ping", None)
        if not callable(ping):
            return True
        try:
            try:
                coro = ping(timeout_s=self._probe_timeout_s)
            except TypeError:  # in-process nodes: plain ping()
                coro = ping()
            await coro
        except asyncio.CancelledError:
            # Cancellation is no verdict on the node: free the slot so
            # the next caller probes instead of rejecting forever.
            self._breakers[j].release_probe()
            raise
        except Exception as exc:
            self._note_node_error(j, exc)  # records the breaker failure
            return False                   # → back to OPEN
        self._breakers[j].record_success()
        return True

    async def _guarded_call(self, j: int, call, fallback=None):
        """Run one node operation under the node's breaker: OPEN sheds
        (or serves the fallback), HALF_OPEN probes first, failures are
        noted (counter + log + breaker) and — when a fallback exists —
        absorbed into a degraded decision instead of an error."""
        br = self._breakers[j] if self._breakers is not None else None
        if br is not None:
            verdict = br.allow()
            if verdict == "probe" and not await self._probe(j):
                verdict = "reject"
            if verdict == "reject":
                return self._shed_or_fallback(j, fallback)
        try:
            res = await call()
        except asyncio.CancelledError:
            if br is not None:
                # The probe-winning request may be the one cancelled (a
                # ping-less node settles via the real call): free the
                # slot — no-op otherwise.
                br.release_probe()
            raise
        except Exception as exc:
            self._note_node_error(j, exc)
            if fallback is not None and self._degraded is not None:
                self.degraded_decisions += 1
                return fallback()
            raise
        if br is not None:
            br.record_success()
        return res

    # -- blocking-surface plumbing ------------------------------------------
    def _ensure_loop(self) -> asyncio.AbstractEventLoop:
        loop = self._io_loop
        if loop is not None:
            return loop
        with self._thread_gate:
            if self._io_loop is None:
                loop = asyncio.new_event_loop()
                ready = threading.Event()

                def run() -> None:
                    asyncio.set_event_loop(loop)
                    ready.set()
                    loop.run_forever()

                t = threading.Thread(target=run, name="cluster-store-io",
                                     daemon=True)
                t.start()
                ready.wait()
                self._io_loop = loop
                self._io_thread = t
        return self._io_loop

    def _blocking(self, coro):
        return asyncio.run_coroutine_threadsafe(
            coro, self._ensure_loop()).result()

    # -- lifecycle ----------------------------------------------------------
    async def connect(self) -> None:
        """Eagerly connect every node (each node also lazily connects on
        first use, the reference's posture — this is for fail-fast setups)."""
        await asyncio.gather(*(n.connect() for n in self.nodes))

    async def aclose(self) -> None:
        if self._closed:
            return
        self._closed = True
        # return_exceptions: one node's failed close must not skip the
        # others or leak the I/O loop thread below.
        outs = await asyncio.gather(*(n.aclose() for n in self.nodes),
                                    return_exceptions=True)
        loop = self._io_loop
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)
            if self._io_thread is not None:
                # to_thread: a 5s worst-case join must not stall the
                # CALLER's event loop (drl-check async-blocking).
                await asyncio.to_thread(self._io_thread.join, 5.0)
            # Close only a stopped loop: if the join timed out the loop
            # thread is still running, and loop.close() would raise
            # RuntimeError here — masking any node-close exception
            # collected above (the daemon thread dies with the process).
            if self._io_thread is None or not self._io_thread.is_alive():
                loop.close()
            self._io_loop = None
        for out in outs:
            if isinstance(out, BaseException):
                raise out

    # -- single-key ops: route, guard, forward -------------------------------
    async def acquire(self, key: str, count: int, capacity: float,
                      fill_rate_per_sec: float) -> AcquireResult:
        j = shard_of_key(key, self.n_nodes)
        if not self._resilient:
            return await self.nodes[j].acquire(key, count, capacity,
                                               fill_rate_per_sec)
        return await self._guarded_call(
            j,
            lambda: self.nodes[j].acquire(key, count, capacity,
                                          fill_rate_per_sec),
            fallback=lambda: self._degraded.acquire(
                j, key, count, capacity, fill_rate_per_sec))

    def acquire_blocking(self, key: str, count: int, capacity: float,
                         fill_rate_per_sec: float) -> AcquireResult:
        if self._resilient:
            return self._blocking(self.acquire(key, count, capacity,
                                               fill_rate_per_sec))
        return self.node_of(key).acquire_blocking(key, count, capacity,
                                                  fill_rate_per_sec)

    def peek_blocking(self, key: str, capacity: float,
                      fill_rate_per_sec: float) -> float:
        # No degraded value exists for a peek — it reports the
        # AUTHORITATIVE balance; a quarantined node surfaces the typed
        # shed error instead of a made-up number.
        if self._breakers is not None:
            j = shard_of_key(key, self.n_nodes)
            if self._breakers[j].quarantined():
                self.shed += 1
                raise NodeUnavailableError(
                    f"cluster node {j} is quarantined (circuit open)")
        return self.node_of(key).peek_blocking(key, capacity,
                                               fill_rate_per_sec)

    def acquire_submitter(self, capacity: float, fill_rate_per_sec: float):
        if self._resilient:
            # The guarded path costs a route + breaker check per
            # request; resilience was asked for explicitly.
            async def submit(key: str, count: int) -> AcquireResult:
                return await self.acquire(key, count, capacity,
                                          fill_rate_per_sec)

            return submit
        # Hoist per-node submitters once; per request only the route runs.
        subs = [n.acquire_submitter(capacity, fill_rate_per_sec)
                for n in self.nodes]
        n_nodes = self.n_nodes

        async def submit(key: str, count: int) -> AcquireResult:
            return await subs[shard_of_key(key, n_nodes)](key, count)

        return submit

    async def sync_counter(self, key: str, local_count: float,
                           decay_rate_per_sec: float) -> SyncResult:
        # No fallback on purpose: the approximate limiter OWNS its
        # degraded mode (keep serving from the last-known global score);
        # it needs the error, not a made-up sync result.
        j = shard_of_key(key, self.n_nodes)
        if not self._resilient:
            return await self.nodes[j].sync_counter(key, local_count,
                                                    decay_rate_per_sec)
        return await self._guarded_call(
            j, lambda: self.nodes[j].sync_counter(key, local_count,
                                                  decay_rate_per_sec))

    def sync_counter_blocking(self, key: str, local_count: float,
                              decay_rate_per_sec: float) -> SyncResult:
        if self._resilient:
            return self._blocking(self.sync_counter(key, local_count,
                                                    decay_rate_per_sec))
        return self.node_of(key).sync_counter_blocking(key, local_count,
                                                       decay_rate_per_sec)

    async def window_acquire(self, key: str, count: int, limit: float,
                             window_sec: float) -> AcquireResult:
        j = shard_of_key(key, self.n_nodes)
        if not self._resilient:
            return await self.nodes[j].window_acquire(key, count, limit,
                                                      window_sec)
        return await self._guarded_call(
            j,
            lambda: self.nodes[j].window_acquire(key, count, limit,
                                                 window_sec),
            fallback=lambda: self._degraded.acquire(
                j, key, count, limit, limit / window_sec))

    def window_acquire_blocking(self, key: str, count: int, limit: float,
                                window_sec: float) -> AcquireResult:
        if self._resilient:
            return self._blocking(self.window_acquire(key, count, limit,
                                                      window_sec))
        return self.node_of(key).window_acquire_blocking(key, count, limit,
                                                         window_sec)

    async def fixed_window_acquire(self, key: str, count: int, limit: float,
                                   window_sec: float) -> AcquireResult:
        j = shard_of_key(key, self.n_nodes)
        if not self._resilient:
            return await self.nodes[j].fixed_window_acquire(
                key, count, limit, window_sec)
        return await self._guarded_call(
            j,
            lambda: self.nodes[j].fixed_window_acquire(key, count, limit,
                                                       window_sec),
            fallback=lambda: self._degraded.acquire(
                j, key, count, limit, limit / window_sec))

    def fixed_window_acquire_blocking(self, key: str, count: int,
                                      limit: float,
                                      window_sec: float) -> AcquireResult:
        if self._resilient:
            return self._blocking(self.fixed_window_acquire(
                key, count, limit, window_sec))
        return self.node_of(key).fixed_window_acquire_blocking(
            key, count, limit, window_sec)

    async def concurrency_acquire(self, key: str, count: int,
                                  limit: int) -> AcquireResult:
        j = shard_of_key(key, self.n_nodes)
        if not self._resilient:
            return await self.nodes[j].concurrency_acquire(key, count,
                                                           limit)
        # Semaphores are strict: a made-up degraded grant could exceed
        # the concurrency limit the moment the node returns. Deny.
        return await self._guarded_call(
            j,
            lambda: self.nodes[j].concurrency_acquire(key, count, limit),
            fallback=lambda: AcquireResult(False, 0.0))

    def concurrency_acquire_blocking(self, key: str, count: int,
                                     limit: int) -> AcquireResult:
        if self._resilient:
            return self._blocking(self.concurrency_acquire(key, count,
                                                           limit))
        return self.node_of(key).concurrency_acquire_blocking(key, count,
                                                              limit)

    async def concurrency_release(self, key: str, count: int) -> None:
        j = shard_of_key(key, self.n_nodes)
        if not self._resilient:
            await self.nodes[j].concurrency_release(key, count)
            return
        # A release against a quarantined node is absorbed (None): the
        # node's semaphore state resets with it anyway (init-on-miss).
        await self._guarded_call(
            j, lambda: self.nodes[j].concurrency_release(key, count),
            fallback=lambda: None)

    def concurrency_release_blocking(self, key: str, count: int) -> None:
        if self._resilient:
            self._blocking(self.concurrency_release(key, count))
            return
        self.node_of(key).concurrency_release_blocking(key, count)

    # -- bulk ops: split by route, fan out, merge ---------------------------
    def _split(self, keys: Sequence[str]):
        """Group a bulk call by owning node, order-stably.

        Returns ``(order, bounds, keys_list)`` where ``order`` is a stable
        permutation grouping requests by node and ``bounds[j]:bounds[j+1]``
        slices node ``j``'s group. Stability keeps each node's sub-batch in
        arrival order, so per-node duplicate serialization is exactly the
        single-node bulk semantics.
        """
        keys = keys if isinstance(keys, list) else list(keys)
        routes = route_keys(keys, self.n_nodes)  # one native C pass
        order = np.argsort(routes, kind="stable")
        bounds = np.searchsorted(routes[order],
                                 np.arange(self.n_nodes + 1))
        return order, bounds, keys

    def _bulk_degraded(self, j: int, sub_keys, sub_counts,
                       degraded_row) -> BulkAcquireResult:
        """Serve one node's bulk rows from the degraded fallback (a
        Python loop — this is the outage path, not the hot path)."""
        n = len(sub_keys)
        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32)
        for i, (k, c) in enumerate(zip(sub_keys, sub_counts)):
            res = degraded_row(j, k, int(c))
            granted[i] = res.granted
            remaining[i] = res.remaining
        self.degraded_decisions += n
        return BulkAcquireResult(granted, remaining)

    def _bulk_reject(self, j: int, sub_keys, sub_counts, degraded_row
                     ) -> "BulkAcquireResult | None":
        """A quarantined node's bulk group: degraded rows when possible,
        else the partial_failures contract ('deny' → None, rows stay
        denied; 'raise' → typed shed error)."""
        if degraded_row is not None and self._degraded is not None:
            return self._bulk_degraded(j, sub_keys, sub_counts,
                                       degraded_row)
        self.shed += len(sub_keys)
        if self._partial_failures == "raise":
            raise NodeUnavailableError(
                f"cluster node {j} is quarantined (circuit open)")
        return None

    async def _bulk_fan_out(self, keys, counts, call, with_remaining: bool,
                            degraded_row=None) -> BulkAcquireResult:
        n = len(keys)
        if n == 0:
            return BulkAcquireResult(
                np.zeros(0, bool),
                np.zeros(0, np.float32) if with_remaining else None)
        counts_np = np.asarray(counts, np.int64)
        if self.n_nodes == 1 and not self._resilient:
            return await call(self.nodes[0], keys, counts_np)
        order, bounds, keys = self._split(keys)

        tracer = tracing.get_tracer()
        live = [(j, int(bounds[j]), int(bounds[j + 1]))
                for j in range(self.n_nodes) if bounds[j] < bounds[j + 1]]
        # The whole fan-out is one span (a new root when the caller has
        # none, subject to the head-sampling coin): the per-node
        # children parent on it EXPLICITLY — if the coin fails here,
        # the nodes must not re-flip it N times and litter the buffer
        # with unrooted single-node traces.
        fspan = (tracer.start_span("cluster.fan_out",
                                   attrs={"nodes": len(live),
                                          "rows": int(n)})
                 if tracer.enabled else tracing._NULL_SPAN)
        fctx = fspan.context

        async def node_call(j: int, lo: int, hi: int):
            idx = order[lo:hi]
            sub_keys = [keys[i] for i in idx]
            sub_counts = counts_np[idx]
            # One child span per node: the fan-out share of a traced bulk
            # call decomposes into which node was slow.
            nspan = (tracer.start_span("cluster.node", parent=fctx,
                                       attrs={"node": j,
                                              "rows": int(hi - lo)})
                     if fctx is not None else tracing._NULL_SPAN)
            with nspan:
                br = (self._breakers[j] if self._breakers is not None
                      else None)
                if br is not None:
                    verdict = br.allow()
                    if verdict == "probe" and not await self._probe(j):
                        verdict = "reject"
                    if verdict == "reject":
                        nspan.set_status("degraded")
                        nspan.set_attr("breaker", br.state)
                        return self._bulk_reject(j, sub_keys, sub_counts,
                                                 degraded_row)
                try:
                    out = await call(self.nodes[j], sub_keys, sub_counts)
                except asyncio.CancelledError:
                    if br is not None:
                        br.release_probe()  # no-op unless we held it
                    raise
                except Exception as exc:
                    self._note_node_error(j, exc)
                    nspan.set_status("degraded")
                    if degraded_row is not None \
                            and self._degraded is not None:
                        return self._bulk_degraded(j, sub_keys,
                                                   sub_counts,
                                                   degraded_row)
                    if self._partial_failures == "raise":
                        raise
                    return None  # rows stay denied
                if br is not None:
                    br.record_success()
                return out

        with fspan:
            outs = await asyncio.gather(*(node_call(*t) for t in live))

        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32) if with_remaining else None
        for (j, lo, hi), out in zip(live, outs):
            if out is None:
                continue
            idx = order[lo:hi]
            granted[idx] = out.granted
            if remaining is not None and out.remaining is not None:
                remaining[idx] = out.remaining
        return BulkAcquireResult(granted, remaining)

    async def acquire_many(self, keys: Sequence[str], counts: Sequence[int],
                           capacity: float, fill_rate_per_sec: float, *,
                           with_remaining: bool = True) -> BulkAcquireResult:
        async def call(node, sub_keys, sub_counts):
            return await node.acquire_many(
                sub_keys, sub_counts, capacity, fill_rate_per_sec,
                with_remaining=with_remaining)

        degraded_row = (
            (lambda j, k, c: self._degraded.acquire(
                j, k, c, capacity, fill_rate_per_sec))
            if self._degraded is not None else None)
        return await self._bulk_fan_out(keys, counts, call, with_remaining,
                                        degraded_row)

    def acquire_many_blocking(self, keys: Sequence[str],
                              counts: Sequence[int], capacity: float,
                              fill_rate_per_sec: float, *,
                              with_remaining: bool = True
                              ) -> BulkAcquireResult:
        return self._blocking(self.acquire_many(
            keys, counts, capacity, fill_rate_per_sec,
            with_remaining=with_remaining))

    async def window_acquire_many(self, keys: Sequence[str],
                                  counts: Sequence[int], limit: float,
                                  window_sec: float, *, fixed: bool = False,
                                  with_remaining: bool = True
                                  ) -> BulkAcquireResult:
        async def call(node, sub_keys, sub_counts):
            return await node.window_acquire_many(
                sub_keys, sub_counts, limit, window_sec, fixed=fixed,
                with_remaining=with_remaining)

        degraded_row = (
            (lambda j, k, c: self._degraded.acquire(
                j, k, c, limit, limit / window_sec))
            if self._degraded is not None else None)
        return await self._bulk_fan_out(keys, counts, call, with_remaining,
                                        degraded_row)

    def window_acquire_many_blocking(self, keys: Sequence[str],
                                     counts: Sequence[int], limit: float,
                                     window_sec: float, *,
                                     fixed: bool = False,
                                     with_remaining: bool = True
                                     ) -> BulkAcquireResult:
        return self._blocking(self.window_acquire_many(
            keys, counts, limit, window_sec, fixed=fixed,
            with_remaining=with_remaining))

    # -- ops fan-out ---------------------------------------------------------
    async def ping(self) -> None:
        await asyncio.gather(*(n.ping() for n in self.nodes
                               if hasattr(n, "ping")))

    async def save(self) -> None:
        """Checkpoint every node that supports it (≙ cluster-wide BGSAVE)."""
        await asyncio.gather(*(n.save() for n in self.nodes
                               if hasattr(n, "save")))

    # -- metrics -------------------------------------------------------------
    def metrics_registry(self):
        """The cluster client's own OpenMetrics families: per-node error
        counters and breaker state, shed / degraded decision counters,
        and the wire clients' retry/timeout sums. Appended to the fleet
        scrape by :meth:`cluster_metrics`."""
        from distributedratelimiting.redis_tpu.utils.metrics import (
            MetricsRegistry,
        )

        if self._registry is not None:
            return self._registry
        reg = MetricsRegistry()
        for j in range(self.n_nodes):
            reg.counter("cluster_node_errors",
                        "Store-operation failures per cluster node",
                        lambda j=j: self.node_errors[j],
                        labels={"node": str(j)})
        if self._breakers is not None:
            for j, br in enumerate(self._breakers):
                reg.gauge("cluster_breaker_state",
                          "Circuit state per node: 0 closed, 1 "
                          "half-open, 2 open",
                          br.state_gauge, labels={"node": str(j)})
                reg.counter("cluster_breaker_opens",
                            "Times the node's circuit tripped open",
                            lambda b=br: b.opens,
                            labels={"node": str(j)})
                reg.counter("cluster_breaker_probes",
                            "Half-open probes admitted",
                            lambda b=br: b.probes,
                            labels={"node": str(j)})
        reg.counter("cluster_shed",
                    "Requests failed fast against quarantined nodes",
                    lambda: self.shed)
        reg.counter("cluster_degraded_decisions",
                    "Decisions served by the local fair-share fallback",
                    lambda: self.degraded_decisions)
        reg.gauge("cluster_degraded_keys",
                  "Keys currently held by the degraded fallback",
                  lambda: (len(self._degraded)
                           if self._degraded is not None else 0))
        reg.counter("cluster_client_retries",
                    "Wire-client retries, summed over nodes",
                    lambda: self._sum_node_stat("retries"))
        reg.counter("cluster_client_timeouts",
                    "Wire-client request timeouts, summed over nodes",
                    lambda: self._sum_node_stat("timeouts"))
        self._registry = reg
        return reg

    def _sum_node_stat(self, key: str) -> int:
        total = 0
        for n in self.nodes:
            stats_fn = getattr(n, "resilience_stats", None)
            if callable(stats_fn):
                total += stats_fn().get(key, 0)
        return total

    async def cluster_metrics(self) -> str:
        """Fleet-wide OpenMetrics exposition: scrape every node's
        ``OP_METRICS`` text and merge — each sample re-emitted per node
        with a ``node="<j>"`` label (positional, same convention as
        :meth:`stats`) plus an aggregated summed series without it, so
        one scrape answers both "what is the fleet doing" and "which
        node is the outlier". Nodes without a metrics surface (bare
        in-process stores in tests) contribute nothing rather than
        failing the scrape. The cluster client's own resilience families
        (breakers, shed, retries) are appended after the merge."""
        from distributedratelimiting.redis_tpu.utils.metrics import (
            aggregate_openmetrics,
        )

        async def one(j: int, n: BucketStore) -> str:
            # callable check: on device stores `metrics` is the
            # StoreMetrics ATTRIBUTE, not the remote scrape method.
            if not callable(getattr(n, "metrics", None)):
                return ""
            try:
                return await n.metrics()
            except Exception as exc:  # a down node must not kill the
                # fleet scrape — but it must be SEEN, not swallowed.
                self._note_scrape_error(j, exc)
                return ""

        texts = await asyncio.gather(*(one(j, n)
                                       for j, n in enumerate(self.nodes)))
        merged = aggregate_openmetrics(texts)
        own = self.metrics_registry().render()
        # Both are complete expositions; splice ours before the EOF
        # terminator (families stay contiguous — each side emits its
        # own distinct family names).
        eof = "# EOF\n"
        if merged.endswith(eof):
            merged = merged[:-len(eof)]
        return merged + own

    def cluster_metrics_blocking(self) -> str:
        return self._blocking(self.cluster_metrics())

    async def stats(self) -> dict:
        """Per-node stats plus cluster-level sums of the numeric metrics.
        ``nodes[j]`` is positionally node ``j``'s stats (``{}`` for nodes
        without a stats surface) — consumers correlate by index. The
        ``resilience`` section carries breaker snapshots and the chaos
        counters."""

        async def one(j: int, n: BucketStore) -> dict:
            if not hasattr(n, "stats"):
                return {}
            try:
                return await n.stats()
            except Exception as exc:
                # A down node must not kill the fleet stats — an ops
                # surface that dies DURING the outage it should be
                # describing. Visible (event 3 + counter), not silent.
                self._note_scrape_error(j, exc)
                return {}

        per_node = await asyncio.gather(*(one(j, n)
                                          for j, n in
                                          enumerate(self.nodes)))
        total: dict = {}
        for s in per_node:
            for k, v in s.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    total[k] = total.get(k, 0) + v
        out = {"n_nodes": self.n_nodes, "nodes": list(per_node),
               "total": total}
        resilience: dict = {
            "node_errors": list(self.node_errors),
            "shed": self.shed,
            "degraded_decisions": self.degraded_decisions,
        }
        if self._breakers is not None:
            resilience["breakers"] = [b.snapshot() for b in self._breakers]
        if self._degraded is not None:
            resilience["degraded_keys"] = len(self._degraded)
        out["resilience"] = resilience
        return out

    # -- checkpoint ----------------------------------------------------------
    def snapshot(self) -> dict:
        """Cluster checkpoint = each node's snapshot, keyed by position.
        Remote nodes raise by design (state lives with the server — use
        :meth:`save` for server-side checkpoints); in-process nodes
        snapshot locally."""
        return {"cluster": True, "n_nodes": self.n_nodes,
                "nodes": [n.snapshot() for n in self.nodes]}

    def restore(self, snap: dict) -> None:
        if not snap.get("cluster") or snap.get("n_nodes") != self.n_nodes:
            raise ValueError(
                "snapshot is not a cluster snapshot for this topology "
                f"(need n_nodes={self.n_nodes})")
        for node, sub in zip(self.nodes, snap["nodes"]):
            node.restore(sub)
