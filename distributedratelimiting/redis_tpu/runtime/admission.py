"""Token-denominated, SLO-aware admission — the LLM-serving policy layer.

The raw machinery below this module counts *permits*; production LLM
gateways limit by **token budget** with wildly heavy-tailed
cost-per-request (PAPERS.md "Token-Budget-Aware Pool Routing",
"TokenScale"): a 4K-token completion must cost 4096× what a 1-token
probe costs, a tenant's whole fleet of keys must share one budget, and
under pressure the *right* traffic must shed first. This module turns
the counted-acquire machinery into that admission plane:

- **Weighted-cost acquire** — every lane already carries a ``count``
  operand end to end (wire ACQUIRE tail, bulk counts arrays, the
  ``debit_many`` kernel); this module makes N-token costs the
  first-class unit: budgets, envelopes, velocity, and the tier-0 edge
  cache all denominate in tokens (a 4K-token grant can never hide
  inside a 1-permit epsilon — the C replica install requires its
  budget to cover the observed cost, ``native/frontend.cc
  t0_install``).
- **Hierarchical tenant → key budgets** — a two-level composition of
  the existing bucket tables: the child key's bucket AND the parent
  tenant's bucket decide in ONE fused kernel launch
  (:func:`~.ops.kernels.acquire_hierarchical_packed`), grant iff both
  levels admit, with both-or-neither state change (parent refund on
  child deny). Rides the wire as ``OP_ACQUIRE_H`` / the
  ``BULK_KIND_HBUCKET`` bulk kind (:mod:`~.runtime.wire`); tenant
  budgets are plain bucket configs, so the live-config mutation plane
  (``OP_CONFIG``) rebases them with no restart.
- **Priority classes** — interactive / batch / scavenger with a defined
  shed order, honored wherever bounded envelopes serve instead of the
  authoritative store (drain windows, parked handoffs, the cluster's
  degraded fallback): scavenger sheds first, batch cannot spend the
  envelope's reserved half, interactive gets the whole envelope
  (:func:`shed_allows` — THE shared gate, called from
  ``placement.envelope_step``).
- **Token velocity** — per-tenant tokens/sec as an exponentially
  decayed rate (:class:`TokenVelocity`), exported via OP_STATS and
  OpenMetrics (``drl_token_velocity{tenant=…}``). The heavy-hitter
  sketch weights offers by cost on every lane (scalar, asyncio bulk via
  :meth:`~.utils.heavy_hitters.HeavyHitters.offer_blob`, native batch,
  native bulk via the C per-frame aggregation), so the resharder's
  ``split_hot_keys`` candidates are hot-*cost* keys, not just
  hot-count keys.

Contract (docs/DESIGN.md §15): a hierarchical decision changes state
both-or-neither — a denied request leaves both buckets exactly as a
refill-only touch would. In-batch duplicate demand serializes
conservatively on BOTH axes (an earlier row's demand reserves ahead on
its key and, when child-admitted, on its tenant, even if ultimately
denied), identical to the flat bulk paths' documented posture: exact
on serial stores and whenever the in-call demand fits.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Mapping

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
)

__all__ = [
    "PRIORITY_INTERACTIVE", "PRIORITY_BATCH", "PRIORITY_SCAVENGER",
    "PRIORITY_NAMES", "BATCH_ENVELOPE_RESERVE",
    "shed_allows", "TenantBudget", "TokenVelocity", "AdmissionPolicy",
]

#: Priority classes, shed-order ascending: the HIGHEST value sheds
#: first. The wire carries the value as one byte on the tenant
#: extension (wire.py ``_HIER_TAIL``); plain (non-hierarchical) frames
#: default to interactive — unchanged behavior for every existing
#: caller.
PRIORITY_INTERACTIVE = 0
PRIORITY_BATCH = 1
PRIORITY_SCAVENGER = 2

PRIORITY_NAMES = ("interactive", "batch", "scavenger")

#: Fraction of an envelope's budget reserved for interactive traffic:
#: batch admits only while the post-grant balance stays above
#: ``budget × BATCH_ENVELOPE_RESERVE`` — the envelope's last half is
#: spent on interactive alone. Scavenger never touches an envelope.
BATCH_ENVELOPE_RESERVE = 0.5


def priority_name(priority: int) -> str:
    if 0 <= priority < len(PRIORITY_NAMES):
        return PRIORITY_NAMES[priority]
    return f"priority{priority}"


def shed_allows(priority: int, tokens: float, count: int,
                budget: float) -> bool:
    """THE envelope shed gate — one formula for every bounded-envelope
    serving site (drain windows, parked handoffs, degraded fallback),
    so the documented shed order can never drift between them:

    - **scavenger** is shed outright: envelope serving exists to keep a
      bounded epsilon of availability through an outage/handoff, and
      that epsilon is not spent on best-effort traffic (probes
      included — a scavenger probe during degraded serving answers
      "no").
    - **batch** admits only while the post-grant balance stays above
      ``budget × BATCH_ENVELOPE_RESERVE`` — it cannot consume the
      reserved half.
    - **interactive** (and anything unclassified below batch) gets the
      plain ``tokens >= count`` envelope rule.

    ``tokens`` is the envelope's refilled balance, ``budget`` its full
    size (``headroom_budget(cap, fraction)``). Callers debit on True
    exactly as before."""
    if count < 0:
        return False
    if priority >= PRIORITY_SCAVENGER:
        return False
    if priority >= PRIORITY_BATCH:
        return tokens - count >= budget * BATCH_ENVELOPE_RESERVE
    return tokens >= count


class TenantBudget:
    """One tenant's token budget: a plain bucket config (capacity in
    tokens, refill in tokens/sec) under the tenant's id. Being an
    ordinary bucket config, it is live-mutable through the OP_CONFIG
    plane (``ClusterBucketStore.mutate_config("bucket", old, new)``)
    and checkpointed/migrated like any other bucket state."""

    __slots__ = ("tenant", "capacity", "fill_rate_per_sec")

    def __init__(self, tenant: str, capacity: float,
                 fill_rate_per_sec: float) -> None:
        if not tenant:
            raise ValueError("tenant id must be non-empty")
        if not math.isfinite(capacity) or capacity <= 0:
            raise ValueError(f"tenant capacity must be > 0: {capacity}")
        if not math.isfinite(fill_rate_per_sec) or fill_rate_per_sec < 0:
            raise ValueError(
                f"tenant fill rate must be >= 0: {fill_rate_per_sec}")
        self.tenant = tenant
        self.capacity = float(capacity)
        self.fill_rate_per_sec = float(fill_rate_per_sec)

    def config(self) -> tuple[float, float]:
        return self.capacity, self.fill_rate_per_sec

    def __repr__(self) -> str:
        return (f"TenantBudget({self.tenant!r}, {self.capacity}, "
                f"{self.fill_rate_per_sec}/s)")


class TokenVelocity:
    """Per-tenant tokens/sec — the signal autoscalers and the resharder
    consume (TokenScale's observation: token *velocity*, not request
    rate, is what predicts LLM-serving load).

    Estimator: an exponentially decayed token sum per tenant —
    ``S ← S·exp(−Δt/τ) + cost`` on every observation — read as
    ``rate = S/τ``. Under a steady feed of r tokens/sec, S converges to
    ``r·τ``, so the readout converges to r; after the feed stops, the
    estimate decays to zero with time constant τ. One dict entry and
    two floats per tenant, deterministic under an injected clock (the
    seeded soaks), bounded tenant cardinality (smallest sum evicts
    first — a tenant hot enough to matter re-enters immediately)."""

    __slots__ = ("tau_s", "max_tenants", "_clock", "_state",
                 "observed_tokens", "_totals")

    def __init__(self, tau_s: float = 10.0, max_tenants: int = 512,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if tau_s <= 0:
            raise ValueError("tau_s must be positive")
        self.tau_s = float(tau_s)
        self.max_tenants = max_tenants
        self._clock = clock
        self._state: dict[str, tuple[float, float]] = {}  # S, last_t
        #: Lifetime admitted tokens observed (all tenants) — the
        #: cheap absolute counter beside the rate gauge.
        self.observed_tokens = 0.0
        # Per-tenant lifetime admitted tokens — the MONOTONIC companion
        # of the decayed rate, for consumers that derive their own
        # windowed rates from counter deltas instead of trusting a
        # wall-clock-decayed gauge (the controller's determinism
        # contract: same traffic schedule ⇒ same deltas, regardless of
        # when the scrape lands). Evicted together with the rate state;
        # delta consumers tolerate the reset (CounterDeltas).
        self._totals: dict[str, float] = {}

    def observe(self, tenant: str, cost: float) -> None:
        """Fold ``cost`` admitted tokens for ``tenant`` into the rate."""
        if cost <= 0:
            return
        now = self._clock()
        self.observed_tokens += cost
        entry = self._state.get(tenant)
        if entry is None:
            if len(self._state) >= self.max_tenants:
                victim = min(self._state, key=lambda t: self._state[t][0])
                del self._state[victim]
                self._totals.pop(victim, None)
            self._state[tenant] = (float(cost), now)
            self._totals[tenant] = self._totals.get(tenant, 0.0) + cost
            return
        s, last = entry
        s = s * math.exp(-(now - last) / self.tau_s) + cost
        self._state[tenant] = (s, now)
        self._totals[tenant] = self._totals.get(tenant, 0.0) + cost

    def rate(self, tenant: str) -> float:
        """Current tokens/sec estimate for one tenant (0.0 unknown)."""
        entry = self._state.get(tenant)
        if entry is None:
            return 0.0
        s, last = entry
        return s * math.exp(-(self._clock() - last) / self.tau_s) \
            / self.tau_s

    def rates(self) -> dict[str, float]:
        """``{tenant: tokens_per_sec}`` for every tracked tenant,
        decay-corrected to now."""
        now = self._clock()
        return {t: s * math.exp(-(now - last) / self.tau_s) / self.tau_s
                for t, (s, last) in self._state.items()}

    def totals(self) -> dict[str, float]:
        """Per-tenant lifetime admitted tokens (monotonic while the
        tenant stays tracked) — the delta-of-counters feed."""
        return dict(self._totals)

    def snapshot(self) -> dict:
        """JSON-shaped summary for OP_STATS embedding."""
        rates = self.rates()
        return {
            "tau_s": self.tau_s,
            "observed_tokens": self.observed_tokens,
            "tenants": {t: round(r, 6)
                        for t, r in sorted(rates.items(),
                                           key=lambda kv: -kv[1])},
            # Monotonic per-tenant counters beside the decayed gauges:
            # rate derivation that must be scrape-time independent
            # (runtime/controller.py) diffs these instead.
            "admitted": {t: self._totals[t]
                         for t in sorted(self._totals)},
        }


class AdmissionPolicy:
    """The client-side admission façade: tenant budgets + priorities +
    velocity over any :class:`~.runtime.store.BucketStore`.

    One instance binds a store, a default child (per-key) bucket
    config, and a set of :class:`TenantBudget` rows; ``acquire`` is
    then the LLM-gateway entry point::

        policy = AdmissionPolicy(store, key_config=(4096.0, 64.0))
        policy.set_tenant(TenantBudget("tenant:acme", 1e6, 5e4))
        res = await policy.acquire("tenant:acme", "user:42", cost=812,
                                   priority=PRIORITY_BATCH)

    Decisions go through the store's hierarchical lane (grant iff both
    the key's bucket and the tenant's budget admit — on remote/cluster
    stores that is the ``OP_ACQUIRE_H`` wire op, priority stamped on
    the frame). Granted costs feed the local :class:`TokenVelocity`.

    ``shed_level`` is the operator brownout knob: priorities at/above
    it are denied locally without touching the store (e.g.
    ``set_shed_level(PRIORITY_SCAVENGER)`` during an incident sheds
    scavenger fleet-wide at the edge). ``None`` (default) sheds
    nothing."""

    def __init__(self, store, *, key_config: tuple[float, float],
                 tenants: "Mapping[str, TenantBudget] | None" = None,
                 velocity_tau_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.store = store
        self.key_config = (float(key_config[0]), float(key_config[1]))
        self._tenants: dict[str, TenantBudget] = dict(tenants or {})
        self.velocity = TokenVelocity(velocity_tau_s, clock=clock)
        self.shed_level: "int | None" = None
        # Client-side estimate prior for the reservation lane
        # (runtime/reservations.py): fed by this gateway's own settled
        # actuals, consulted when reserve() is called with no estimate
        # — the server keeps its own prior too; the client one lets the
        # old-peer fallback (flat acquire at the estimate) stay sane.
        from distributedratelimiting.redis_tpu.runtime.reservations import (
            EstimatePrior,
        )

        self.prior = EstimatePrior()
        self._rid_seq = 0
        # Visible counters (stats()).
        self.decisions = 0
        self.granted = 0
        self.admitted_tokens = 0.0
        self.shed = 0
        self.reserves = 0
        self.reserved_tokens = 0.0
        self.settles = 0
        self.settled_tokens = 0.0
        # Retry-aware admission (docs/DESIGN.md §24): first-attempt vs
        # retry traffic per tenant, and the retry-shed switch — the
        # controller's storm actuator. Under retry-shed, attempts >= 1
        # are denied locally BEFORE the store (retries shed before any
        # priority class: a retry burns budget a first attempt could
        # have used for useful work).
        self.retry_shed = False
        self.first_attempts = 0
        self.retry_attempts = 0
        self.retries_shed = 0
        self._first_by_tenant: dict[str, int] = {}
        self._retry_by_tenant: dict[str, int] = {}

    # -- tenant budget management (live-mutable) -----------------------------
    def set_tenant(self, budget: TenantBudget) -> None:
        """Install/replace a tenant's budget for FUTURE local calls.
        NOTE for wire fleets: this changes only which config this
        client *sends*; balances already accumulated under the old
        config keep living in the old table until a live-config
        mutation rebases them (``mutate_config("bucket", old, new)`` —
        docs/OPERATIONS.md §11). Both together are the zero-restart
        tenant-budget change."""
        self._tenants[budget.tenant] = budget

    def tenant(self, tenant: str) -> TenantBudget:
        b = self._tenants.get(tenant)
        if b is None:
            raise KeyError(f"no budget configured for tenant {tenant!r}")
        return b

    def tenants(self) -> dict[str, TenantBudget]:
        return dict(self._tenants)

    def set_shed_level(self, level: "int | None") -> None:
        self.shed_level = level

    def set_retry_shed(self, enabled: bool) -> None:
        """Arm/disarm the retry-shed rung: while armed, calls stamped
        ``attempt >= 1`` are denied locally without touching the store
        — the controller's storm defense actuator (it fires BEFORE the
        priority ladder; docs/DESIGN.md §24)."""
        self.retry_shed = bool(enabled)

    def _note_attempt(self, tenant: str, attempt: int) -> None:
        if attempt:
            self.retry_attempts += 1
            self._retry_by_tenant[tenant] = \
                self._retry_by_tenant.get(tenant, 0) + 1
        else:
            self.first_attempts += 1
            self._first_by_tenant[tenant] = \
                self._first_by_tenant.get(tenant, 0) + 1

    # -- admission -----------------------------------------------------------
    async def acquire(self, tenant: str, key: str, cost: int = 1,
                      priority: int = PRIORITY_INTERACTIVE,
                      attempt: int = 0):
        """One weighted-cost hierarchical admission decision.
        ``attempt`` fingerprints retries (0 = first attempt): tracked
        per tenant, and denied locally while retry-shed is armed."""
        from distributedratelimiting.redis_tpu.runtime.store import (
            AcquireResult,
        )

        self.decisions += 1
        self._note_attempt(tenant, attempt)
        if self.retry_shed and attempt:
            self.retries_shed += 1
            self.shed += 1
            return AcquireResult(False, 0.0)
        if self.shed_level is not None and priority >= self.shed_level:
            self.shed += 1
            return AcquireResult(False, 0.0)
        budget = self.tenant(tenant)
        cap, rate = self.key_config
        res = await self.store.acquire_hierarchical(
            tenant, key, int(cost), budget.capacity,
            budget.fill_rate_per_sec, cap, rate, priority=priority)
        if res.granted:
            self.granted += 1
            self.admitted_tokens += cost
            self.velocity.observe(tenant, float(cost))
        return res

    def acquire_blocking(self, tenant: str, key: str, cost: int = 1,
                         priority: int = PRIORITY_INTERACTIVE,
                         attempt: int = 0):
        from distributedratelimiting.redis_tpu.runtime.store import (
            AcquireResult,
        )

        self.decisions += 1
        self._note_attempt(tenant, attempt)
        if self.retry_shed and attempt:
            self.retries_shed += 1
            self.shed += 1
            return AcquireResult(False, 0.0)
        if self.shed_level is not None and priority >= self.shed_level:
            self.shed += 1
            return AcquireResult(False, 0.0)
        budget = self.tenant(tenant)
        cap, rate = self.key_config
        res = self.store.acquire_hierarchical_blocking(
            tenant, key, int(cost), budget.capacity,
            budget.fill_rate_per_sec, cap, rate, priority=priority)
        if res.granted:
            self.granted += 1
            self.admitted_tokens += cost
            self.velocity.observe(tenant, float(cost))
        return res

    # -- streaming reservations (runtime/reservations.py) --------------------
    def next_rid(self, tenant: str) -> str:
        """A per-gateway reservation id: tenant-scoped + monotonic.
        Unique across gateways only when each gateway's ids carry a
        distinct prefix — callers with several gateways pass their own
        rids instead (the seeded soaks do, for determinism)."""
        self._rid_seq += 1
        return f"{tenant}#{id(self) & 0xFFFFFF:x}#{self._rid_seq}"

    async def reserve(self, tenant: str, key: str, *,
                      estimate: "float | None" = None,
                      priority: int = PRIORITY_INTERACTIVE,
                      rid: "str | None" = None,
                      ttl_s: "float | None" = None,
                      attempt: int = 0,
                      deadline_s: "float | None" = None):
        """Phase 1 of a streaming request: admit an ESTIMATED cost and
        hold it against the tenant → key budgets. With no ``estimate``
        the gateway's own prior supplies one (interactive → p99,
        batch/scavenger → mean — the server-side prior applies the same
        rule when the estimate is omitted on the wire). Returns the
        store's ReserveResult; pass ``result``'s rid (yours or
        :meth:`next_rid`'s) to :meth:`settle` when the stream ends."""
        from distributedratelimiting.redis_tpu.runtime.reservations import (
            ReserveResult,
        )

        self.decisions += 1
        self._note_attempt(tenant, attempt)
        if self.retry_shed and attempt:
            self.retries_shed += 1
            self.shed += 1
            return ReserveResult(False, 0.0, 0.0, 0.0)
        if self.shed_level is not None and priority >= self.shed_level:
            self.shed += 1
            return ReserveResult(False, 0.0, 0.0, 0.0)
        budget = self.tenant(tenant)
        cap, rate = self.key_config
        if estimate is None:
            estimate = self.prior.estimate(tenant, priority)
        res = await self.store.reserve(
            rid if rid is not None else self.next_rid(tenant),
            tenant, key, estimate, budget.capacity,
            budget.fill_rate_per_sec, cap, rate, priority=priority,
            ttl_s=ttl_s, attempt=attempt, deadline_s=deadline_s)
        if res.granted:
            self.granted += 1
            self.reserves += 1
            self.reserved_tokens += res.reserved
        return res

    async def settle(self, rid: str, tenant: str, actual: float, *,
                     priority: int = PRIORITY_INTERACTIVE):
        """Phase 3: reconcile the actual cost. Feeds the gateway's
        velocity (at the TRUE spend) and its estimate prior."""
        res = await self.store.settle(rid, tenant, actual)
        if res.outcome in ("settled", "fallback", "expired"):
            self.settles += 1
            self.settled_tokens += actual
            self.admitted_tokens += actual
            if actual > 0:
                self.velocity.observe(tenant, float(actual))
            self.prior.observe(tenant, priority, float(actual))
        return res

    def envelope_budget(self, tenant: str, *,
                        fraction: float = 0.5) -> float:
        """The tenant's fair-share envelope size — the epsilon term a
        degraded/drain window can add on top of the budget (the same
        ``headroom_budget`` formula every envelope uses)."""
        return headroom_budget(self.tenant(tenant).capacity,
                               fraction=fraction, min_budget=1.0)

    def stats(self) -> dict:
        return {
            "decisions": self.decisions,
            "granted": self.granted,
            "admitted_tokens": self.admitted_tokens,
            "shed": self.shed,
            "reserves": self.reserves,
            "reserved_tokens": self.reserved_tokens,
            "settles": self.settles,
            "settled_tokens": self.settled_tokens,
            "shed_level": self.shed_level,
            "retry_shed": self.retry_shed,
            "first_attempts": self.first_attempts,
            "retry_attempts": self.retry_attempts,
            "retries_shed": self.retries_shed,
            "first_attempts_by_tenant": {
                t: self._first_by_tenant[t]
                for t in sorted(self._first_by_tenant)},
            "retry_attempts_by_tenant": {
                t: self._retry_by_tenant[t]
                for t in sorted(self._retry_by_tenant)},
            "tenants": {t: list(b.config())
                        for t, b in sorted(self._tenants.items())},
            "token_velocity": self.velocity.snapshot(),
        }
