"""Host runtime: clocks, the device bucket store, micro-batching, queueing.

This is the layer the reference outsourced to Redis + StackExchange.Redis
(connection manager, §2 #6 of SURVEY.md) plus the client-side queueing
machinery (§2 #5). Here the "store" is device HBM fronted by an asyncio
micro-batcher, and the "connection" is a kernel launch.
"""
