"""Device-directory store: key→slot lives in HBM, not on the host.

:class:`FingerprintBucketStore` is a :class:`~.store.DeviceBucketStore`
whose token-bucket tier swaps the host-side key directory
(``runtime/directory.py`` + ``native/directory.cc``) for the
device-resident fingerprint table of :mod:`~..ops.fp_directory`. Per
batch, the host's only duty is one 64-bit hashing pass over the keys
(``dir_fp64_pylist``); the kernel finds-or-claims each key's slot and
decides it in the SAME launch. What this buys over the host directory:

- no host table at all for buckets — no arena RAM at 10M keys, no
  GIL-held insert pass, no host free-list bookkeeping on sweeps (TTL
  eviction clears fingerprints on device, `fp_sweep_expired`);
- growth is a device-side rehash (``fp_migrate_chunk``): the host reads
  fingerprints back and chunks, placement + state movement stay on
  device.

The trade (made explicit, not hidden): requests ship 8-byte fingerprints
instead of packed slot ids, so per-decision transfer is larger than the
packed24 host-directory path — on transfer-bound links the classic store
stays the throughput champion, while this store wins where host CPU and
memory are the scarce resource (the SURVEY.md §7 "device-side
hashing/eviction/TTL" regime). Fingerprint collisions (two keys sharing a
bucket) occur with probability ≈ n²/2⁶⁵ — about 3·10⁻⁶ at 10M keys —
versus never for the byte-comparing host directory; see
``ops/fp_directory.py`` for the full disclosure.

The keyed hot tiers — token buckets AND sliding/fixed windows (the two
10M-key table families, BASELINE configs 3-4) — both run on the
device-resident directory (:class:`_FpTable` / :class:`_FpWindowTable`).
The remaining aux tiers (decaying counters, concurrency semaphores) are
inherited with the host directory: their key cardinality is per-limiter,
not per-end-user, so a host table of a few dozen entries is the right
tool there.
"""

from __future__ import annotations

import asyncio
import ctypes
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributedratelimiting.redis_tpu.ops import fp_directory as F
from distributedratelimiting.redis_tpu.ops import kernels as K
from distributedratelimiting.redis_tpu.runtime.batcher import MicroBatcher
from distributedratelimiting.redis_tpu.runtime.store import (
    AcquireResult,
    BulkAcquireResult,
    DeviceBucketStore,
    _AcquireReq,
    _grant_zero_probes,
    _pad_size,
    _rate_per_tick,
    _shift_ts,
)
from distributedratelimiting.redis_tpu.utils.native import load_directory_lib

__all__ = ["FingerprintBucketStore", "fingerprints"]

_FNV_OFFSET = 1469598103934665603
_FNV_PRIME = 1099511628211
_PLACEMENT_VERSION = F.PLACEMENT_VERSION


def _fp64_py(key: str) -> int:
    """Pure-Python FNV-1a 64 — must stay bit-identical to the native
    ``dir_fp64_pylist`` (fingerprints live in device tables and
    checkpoints; every process must hash keys the same way)."""
    h = _FNV_OFFSET
    for byte in key.encode("utf-8", "surrogateescape"):
        h = ((h ^ byte) * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h or _FNV_OFFSET


def fingerprints(keys: Sequence[str]) -> np.ndarray:
    """Hash a key batch to ``u32[n, 2]`` (lo, hi) fingerprints — one
    native C pass when the directory library is built, the identical
    pure-Python FNV elsewhere. Never returns the all-zero EMPTY
    sentinel."""
    n = len(keys)
    out = np.empty((n, 2), np.uint32)
    lib = load_directory_lib()
    blob = getattr(keys, "blob", None)
    if lib is not None and blob is not None and n:
        # wire.KeyBlob zero-copy lane: hash straight off the frame bytes.
        lib.dir_fp64_batch(
            blob,
            keys.offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return out
    if lib is not None and getattr(lib, "has_pylist", False) and n:
        ks = keys if isinstance(keys, list) else list(keys)
        if lib.dir_fp64_pylist(
                ks, out.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint32))) == 0:
            return out
    for i, k in enumerate(keys):
        h = _fp64_py(k)
        out[i, 0] = h & 0xFFFFFFFF
        out[i, 1] = h >> 32
    return out


class _FpTable:
    """One homogeneous-config bucket table with a device-resident
    directory. External interface mirrors ``store._DeviceTable`` (the
    parent store's methods are reused wholesale); internally every launch
    carries fingerprints and the probe/insert happens in-kernel."""

    #: Scan depth cap for bulk dispatches (mirrors _PackedLaunchMixin).
    _BULK_MAX_K = 16
    #: Per-dispatch operand byte budget: the tunnel's sustained rate
    #: collapses ~5-10x when one dispatch's operands cross ~768KB-1MB
    #: (RESULTS.md "Transfer-bound analysis"); the classic store pins its
    #: compact path at 640KB with margin — same discipline here, at the
    #: fused layout's 12 B/decision.
    _BULK_BYTE_BUDGET = 640 * 1024
    #: Grow when (occupied / n_slots) crosses this after window pressure.
    _GROW_AT = 0.7

    #: Dirty accounting for incremental checkpoints (store.py
    #: ``enable_dirty_tracking``): slot placement happens in-kernel here
    #: — the host never sees which slot a row landed in — so the gauge
    #: counts dispatched rows instead, a documented UPPER bound on dirty
    #: slots (duplicates re-count). ``None`` until armed; the v4 delta
    #: itself is a structural diff over the slot arrays, exact either
    #: way (runtime/checkpoint.py).
    dirty_rows: "int | None" = None

    def __init__(self, store: "FingerprintBucketStore", capacity: float,
                 fill_rate_per_sec: float, n_slots: int) -> None:
        if n_slots < store.probe_window:
            # n - L + 1 must stay positive: the non-wrapping window
            # placement (_base_index) is undefined below one window.
            raise ValueError(
                f"n_slots ({n_slots}) must be >= probe_window "
                f"({store.probe_window})")
        self.store = store
        self.capacity = float(capacity)
        self.fill_rate_per_sec = float(fill_rate_per_sec)
        self.rate_per_tick = _rate_per_tick(fill_rate_per_sec)
        self.n_slots = n_slots
        self.fp = F.init_fp_table(n_slots)
        self.state = K.init_bucket_state(n_slots)
        self.cap_dev = jnp.float32(self.capacity)
        self.rate_dev = jnp.float32(self.rate_per_tick)
        self.probe_window = store.probe_window
        self.rounds = store.insert_rounds
        self.batcher: MicroBatcher[_AcquireReq, AcquireResult] = MicroBatcher(
            self._flush,
            max_batch=store.max_batch,
            max_delay_s=store.max_delay_s,
            max_inflight=store.max_inflight,
            flush_latency=store.metrics.flush_latency,
            queue_latency=store.metrics.queue_latency,
            flush_observer=store._flush_observer,
        )

    # -- kernel bindings (the window subclass swaps these) ------------------
    def _call_batch(self, kpair, counts, valid, now):
        """Run one fused resolve+decide batch, updating the table in
        place; returns ``(granted, remaining, resolved)`` device handles.
        Caller holds the store lock (donated buffers)."""
        self.fp, self.state, granted, remaining, resolved = (
            F.fp_acquire_batch(
                self.fp, self.state, jnp.asarray(kpair),
                jnp.asarray(counts), jnp.asarray(valid), jnp.int32(now),
                self.cap_dev, self.rate_dev,
                probe_window=self.probe_window, rounds=self.rounds))
        return granted, remaining, resolved

    def _call_scan_fused(self, fused, nows):
        """Minimum-transfer bulk dispatch (with remaining): one
        :func:`~.ops.fp_directory.pack_fp12` operand up, one
        ``f32[K, 2, B]`` result down. Caller holds the store lock."""
        self.fp, self.state, out = F.fp_acquire_scan_fused(
            self.fp, self.state, jnp.asarray(fused), jnp.asarray(nows),
            self.cap_dev, self.rate_dev,
            probe_window=self.probe_window, rounds=self.rounds)
        return out

    def _call_scan_fused_bits(self, fused, nows):
        """Verdict-only bulk dispatch: one operand up, granted+resolved
        bit-planes down (2 bits/decision). Caller holds the store lock."""
        self.fp, self.state, bits = F.fp_acquire_scan_fused_bits(
            self.fp, self.state, jnp.asarray(fused), jnp.asarray(nows),
            self.cap_dev, self.rate_dev,
            probe_window=self.probe_window, rounds=self.rounds)
        return bits

    # -- launches (donated state: dispatch under the store lock) -----------
    def _launch_batch(self, kpair: np.ndarray, counts: np.ndarray,
                      valid: np.ndarray):
        """One fused resolve+acquire dispatch; returns device handles."""
        store = self.store
        with store._lock:
            now = store.now_ticks_checked()
            out = self._call_batch(kpair, counts, valid, now)
            n_valid = int(valid.sum())
            if self.dirty_rows is not None:
                self.dirty_rows += n_valid
            store.metrics.record_launch(len(valid), n_valid)
        return out

    def _postprocess(self, granted_np, remaining_np, resolved_np,
                     counts_np, m: int):
        """Shared readback fixups: zero-permit probes always grant
        (``_grant_probes`` contract) and window-pressure rows are counted
        + relieved."""
        granted = granted_np[:m].copy()
        _grant_zero_probes(granted, counts_np[:m])
        pressure = int((~resolved_np[:m]).sum())
        if pressure:
            self.store.metrics.fp_unresolved += pressure
            self._relieve_pressure()
        return granted, remaining_np[:m], resolved_np[:m]

    async def _flush(self, reqs: Sequence[_AcquireReq]) -> list[AcquireResult]:
        n = len(reqs)
        b = _pad_size(n)
        kpair = np.zeros((b, 2), np.uint32)
        kpair[:n] = fingerprints([r.key for r in reqs])
        counts = np.zeros((b,), np.int32)
        counts[:n] = [min(r.count, 2**31 - 1) for r in reqs]
        valid = np.zeros((b,), bool)
        valid[:n] = True
        granted_d, remaining_d, resolved_d = self._launch_batch(
            kpair, counts, valid)
        loop = asyncio.get_running_loop()
        g, r, res = await loop.run_in_executor(
            None, lambda: (np.asarray(granted_d), np.asarray(remaining_d),
                           np.asarray(resolved_d)))
        g, r, _ = self._postprocess(g, r, res, counts, n)
        return [AcquireResult(bool(g[i]), float(r[i])) for i in range(n)]

    def acquire_blocking(self, key: str, count: int) -> AcquireResult:
        b = 64
        kpair = np.zeros((b, 2), np.uint32)
        kpair[0] = fingerprints([key])[0]
        counts = np.zeros((b,), np.int32)
        counts[0] = min(count, 2**31 - 1)
        valid = np.zeros((b,), bool)
        valid[0] = True
        granted_d, remaining_d, resolved_d = self._launch_batch(
            kpair, counts, valid)
        g, r, _ = self._postprocess(
            np.asarray(granted_d), np.asarray(remaining_d),
            np.asarray(resolved_d), counts, 1)
        return AcquireResult(bool(g[0]), float(r[0]))

    # -- bulk --------------------------------------------------------------
    def _bulk_dispatch(self, keys: Sequence[str], counts_np: np.ndarray,
                       with_remaining: bool = True):
        """Chunked scan dispatches over the whole key array; returns
        ``[(result handle, take), ...]`` with no readback — each dispatch
        ships ONE fused operand array and fetches ONE result array
        (bit-planes on the verdict-only path): on high-RTT tunnel days
        the transfer count dominated this path (r05 profile: ~70 ms per
        fetch, 6 fetches/call → 3 of the call's 4.5 ms/1K-keys)."""
        n = len(keys)
        fps = fingerprints(keys)  # KeyBlob-aware
        b = self.store.max_batch
        outs = []
        store = self.store
        pos = 0
        with store.profiler.span("acquire_many_fp", n), store._lock:
            now = store.now_ticks_checked()
            if self.dirty_rows is not None:
                self.dirty_rows += n
            max_k = self._BULK_MAX_K
            while max_k > 1 and max_k * b * 12 > self._BULK_BYTE_BUDGET:
                max_k //= 2
            while pos < n:
                rows = -(-(n - pos) // b)
                k = 1
                while k < rows and k < max_k:
                    k *= 2
                take = min(k * b, n - pos)
                kp = np.zeros((k * b, 2), np.uint32)
                kp[:take] = fps[pos:pos + take]
                fused = F.pack_fp12(kp, counts_np[pos:pos + take])
                nows = np.full((k,), now, np.int32)
                # Bit-planes need B % 8 == 0 (same guard as the classic
                # store's bits path, store.py); otherwise ship the f32
                # fused result and let the gather ignore its remaining row.
                call = (self._call_scan_fused_bits
                        if not with_remaining and b % 8 == 0
                        else self._call_scan_fused)
                outs.append((call(fused.reshape(k, b, 3), nows), take))
                store.metrics.record_launch(k * b, take)
                pos += take
        return outs

    def _gather_bulk(self, outs, counts_np: np.ndarray,
                     with_remaining: bool) -> BulkAcquireResult:
        n = len(counts_np)
        granted = np.empty((n,), bool)
        remaining = np.empty((n,), np.float32) if with_remaining else None
        pressure = 0
        pos = 0
        # One device_get over every dispatch's handle: lets the runtime
        # overlap the fetches instead of paying one link RTT per dispatch
        # sequentially (multi-dispatch calls on a ~70 ms-RTT day).
        arrs = jax.device_get([h for h, _ in outs])
        for arr, (_, take) in zip(arrs, outs):
            if arr.dtype == np.uint8:  # u8[K, 2, B//8] bit-planes
                granted[pos:pos + take] = np.unpackbits(
                    arr[:, 0, :].reshape(-1),
                    bitorder="little").astype(bool)[:take]
                res = np.unpackbits(
                    arr[:, 1, :].reshape(-1),
                    bitorder="little").astype(bool)[:take]
            else:                    # f32[K, 2, B]: code row + remaining
                code = arr[:, 0, :].reshape(-1)[:take].astype(np.int32)
                granted[pos:pos + take] = (code & 1).astype(bool)
                res = (code & 2) > 0
                if remaining is not None:
                    remaining[pos:pos + take] = arr[:, 1, :].reshape(
                        -1)[:take]
            pressure += int((~res).sum())
            pos += take
        _grant_zero_probes(granted, counts_np)
        if pressure:
            self.store.metrics.fp_unresolved += pressure
            self._relieve_pressure()
        return BulkAcquireResult(granted, remaining)

    def acquire_many_blocking(self, keys: Sequence[str],
                              counts: Sequence[int], *,
                              with_remaining: bool = True
                              ) -> BulkAcquireResult:
        counts_np = np.asarray(counts, np.int64)
        outs = self._bulk_dispatch(keys, counts_np,
                                   with_remaining=with_remaining)
        return self._gather_bulk(outs, counts_np, with_remaining)

    async def acquire_many(self, keys: Sequence[str],
                           counts: Sequence[int], *,
                           with_remaining: bool = True) -> BulkAcquireResult:
        counts_np = np.asarray(counts, np.int64)
        outs = self._bulk_dispatch(keys, counts_np,
                                   with_remaining=with_remaining)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: self._gather_bulk(outs, counts_np, with_remaining))

    def _debit_launch(self, keys: Sequence[str],
                      amounts: Sequence[float]):
        """One saturating-debit launch with in-kernel slot resolution
        (``fp_debit_batch``) — the lane ``DeviceBucketStore.debit_many``
        dispatches through. On this store it carries BOTH the tier-0
        reconciliation shape and the hierarchical deny-refund's
        NEGATIVE-amount credit (the PR-9 base-compose fallback: fp
        tables skip the fused hierarchical kernel, so a child deny
        refunds the parent here). Mirrors the host-directory
        ``_DeviceTable._debit_launch`` contract — returns the packed
        ``f32[2, B]`` (post-debit balance, clamped shortfall)."""
        store = self.store
        n = len(keys)
        with store.profiler.span("debit_batch", n), store._lock:
            b = _pad_size(n, floor=64)
            kpair = np.zeros((b, 2), np.uint32)
            kpair[:n] = fingerprints(list(keys))
            amts = np.zeros((b,), np.float32)
            amts[:n] = np.asarray(amounts, np.float32)
            valid = np.zeros((b,), bool)
            valid[:n] = True
            now = store.now_ticks_checked()
            self.fp, self.state, out = F.fp_debit_batch(
                self.fp, self.state, jnp.asarray(kpair),
                jnp.asarray(amts), jnp.asarray(valid), jnp.int32(now),
                self.cap_dev, self.rate_dev,
                probe_window=self.probe_window, rounds=self.rounds)
            if self.dirty_rows is not None:
                self.dirty_rows += n
            store.metrics.record_launch(b, n)
            return out

    # -- reads -------------------------------------------------------------
    def peek_blocking(self, key: str) -> float:
        b = 64
        kpair = np.zeros((b, 2), np.uint32)
        kpair[0] = fingerprints([key])[0]
        valid = np.zeros((b,), bool)
        valid[0] = True
        with self.store._lock:
            est = F.fp_peek_batch(
                self.fp, self.state, jnp.asarray(kpair), jnp.asarray(valid),
                jnp.int32(self.store.now_ticks_checked()), self.cap_dev,
                self.rate_dev, probe_window=self.probe_window)
        return float(np.asarray(est)[0])

    # -- maintenance -------------------------------------------------------
    def _occupancy(self) -> int:
        # Under the store lock: concurrent launches donate self.fp, and a
        # readback racing a donation dies with "Array has been deleted".
        with self.store._lock:
            return int(np.asarray((np.asarray(self.fp) != 0).any(-1).sum()))

    def _relieve_pressure(self) -> None:
        """Window pressure response: sweep expired slots; grow when the
        table is past the growth threshold OR the sweep freed (almost)
        nothing — with only live keys, one full probe window can fill at
        modest load factors, and without the freed-nothing clause the key
        hashing there would be denied forever while paying a full-table
        sweep per attempt. The denied requests are NOT retried here —
        deny-and-heal keeps the launch path deterministic; the caller's
        next attempt lands in the relieved table."""
        with self.store._lock:
            before = self.store.metrics.slots_evicted
            self._sweep()
            freed = self.store.metrics.slots_evicted - before
            if (freed < max(1, self.n_slots // 16)
                    or self._occupancy() >= self._GROW_AT * self.n_slots):
                self._grow()

    def _sweep(self, pinned=None) -> None:
        store = self.store
        with store.profiler.span("sweep_fp", self.n_slots), store._lock:
            now = store.now_ticks_checked()
            self.fp, self.state, n_freed = F.fp_sweep_expired(
                self.fp, self.state, jnp.int32(now), self.cap_dev,
                self.rate_dev)
            store.metrics.sweeps += 1
            store.metrics.slots_evicted += int(np.asarray(n_freed))

    # Growth hooks (the window subclass swaps these three):
    def _init_fresh(self, n: int):
        return F.init_fp_table(n), K.init_bucket_state(n)

    def _migrate_kernel(self):
        return F.fp_migrate_chunk

    def _grow(self) -> None:
        """Double the table with a device-side rehash: read old
        fingerprints back, then per chunk claim slots in the new table and
        scatter the old per-slot state across (the migrate kernel) — the
        host never computes a placement."""
        store = self.store
        with store._lock:
            self._rehash(np.asarray(self.fp),
                         [np.asarray(a) for a in self.state],
                         self.n_slots * 2)
            store.metrics.pregrows += 1

    def _rehash(self, old_fp: np.ndarray, olds: list, start_n: int,
                probe_window: int | None = None) -> None:
        """Re-place every live entry into a fresh table via the migrate
        kernel, doubling and retrying when placement gets stuck — the
        shared driver behind growth AND legacy-snapshot adoption (caller
        holds the store lock; ``olds`` are state columns in field order).
        Mutates nothing until placement succeeds, so a raise leaves the
        table exactly as it was. ``probe_window`` lets snapshot adoption
        place under the snapshot's geometry before committing it.

        An entry whose whole window fills with OTHER entries is
        unplaceable at a given size — a density accident, not a bug
        (observed at ~0.8 load). Doubling always converges (load halves
        per attempt); the attempt cap makes a pathological hash set fail
        loudly instead of allocating forever."""
        pw = self.probe_window if probe_window is None else probe_window
        entries = np.nonzero((old_fp != 0).any(-1))[0]
        migrate = self._migrate_kernel()
        b = self.store.max_batch
        new_n = start_n
        leftover = 0
        for _attempt in range(4):
            fp, state = self._init_fresh(new_n)
            pending = entries
            stuck = False
            # Entries a pass can't place (bounded insert rounds under
            # in-chunk window contention) retry in later passes; each
            # pass places ≥1 contender per contested cell, so a pass
            # with zero progress means some window is genuinely full.
            while len(pending):
                next_pending = []
                for pos in range(0, len(pending), b):
                    idx = pending[pos:pos + b]
                    m = len(idx)
                    kpair = np.zeros((b, 2), np.uint32)
                    kpair[:m] = old_fp[idx]
                    cols = []
                    for arr in olds:
                        col = np.zeros((b,), arr.dtype)
                        col[:m] = arr[idx]
                        cols.append(col)
                    valid = np.zeros((b,), bool)
                    valid[:m] = True
                    fp, state, placed = migrate(
                        fp, state, jnp.asarray(kpair),
                        *(jnp.asarray(c) for c in cols),
                        jnp.asarray(valid),
                        probe_window=pw,
                        rounds=self.rounds)
                    miss = ~np.asarray(placed)[:m]
                    if miss.any():
                        next_pending.append(idx[miss])
                if not next_pending:
                    break
                next_pending = np.concatenate(next_pending)
                if len(next_pending) >= len(pending):
                    stuck, leftover = True, len(next_pending)
                    break
                pending = next_pending
            if not stuck:
                self.fp, self.state, self.n_slots = fp, state, new_n
                self.probe_window = pw
                return
            new_n *= 2
        raise RuntimeError(
            f"fingerprint rehash cannot place {leftover} entries even "
            f"at {new_n // 2} slots")

    def rebase(self, offset: int) -> None:
        self.state = K.rebase_bucket_epoch(self.state, jnp.int32(offset))

    # -- checkpoint form ---------------------------------------------------
    def to_snap(self) -> dict:
        return {
            "fp": np.asarray(self.fp),
            "probe_window": self.probe_window,
            "placement": _PLACEMENT_VERSION,
            "tokens": np.asarray(self.state.tokens),
            "last_ts": np.asarray(self.state.last_ts),
            "exists": np.asarray(self.state.exists),
        }

    def load_snap(self, data: dict, shift: int) -> None:
        if "fp" not in data:
            raise ValueError(
                "checkpoint's bucket tables use the host key directory — "
                "restore into a DeviceBucketStore")
        # Adopt the snapshot's probe window along with its size: a key
        # placed at offset 12 of a 16-cell window is invisible to an
        # 8-cell scan — restoring into a narrower window would silently
        # orphan such entries (and later duplicate their fingerprints).
        pw = int(data.get("probe_window", self.probe_window))
        cols = [np.asarray(data["tokens"]),
                np.asarray(_shift_ts(data["last_ts"], shift)),
                np.asarray(data["exists"])]
        if data.get("placement") != _PLACEMENT_VERSION:
            # Pre-v2 snapshots placed entries with a WRAPPING h % n
            # window; installing them verbatim under today's non-wrapping
            # placement would silently orphan nearly every key. Re-place
            # everything through the migrate kernel instead — it commits
            # table AND probe_window only on success, so a failed restore
            # leaves this table fully intact.
            self._rehash(np.asarray(data["fp"]), cols, len(cols[0]),
                         probe_window=pw)
            return
        self.probe_window = pw
        self.n_slots = len(data["tokens"])
        self.fp = jnp.asarray(data["fp"])
        self.state = K.BucketState(
            tokens=jnp.asarray(cols[0]),
            last_ts=jnp.asarray(cols[1]),
            exists=jnp.asarray(cols[2]),
        )


class _FpWindowTable(_FpTable):
    """Sliding/fixed-window table with the device-resident directory —
    the window-family counterpart of :class:`_FpTable` (shares its flush,
    bulk, pressure, and lock machinery; swaps the kernel bindings, sweep
    rule, growth migrate, and checkpoint form)."""

    def __init__(self, store: "FingerprintBucketStore", limit: float,
                 window_ticks: int, n_slots: int, *,
                 fixed: bool = False) -> None:
        if n_slots < store.probe_window:
            raise ValueError(
                f"n_slots ({n_slots}) must be >= probe_window "
                f"({store.probe_window})")
        self.store = store
        self.limit = float(limit)
        self.window_ticks = int(window_ticks)
        self.fixed = fixed
        self.n_slots = n_slots
        self.fp = F.init_fp_table(n_slots)
        self.state = K.init_window_state(n_slots)
        self.limit_dev = jnp.float32(self.limit)
        self.window_dev = jnp.int32(self.window_ticks)
        self.probe_window = store.probe_window
        self.rounds = store.insert_rounds
        self.batcher: MicroBatcher[_AcquireReq, AcquireResult] = MicroBatcher(
            self._flush,
            max_batch=store.max_batch,
            max_delay_s=store.max_delay_s,
            max_inflight=store.max_inflight,
            flush_latency=store.metrics.flush_latency,
            queue_latency=store.metrics.queue_latency,
            flush_observer=store._flush_observer,
        )

    def _call_batch(self, kpair, counts, valid, now):
        self.fp, self.state, granted, remaining, resolved = (
            F.fp_window_acquire_batch(
                self.fp, self.state, jnp.asarray(kpair),
                jnp.asarray(counts), jnp.asarray(valid), jnp.int32(now),
                self.limit_dev, self.window_dev,
                probe_window=self.probe_window, rounds=self.rounds,
                interpolate=not self.fixed))
        return granted, remaining, resolved

    def _call_scan_fused(self, fused, nows):
        self.fp, self.state, out = F.fp_window_acquire_scan_fused(
            self.fp, self.state, jnp.asarray(fused), jnp.asarray(nows),
            self.limit_dev, self.window_dev,
            probe_window=self.probe_window, rounds=self.rounds,
            interpolate=not self.fixed)
        return out

    def _call_scan_fused_bits(self, fused, nows):
        self.fp, self.state, bits = F.fp_window_acquire_scan_fused_bits(
            self.fp, self.state, jnp.asarray(fused), jnp.asarray(nows),
            self.limit_dev, self.window_dev,
            probe_window=self.probe_window, rounds=self.rounds,
            interpolate=not self.fixed)
        return bits

    def peek_blocking(self, key: str) -> float:
        raise NotImplementedError(
            "window tables expose no peek (matching _DeviceWindowTable)")

    def _sweep(self, pinned=None) -> None:
        store = self.store
        with store.profiler.span("sweep_fp_windows", self.n_slots), \
                store._lock:
            now = store.now_ticks_checked()
            self.fp, self.state, n_freed = F.fp_sweep_windows(
                self.fp, self.state, jnp.int32(now), self.window_dev)
            store.metrics.sweeps += 1
            store.metrics.slots_evicted += int(np.asarray(n_freed))

    def _init_fresh(self, n: int):
        return F.init_fp_table(n), K.init_window_state(n)

    def _migrate_kernel(self):
        return F.fp_migrate_window_chunk

    def rebase(self, offset_ticks: int) -> None:
        self.state = K.rebase_window_epoch(
            self.state, jnp.int32(offset_ticks // self.window_ticks))

    def to_snap(self) -> dict:
        return {
            "fp": np.asarray(self.fp),
            "probe_window": self.probe_window,
            "placement": _PLACEMENT_VERSION,
            "prev_count": np.asarray(self.state.prev_count),
            "curr_count": np.asarray(self.state.curr_count),
            "window_idx": np.asarray(self.state.window_idx),
            "exists": np.asarray(self.state.exists),
        }

    def load_snap(self, data: dict, shift: int) -> None:
        if "fp" not in data:
            raise ValueError(
                "checkpoint's window tables use the host key directory — "
                "restore into a DeviceBucketStore")
        pw = int(data.get("probe_window", self.probe_window))
        cols = [np.asarray(data["prev_count"]),
                np.asarray(data["curr_count"]),
                np.asarray(_shift_ts(data["window_idx"],
                                     shift // self.window_ticks)),
                np.asarray(data["exists"])]
        if data.get("placement") != _PLACEMENT_VERSION:
            # Pre-v2 wrapping placement: re-place via the migrate kernel
            # (see _FpTable.load_snap — commit-on-success).
            self._rehash(np.asarray(data["fp"]), cols, len(cols[0]),
                         probe_window=pw)
            return
        self.probe_window = pw
        self.n_slots = len(data["prev_count"])
        self.fp = jnp.asarray(data["fp"])
        self.state = K.WindowState(
            prev_count=jnp.asarray(cols[0]),
            curr_count=jnp.asarray(cols[1]),
            window_idx=jnp.asarray(cols[2]),
            exists=jnp.asarray(cols[3]),
        )


class FingerprintBucketStore(DeviceBucketStore):
    """``DeviceBucketStore`` with the bucket tier's key directory moved
    into device memory (module docstring). Drop-in: same ``BucketStore``
    surface, same limiter compatibility, checkpoints interchange only
    with other fingerprint stores (the snapshot carries fingerprints, not
    key strings — keys are not recoverable from a fingerprint table)."""

    def __init__(self, *, probe_window: int = 16, insert_rounds: int = 4,
                 **kwargs) -> None:
        super().__init__(**kwargs)
        self.probe_window = probe_window
        self.insert_rounds = insert_rounds

    _TABLE_CLS = _FpTable
    _WTABLE_CLS = _FpWindowTable
