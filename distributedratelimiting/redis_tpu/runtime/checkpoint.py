"""Planned-restart checkpoints on disk (SURVEY.md §5.4).

The reference needs no checkpointing — all durable state lives in Redis
and clients are stateless. Here the store's HBM arrays ARE the store, so
planned restarts snapshot ``(keys, tokens, ts)`` to a file and restore
re-aligns every timestamp to the new process's clock epoch
(``BucketStore.snapshot``/``restore`` do the pulling and re-alignment;
this module only adds the durable file form). Crash recovery deliberately
stays init-on-miss — wiped state self-heals to "full bucket", exactly the
reference's failover posture (``RedisTokenBucketRateLimiter.cs:210-215``).

Format: one pickle (protocol 5 — numpy arrays serialize as raw buffers),
written atomically via temp-file + fsync + ``os.replace`` (plus a
directory fsync, so the rename itself is durable) — a crash mid-write
can never leave a torn file where the previous checkpoint was. Since v3
the store state is nested as its own pickle with a CRC-32 over those
bytes, so a torn or bit-flipped file is detected and raised as
:class:`SnapshotCorruptError` — a TYPED error naming the recovery path
(delete the file; the store initializes empty and self-heals, the
init-on-miss posture above) — never an opaque ``pickle`` traceback from
the middle of a server start.

**Incremental checkpoints (v4, round 7).** A full snapshot's cost
scales with table size — at production key cardinality that makes every
``OP_SAVE`` a multi-megabyte write for a handful of changed slots.
:class:`SnapshotChain` layers a *delta chain* on the v3 base: each save
diffs the live state against the previously saved state (a generic
structural diff — per-slot for device arrays, per-key for host dicts)
and writes only the changes to ``<path>.delta.<seq>``. The chain is
bounded (``max_chain``, plus a size threshold: a delta approaching the
base's size compacts into a fresh base) and every link is integrity-
chained: the base's CRC, the previous link's CRC, a contiguous ``seq``,
and its own CRC-32 — so a truncated delta, a missing base, a corrupt
middle link, or a stale regenerated link all raise the typed
:class:`SnapshotChainError`, which subclasses
:class:`SnapshotCorruptError` so EVERY existing init-on-miss fallback
(server startup, rejoin gates) handles it unchanged. Placement epochs
stamp every link; a mixed-epoch chain is a
:class:`PlacementMismatchError` before any state is restored.
"""

from __future__ import annotations

import glob
import os
import pickle
import tempfile
import zlib

import numpy as np

__all__ = ["save_snapshot", "load_snapshot", "load_snapshot_chain",
           "SnapshotChain", "SnapshotCorruptError", "SnapshotChainError",
           "PlacementMismatchError", "diff_snapshot",
           "apply_snapshot_delta"]

_MAGIC = "drl-tpu-snapshot"
# v1: initial format (2-tuple wtable keys, no semaphore sections).
# v2: wtable keys widened to 3-tuples; sema_dir/semas sections added.
# v3: store state nested as its own pickle ("snapshot_pickle") with a
#     CRC-32 checksum ("crc32") over those bytes. Since round 6 a v3
#     payload may additionally carry "placement_epoch" (the cluster
#     placement epoch the state was owned under — see runtime/
#     placement.py); absent in older files and for placement-unaware
#     servers, and ignored by older readers (optional payload key).
# Readers accept any version in _COMPAT — a v1/v2 snapshot restores into
# a v3 build (no checksum to verify; restore() treats newer sections as
# optional); an *unknown* (newer) version fails loudly here instead of as
# an opaque KeyError deep in restore() during a rollback.
_VERSION = 3
_COMPAT = frozenset({1, 2, 3})

#: Unpickling failure modes a torn/corrupt file produces. AttributeError/
#: ImportError cover a payload whose pickled class moved or never existed
#: (bit flips in the class name land here); ValueError covers truncated
#: numpy buffer reconstruction.
_UNPICKLE_ERRORS = (pickle.UnpicklingError, EOFError, AttributeError,
                    ImportError, IndexError, ValueError)


class SnapshotCorruptError(ValueError):
    """The checkpoint file is torn or corrupt (truncated write, bit
    flip, checksum mismatch). Recovery: delete the file and restart —
    the store initializes empty and self-heals to full buckets, the
    documented init-on-miss posture. Subclasses :class:`ValueError` so
    pre-typed catches keep working."""


class SnapshotChainError(SnapshotCorruptError):
    """A delta chain link is unusable: truncated, checksum-bad, pointing
    at a different base, out of sequence, or stamped with a different
    placement epoch than its base. Recovery is the base's own posture:
    delete the ``.delta.*`` files (the base alone restores the state up
    to its save point) or delete everything and fall back to
    init-on-miss. Subclasses :class:`SnapshotCorruptError` so every
    existing fallback path already does the right thing."""


class PlacementMismatchError(SnapshotCorruptError):
    """The checkpoint was written under a different cluster placement
    epoch than the caller expects: its key memberships belong to a
    retired map, and restoring it would let a rejoining node serve (and
    double-admit) keys it no longer owns. Recovery is the same
    init-on-miss fallback as a torn file — which is why this subclasses
    :class:`SnapshotCorruptError`: every existing fallback path already
    does the right thing."""


def _atomic_write(path: str, payload: dict) -> None:
    """THE checkpoint write discipline, shared by full saves and every
    delta link: temp file in the destination directory, fsync the data,
    ``os.replace`` into place, fsync the directory so the rename itself
    survives a crash — at no instant does a torn file sit where a
    checkpoint name points (the CRC exists to catch bit rot, not our
    own writes)."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".snapshot-")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(payload, f, protocol=5)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _full_payload(snap: dict, placement_epoch: "int | None") -> dict:
    snap_bytes = pickle.dumps(snap, protocol=5)
    payload = {
        "magic": _MAGIC,
        "version": _VERSION,
        "crc32": zlib.crc32(snap_bytes),
        "snapshot_pickle": snap_bytes,
    }
    if placement_epoch is not None:
        payload["placement_epoch"] = int(placement_epoch)
    return payload


def _retire_chain(path: str) -> None:
    """Unlink every ``.delta.*`` link beside ``path`` — called BEFORE a
    full save replaces the base. Ordering matters: stale links beside a
    NEW base would be refused at load (base_crc mismatch) and drag the
    valid base down with them into init-on-miss; deleting first risks
    only a crash window that restores to the OLD base's save point —
    bounded staleness, never total loss."""
    for _seq, p in _chain_paths(path):
        try:
            os.unlink(p)
        except OSError:  # pragma: no cover — racing cleanup is fine
            pass


def _snapshot_with_attachments(store) -> dict:
    """``store.snapshot()`` plus the store-attached control-plane
    state that must survive a planned restart: an ACTIVE federation
    ledger's lease state (runtime/federation.py) rides as its own
    ``"federation"`` section — TTLs exported as remaining ages, so a
    restore can only shorten a lease's term (conservative, never
    extended). Non-home stores (no ledger, or a never-used one) keep
    their snapshot shape byte for byte, and the v4 structural diff
    handles the extra dict section generically."""
    snap = store.snapshot()
    fed = getattr(store, "_federation", None)
    if fed is not None and fed.active:
        snap = dict(snap)
        snap["federation"] = fed.export_state()
    return snap


def _restore_with_attachments(store, snap: dict) -> None:
    """The restore half: route a ``"federation"`` section back into
    the store-attached ledger (created on demand) BEFORE the store
    body restores — the store's own ``restore`` never sees the
    attachment key."""
    fed_state = None
    if isinstance(snap, dict) and "federation" in snap:
        snap = dict(snap)
        fed_state = snap.pop("federation")
    store.restore(snap)
    if fed_state is not None:
        store.federation_ledger().restore_state(fed_state)


def save_snapshot(store, path: str,
                  placement_epoch: "int | None" = None) -> None:
    """Pull ``store``'s live state to host and write it to ``path``
    atomically, retiring any incremental delta chain beside it (a full
    save supersedes the chain — leaving the links would poison the NEW
    base at the next chain-aware load). ``placement_epoch`` stamps the
    cluster placement epoch the state was owned under (placement-aware
    servers pass it on OP_SAVE) so a later restore can be held to the
    current map. Store-attached federation lease state rides along
    (:func:`_snapshot_with_attachments`)."""
    payload = _full_payload(_snapshot_with_attachments(store),
                            placement_epoch)
    _retire_chain(path)
    _atomic_write(path, payload)


def load_snapshot(store, path: str,
                  expected_placement_epoch: "int | None" = None) -> None:
    """Restore ``store`` from a checkpoint file written by
    :func:`save_snapshot`. Timestamps re-align to this process's clock
    epoch inside ``store.restore``. Only load files you wrote — the format
    is pickle (trusted-operator checkpoint, not an interchange format).

    ``expected_placement_epoch`` holds the file to a cluster placement
    epoch: a mismatch (including a file with no recorded epoch) raises
    :class:`PlacementMismatchError` BEFORE any state is unpickled into
    the store — the rejoining-node init-on-miss gate. ``None`` skips the
    check (single-node and placement-unaware deployments).

    Raises :class:`SnapshotCorruptError` for a torn or bit-flipped file
    (including a v3 checksum mismatch) and plain :class:`ValueError` for
    a file that is simply not a snapshot or speaks an unknown newer
    version."""
    snap, _crc = _read_full(path, expected_placement_epoch)
    _restore_with_attachments(store, snap)


def _read_full(path: str,
               expected_placement_epoch: "int | None" = None
               ) -> "tuple[dict, int]":
    """Read + validate a full checkpoint; returns ``(snapshot,
    crc32-of-snapshot-bytes)`` (the crc is the delta chain's base
    identity). All the typed-error contracts of :func:`load_snapshot`
    live here."""
    with open(path, "rb") as f:
        try:
            payload = pickle.load(f)
        except _UNPICKLE_ERRORS as exc:
            raise SnapshotCorruptError(
                f"{path} is torn or corrupt ({exc!r}); delete it to fall "
                "back to init-on-miss (state self-heals to full buckets)"
            ) from exc
    if not isinstance(payload, dict) or payload.get("magic") != _MAGIC:
        raise ValueError(f"{path} is not a rate-limiter snapshot")
    if payload.get("version") not in _COMPAT:
        raise ValueError(
            f"snapshot version {payload.get('version')} not supported "
            f"(this build reads {sorted(_COMPAT)})"
        )
    if expected_placement_epoch is not None:
        recorded = payload.get("placement_epoch")
        if recorded != expected_placement_epoch:
            raise PlacementMismatchError(
                f"{path} was written under placement epoch {recorded} "
                f"but the cluster is at epoch {expected_placement_epoch}"
                "; its key memberships are stale — delete it to fall "
                "back to init-on-miss (migration re-ships any state "
                "this node should own)")
    if "snapshot_pickle" in payload:  # v3: verify before unpickling
        blob = payload["snapshot_pickle"]
        crc = zlib.crc32(blob)
        if crc != payload.get("crc32"):
            raise SnapshotCorruptError(
                f"{path} failed its checksum (crc32 {crc:#010x} != "
                f"recorded {payload.get('crc32', 0):#010x}); delete it "
                "to fall back to init-on-miss")
        try:
            snap = pickle.loads(blob)
        except _UNPICKLE_ERRORS as exc:  # pragma: no cover — crc catches
            raise SnapshotCorruptError(                 # almost all of these
                f"{path} snapshot body is corrupt ({exc!r})") from exc
        return snap, crc
    # v1/v2: the state rides in the outer pickle, no checksum
    if "snapshot" not in payload:
        raise SnapshotCorruptError(
            f"{path} carries neither a v3 snapshot body nor a "
            "v1/v2 'snapshot' section")
    return payload["snapshot"], 0


# -- v4 incremental deltas ---------------------------------------------------
#
# A delta node is a tagged dict describing how to turn the PREVIOUSLY
# SAVED value into the current one:
#   {"t": "full", "v": value}              replace outright
#   {"t": "dict", "set": {k: node}, "del": [k, …]}   patch a mapping
#   {"t": "arr", "n": N, "idx": i64[], "val": values[]}  scatter into a
#       1-D array (rows beyond the previous length default to the
#       dtype's zero — device tables grow by doubling with zeroed
#       columns, and every genuinely-live new row is in idx anyway)
# The diff is generic over the snapshot schema — host-dict stores delta
# per key, device/fingerprint stores per slot — so every BucketStore
# (and any future one) gets incremental checkpoints with no per-store
# format code.

def _diff_node(base, curr):
    """Delta node turning ``base`` into ``curr``, or ``None`` when they
    are equal (the subtree is omitted from the delta entirely)."""
    if isinstance(base, dict) and isinstance(curr, dict):
        set_: dict = {}
        deleted = [k for k in base if k not in curr]
        for k, cv in curr.items():
            if k in base:
                sub = _diff_node(base[k], cv)
                if sub is not None:
                    set_[k] = sub
            else:
                set_[k] = {"t": "full", "v": cv}
        if not set_ and not deleted:
            return None
        return {"t": "dict", "set": set_, "del": deleted}
    if isinstance(base, np.ndarray) and isinstance(curr, np.ndarray):
        if (base.dtype == curr.dtype and base.ndim == curr.ndim == 1
                and len(curr) >= len(base)):
            m = len(base)
            changed = np.ones(len(curr), bool)
            if m:
                changed[:m] = curr[:m] != base
            idx = np.nonzero(changed)[0]
            if len(idx) == 0:
                return None
            # A near-total rewrite serializes smaller as the raw array
            # (no index vector); the chain's size threshold still sees
            # the true cost either way.
            if len(idx) * 2 >= len(curr):
                return {"t": "full", "v": curr}
            return {"t": "arr", "n": len(curr),
                    "idx": idx.astype(np.int64), "val": curr[idx]}
        if np.array_equal(base, curr):
            return None
        return {"t": "full", "v": curr}
    try:
        same = bool(base == curr)
    # Equality here is an OPTIMIZATION probe, not a failure path: a leaf
    # type that won't compare (or compares ambiguously, e.g. an array
    # that slipped past the ndarray branch) is simply carried whole.
    # drl-check: ok(swallowed-exception)
    except Exception:
        same = False
    return None if same else {"t": "full", "v": curr}


def _apply_node(base, node):
    t = node["t"]
    if t == "full":
        return node["v"]
    if t == "dict":
        if not isinstance(base, dict):
            raise SnapshotChainError(
                "delta patches a mapping the base does not carry — the "
                "chain does not belong to this base")
        out = dict(base)
        for k in node["del"]:
            out.pop(k, None)
        for k, sub in node["set"].items():
            out[k] = _apply_node(out.get(k), sub)
        return out
    if t == "arr":
        if not isinstance(base, np.ndarray):
            raise SnapshotChainError(
                "delta scatters into an array the base does not carry "
                "— the chain does not belong to this base")
        n = int(node["n"])
        val = np.asarray(node["val"])
        idx = np.asarray(node["idx"], np.int64)
        if len(idx) != len(val) or (len(idx)
                                    and int(idx.max(initial=0)) >= n):
            raise SnapshotChainError("delta scatter indices are corrupt")
        out = np.zeros(n, val.dtype)
        m = min(len(base), n)
        out[:m] = base[:m]
        out[idx] = val
        return out
    raise SnapshotChainError(f"unknown delta node tag {t!r}")


def diff_snapshot(base: dict, curr: dict) -> dict:
    """Structural diff of two store snapshots (see the node grammar
    above). ``{}`` when nothing changed."""
    return _diff_node(base, curr) or {}


def apply_snapshot_delta(base: dict, delta: dict) -> dict:
    """Replay one delta onto a reconstructed snapshot state."""
    if not delta:
        return base
    out = _apply_node(base, delta)
    if not isinstance(out, dict):
        raise SnapshotChainError("delta did not produce a snapshot dict")
    return out


_DELTA_VERSION = 4


def _delta_path(path: str, seq: int) -> str:
    return f"{path}.delta.{seq}"


def _chain_paths(path: str) -> "list[tuple[int, str]]":
    out = []
    for p in glob.glob(glob.escape(path) + ".delta.*"):
        tail = p.rsplit(".", 1)[-1]
        if tail.isdigit():
            out.append((int(tail), p))
    return sorted(out)


def _read_delta(path: str) -> dict:
    """Read + validate one delta link's envelope (not its chain
    position — :func:`load_snapshot_chain` owns that)."""
    try:
        with open(path, "rb") as f:
            payload = pickle.load(f)
    except OSError as exc:
        raise SnapshotChainError(
            f"{path} is unreadable ({exc!r})") from exc
    except _UNPICKLE_ERRORS as exc:
        raise SnapshotChainError(
            f"{path} is torn or corrupt ({exc!r}); delete the .delta.* "
            "files to restore from the base alone") from exc
    if not isinstance(payload, dict) or payload.get("magic") != _MAGIC \
            or payload.get("version") != _DELTA_VERSION:
        raise SnapshotChainError(
            f"{path} is not a v{_DELTA_VERSION} snapshot delta")
    blob = payload.get("delta_pickle", b"")
    crc = zlib.crc32(blob)
    if crc != payload.get("crc32"):
        raise SnapshotChainError(
            f"{path} failed its checksum (crc32 {crc:#010x} != recorded "
            f"{payload.get('crc32', 0):#010x}); delete the .delta.* "
            "files to restore from the base alone")
    try:
        payload["delta"] = pickle.loads(blob)
    except _UNPICKLE_ERRORS as exc:  # pragma: no cover — crc catches
        raise SnapshotChainError(
            f"{path} delta body is corrupt ({exc!r})") from exc
    return payload


def load_snapshot_chain(store, path: str,
                        expected_placement_epoch: "int | None" = None
                        ) -> int:
    """Restore ``store`` from a base checkpoint plus its delta chain
    (``<path>.delta.1 … .delta.K``). With no delta files this is exactly
    :func:`load_snapshot`. Returns the number of deltas applied.

    Chain validation happens BEFORE any state reaches the store: links
    must be contiguous from 1, each must name the base's CRC and the
    previous link's CRC, and each must carry the same placement epoch
    as the caller expects of the base. Any violation raises the typed
    :class:`SnapshotChainError` (a :class:`SnapshotCorruptError`), so
    every existing init-on-miss fallback handles a broken chain the
    way it handles a torn base."""
    links = _chain_paths(path)
    try:
        snap, base_crc = _read_full(path, expected_placement_epoch)
    except OSError as exc:
        if links:
            # Deltas with no base are unusable by construction — the
            # typed error (not a bare FileNotFoundError) so the caller's
            # init-on-miss fallback handles a half-deleted chain the way
            # it handles a torn file.
            raise SnapshotChainError(
                f"{path} is missing but {len(links)} .delta.* file(s) "
                f"remain ({exc!r}); delete them to fall back to "
                "init-on-miss") from exc
        raise
    payloads = []
    prev_crc = base_crc
    for i, (seq, p) in enumerate(links, start=1):
        if seq != i:
            raise SnapshotChainError(
                f"delta chain for {path} is missing link {i} (found "
                f"seq {seq}); delete the .delta.* files to restore "
                "from the base alone")
        payload = _read_delta(p)
        if payload.get("base_crc") != base_crc:
            raise SnapshotChainError(
                f"{p} belongs to a different base (base_crc "
                f"{payload.get('base_crc', 0):#010x} != "
                f"{base_crc:#010x}); stale leftovers from an older "
                "chain — delete the .delta.* files")
        if payload.get("prev_crc") != prev_crc:
            raise SnapshotChainError(
                f"{p} does not chain to its predecessor (prev_crc "
                "mismatch); a middle link was replaced or lost — "
                "delete the .delta.* files")
        if expected_placement_epoch is not None and \
                payload.get("placement_epoch") != expected_placement_epoch:
            raise PlacementMismatchError(
                f"{p} was written under placement epoch "
                f"{payload.get('placement_epoch')} but the cluster is "
                f"at epoch {expected_placement_epoch}; delete it to "
                "fall back to init-on-miss")
        prev_crc = payload["crc32"]
        payloads.append(payload)
    for payload in payloads:
        snap = apply_snapshot_delta(snap, payload["delta"])
    _restore_with_attachments(store, snap)
    return len(payloads)


class SnapshotChain:
    """Incremental-checkpoint writer: owns one base + bounded delta
    chain at ``path`` (the server holds one per snapshot path). Each
    :meth:`save` diffs the live state against the PREVIOUS save and
    writes only the changes; the chain compacts into a fresh base when
    it grows past ``max_chain`` links, when a delta's size approaches
    ``compact_ratio`` of the base's, or when the placement epoch moved
    (a chain must be single-epoch — the load gate refuses mixtures).
    Every file goes through the same atomic temp+fsync+replace
    discipline as a full save."""

    def __init__(self, path: str, *, max_chain: int = 8,
                 compact_ratio: float = 0.5) -> None:
        self.path = path
        self.max_chain = max(1, int(max_chain))
        self.compact_ratio = float(compact_ratio)
        self._prev_snap: "dict | None" = None
        self._base_crc = 0
        self._base_bytes = 0
        self._prev_crc = 0
        self._seq = 0
        self._epoch: "int | None" = None
        self.full_saves = 0
        self.delta_saves = 0
        self.last_delta_bytes = 0

    def save(self, store, placement_epoch: "int | None" = None) -> str:
        """One checkpoint: a delta when a base is held and the chain has
        room, else a compacting full save. Returns the file written.
        Store-attached federation lease state rides every link
        (:func:`_snapshot_with_attachments` — the structural diff
        treats the section like any other dict)."""
        snap = _snapshot_with_attachments(store)
        mark = getattr(store, "mark_snapshot_base", None)
        if callable(mark):
            mark()  # reset the store's dirty accounting window
        if (self._prev_snap is None or self._seq >= self.max_chain
                or self._epoch != placement_epoch):
            return self._save_full(snap, placement_epoch)
        delta = diff_snapshot(self._prev_snap, snap)
        blob = pickle.dumps(delta, protocol=5)
        if len(blob) >= self.compact_ratio * max(1, self._base_bytes):
            return self._save_full(snap, placement_epoch)
        payload = {
            "magic": _MAGIC,
            "version": _DELTA_VERSION,
            "base_crc": self._base_crc,
            "prev_crc": self._prev_crc,
            "seq": self._seq + 1,
            "crc32": zlib.crc32(blob),
            "delta_pickle": blob,
        }
        if placement_epoch is not None:
            payload["placement_epoch"] = int(placement_epoch)
        path = _delta_path(self.path, self._seq + 1)
        _atomic_write(path, payload)
        self._seq += 1
        self._prev_crc = payload["crc32"]
        self._prev_snap = snap
        self.delta_saves += 1
        self.last_delta_bytes = len(blob)
        return path

    def _save_full(self, snap: dict, placement_epoch: "int | None") -> str:
        payload = _full_payload(snap, placement_epoch)
        # Links first, base second (see _retire_chain): a crash between
        # the two restores the OLD base's save point; the other order
        # leaves a new base with foreign links — refused wholesale at
        # load, i.e. total state loss from our own leftovers.
        _retire_chain(self.path)
        _atomic_write(self.path, payload)
        self._prev_snap = snap
        self._base_crc = payload["crc32"]
        self._base_bytes = len(payload["snapshot_pickle"])
        self._prev_crc = payload["crc32"]
        self._seq = 0
        self._epoch = placement_epoch
        self.full_saves += 1
        self.last_delta_bytes = 0
        return self.path

    def stats(self) -> dict:
        return {"chain_len": self._seq,
                "full_saves": self.full_saves,
                "delta_saves": self.delta_saves,
                "last_delta_bytes": self.last_delta_bytes,
                "base_bytes": self._base_bytes}
