"""Planned-restart checkpoints on disk (SURVEY.md §5.4).

The reference needs no checkpointing — all durable state lives in Redis
and clients are stateless. Here the store's HBM arrays ARE the store, so
planned restarts snapshot ``(keys, tokens, ts)`` to a file and restore
re-aligns every timestamp to the new process's clock epoch
(``BucketStore.snapshot``/``restore`` do the pulling and re-alignment;
this module only adds the durable file form). Crash recovery deliberately
stays init-on-miss — wiped state self-heals to "full bucket", exactly the
reference's failover posture (``RedisTokenBucketRateLimiter.cs:210-215``).

Format: one pickle (protocol 5 — numpy arrays serialize as raw buffers),
written atomically via temp-file + rename so a crash mid-write leaves the
previous checkpoint intact.
"""

from __future__ import annotations

import os
import pickle
import tempfile

__all__ = ["save_snapshot", "load_snapshot"]

_MAGIC = "drl-tpu-snapshot"
# v1: initial format (2-tuple wtable keys, no semaphore sections).
# v2: wtable keys widened to 3-tuples; sema_dir/semas sections added.
# Readers accept any version in _COMPAT — a v1 snapshot restores into a
# v2 build (restore() treats the new sections as optional); an *unknown*
# (newer) version fails loudly here instead of as an opaque KeyError deep
# in restore() during a rollback.
_VERSION = 2
_COMPAT = frozenset({1, 2})


def save_snapshot(store, path: str) -> None:
    """Pull ``store``'s live state to host and write it to ``path``
    atomically."""
    payload = {
        "magic": _MAGIC,
        "version": _VERSION,
        "snapshot": store.snapshot(),
    }
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".snapshot-")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(payload, f, protocol=5)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_snapshot(store, path: str) -> None:
    """Restore ``store`` from a checkpoint file written by
    :func:`save_snapshot`. Timestamps re-align to this process's clock
    epoch inside ``store.restore``. Only load files you wrote — the format
    is pickle (trusted-operator checkpoint, not an interchange format)."""
    with open(path, "rb") as f:
        payload = pickle.load(f)
    if payload.get("magic") != _MAGIC:
        raise ValueError(f"{path} is not a rate-limiter snapshot")
    if payload.get("version") not in _COMPAT:
        raise ValueError(
            f"snapshot version {payload.get('version')} not supported "
            f"(this build reads {sorted(_COMPAT)})"
        )
    store.restore(payload["snapshot"])
